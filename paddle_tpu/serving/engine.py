"""Paged decode engine — prefill/decode split over the block-paged cache.

One engine serves many in-flight sequences through exactly TWO compiled
program families, both bounded by the shape ladder:

* **prefill** — the bucketed encoder forward (the same
  ``CompileShapeCache`` contract training feeds ride: source tokens pad to
  a ``DEFAULT_LADDER`` rung, admitted-group batch rows pad to a
  ``DEFAULT_BATCH_LADDER`` rung) fused with the page scatter: encoder
  memory splits into fixed-size blocks written at the allocator's page
  ids, and the decoder boot state lands in the slot plane.  One compiled
  variant per (batch-rung, source-rung) pair.
* **decode** — ONE fused attention-GRU step (ops/rnn.attention_gru_step —
  the PR-2 scan core's generation face) for EVERY live sequence at once,
  rewired to gather the encoder memory through the page table:
  ``pool[page_table]`` reshapes to the padded attention extent, ragged
  true lengths ride as a mask.  One compiled variant per (slot-rung,
  page-rung) pair; admission and retirement change page-table CONTENTS
  and the live mask, never shapes — continuous batching without a single
  recompile.

Decode outputs are BIT-IDENTICAL per request to the one-shot
``Seq2SeqGenerator.generate_greedy`` path (pinned in tests/test_serving.py):
the gathered pages hold exactly the bytes prefill wrote, masked padding
contributes exact zeros, and every per-row op is batch-row independent.

With ``aot_cache_dir`` set (PR 8), both program families dispatch through
the persistent serialized-executable cache, so a serving process boots
warm: deserialize, don't retrace.

**Chunked prefill** (``serving_prefill_chunk_tokens``): a prompt whose
padded source extent exceeds the chunk bound no longer prefills as one
monolithic encoder dispatch that stalls every decoding sequence for its
whole duration.  Instead the bi-GRU encoder runs in ladder-rung chunks
with carried recurrent state — a forward pass of chunk scans left to
right, a backward pass right to left, each chunk one bounded dispatch,
page-scattered as the backward pass completes each span — and
:meth:`ServingEngine.step` advances ONE chunk per call before decoding,
so decode stalls are bounded by a chunk, not a prompt.  Bit-identity
holds because a ``lax.scan`` split at chunk boundaries with carried state
executes the identical per-step op sequence as the unsplit scan (pinned
in tests/test_serving.py against the one-shot path).  The chunk programs
are four fixed-shape jits (fw scan, bw scan, scatter+project, boot
write) counted under ``trace_counts['prefill_chunk']``.

**Decode raw speed (PR 17)** adds three faces over the same two pools:

* *COW prefix sharing* (``serving_prefix_cache``): finished prompts park
  their pages + captured boot state in a cache keyed on signature-seeded
  token-block hash chains; an exact-prompt repeat maps the SAME blocks
  into its page table (refcount +1) and decodes immediately — zero
  prefill dispatches, bit-identical by construction.  Exact-prompt-only
  because the bi-GRU's backward direction makes every encoded position
  suffix-dependent; partial overlap instead resumes the chunked
  prefill's FORWARD pass from cached carries (prefix-determined, so
  bit-exact).  Writes go through the :meth:`ServingEngine.ensure_private_pages`
  COW barrier; blocks free only at refcount 0; eviction is LRU over
  refcount-0 blocks under the same ``serving_hbm_budget_mb``.
* *Speculative decoding* (``serving_spec_decode``): an n-gram
  prompt-lookup draft proposes K tokens and ONE dispatch (the decode
  program family's shape, draft-teacher-forced so the embedding GEMM
  hoists out of the scan) verifies them against the target's own argmax
  chain — the emitted tokens ARE the greedy chain's, acceptance only
  changes how many land per dispatch.
* *Paged beam serving*: a request with ``beam_size`` runs
  ops/beam.beam_search over the page-table-gathered memory with the SAME
  fused step closure the one-shot path uses
  (models/seq2seq.make_fused_step) — beam decode as a serving citizen.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.batch import (
    DEFAULT_BATCH_LADDER,
    DEFAULT_LADDER,
    batch_shape_key,
    ladder_len,
    pad_batch_rows,
)
from paddle_tpu import obs as _obs
from paddle_tpu.core.compiler import CompileShapeCache
from paddle_tpu.ops import acc_matmul
from paddle_tpu.ops.beam import greedy_token_chain
from paddle_tpu.ops.rnn import attention_gru_step
from paddle_tpu.serving.pages import BlockPagedCache

__all__ = ["ServingEngine"]


class _Slot:
    """One in-flight sequence: its page-table row + host-side decode state.

    ``beam`` > 0 routes the slot through the paged whole-sequence beam
    program instead of the continuous greedy/speculative loop; ``boot_h``
    holds the host copy of the decoder boot state captured after prefill
    (None unless the prefix cache is on and the slot prefilled cleanly) —
    it becomes the cache entry's resume state when the slot retires."""

    __slots__ = (
        "request", "pages", "enc_tokens", "last_id", "tokens", "max_new",
        "admit_seq", "beam", "boot_h",
    )

    def __init__(self, request, pages, enc_tokens, last_id, tokens, max_new,
                 admit_seq, beam=0, boot_h=None):
        self.request = request
        self.pages = pages
        self.enc_tokens = enc_tokens
        self.last_id = last_id
        self.tokens = tokens
        self.max_new = max_new
        self.admit_seq = admit_seq
        self.beam = beam
        self.boot_h = boot_h


class _PendingPrefill:
    """One long prompt mid-chunked-prefill: its slot/pages are held, the
    carried bi-GRU state and per-chunk forward activations live here until
    the backward pass finishes scattering every span, then the slot goes
    live for decode."""

    __slots__ = (
        "request", "pages", "enc_tokens", "max_new", "admit_seq", "ids",
        "length", "rows", "n_chunks", "phase", "cursor", "h", "fw_chunks",
        "resume",
    )

    def __init__(self, request, pages, enc_tokens, max_new, admit_seq,
                 ids, length, rows, n_chunks, h0, resume):
        self.request = request
        self.pages = pages
        self.enc_tokens = enc_tokens
        self.max_new = max_new
        self.admit_seq = admit_seq
        self.ids = ids          # [1, S_pad] int32, host
        self.length = length    # [1] int32, host
        self.rows = rows        # [S_pad // block_tokens] page ids, host
        self.n_chunks = n_chunks
        self.phase = "fw"       # "fw" then "bw"
        self.cursor = 0         # next chunk index (fw ascends, bw descends)
        self.h = h0             # carried GRU state [1, H], device
        self.fw_chunks = [None] * n_chunks  # [1, C, H] forward activations
        self.resume = resume    # preemption save-state or None


class ServingEngine:
    """Continuous-batching decode over a trained :class:`Seq2SeqGenerator`.

    The engine is single-threaded by contract — exactly one thread (the
    scheduler's step thread, or a test driving ``admit``/``step``
    directly) owns it.  Cross-thread coordination lives in
    :class:`~paddle_tpu.serving.scheduler.ServingScheduler`.

    Requires the decoder to match the fused attention-GRU idiom (the same
    structural matcher the training scan and beam stepping use); a
    non-matching topology raises — the serving plane has no interpreted
    fallback, by design.
    """

    def __init__(
        self,
        generator,
        *,
        max_slots: Optional[int] = None,
        block_tokens: Optional[int] = None,
        hbm_budget_mb: Optional[int] = None,
        max_new_tokens: Optional[int] = None,
        block_steps: Optional[int] = None,
        prefill_chunk_tokens: Optional[int] = None,
        aot_cache_dir: Optional[str] = None,
        int8_weights: Optional[bool] = None,
        prefix_cache: Optional[bool] = None,
        spec_decode: Optional[bool] = None,
        spec_ngram: Optional[int] = None,
        clock=time.perf_counter,
        stats=None,
    ):
        from paddle_tpu.utils import flags as _flags
        from paddle_tpu.utils.timers import global_stats

        if generator._match is None or not _flags.get_flag("fused_attention_gru"):
            raise ValueError(
                "serving requires the fused attention-GRU decoder step "
                "(the topology did not match, or fused_attention_gru is off)"
            )
        self._gen = generator
        self._clock = clock
        self._stats = stats if stats is not None else global_stats
        self.max_slots = (
            max_slots if max_slots is not None
            else _flags.get_flag("serving_max_slots")
        )
        blk = (
            block_tokens if block_tokens is not None
            else _flags.get_flag("serving_block_tokens")
        )
        if DEFAULT_LADDER[0] % blk != 0:
            raise ValueError(
                f"serving_block_tokens={blk} must divide the base ladder "
                f"rung {DEFAULT_LADDER[0]} so every padded source extent "
                "splits into whole blocks"
            )
        budget_mb = (
            hbm_budget_mb if hbm_budget_mb is not None
            else _flags.get_flag("serving_hbm_budget_mb")
        )
        self.default_max_new_tokens = (
            max_new_tokens if max_new_tokens is not None
            else _flags.get_flag("serving_max_new_tokens")
        )
        # K tokens per dispatch: the make_multi_train_step amortization
        # applied to decode (each dispatch's host sync covers K tokens for
        # every live slot; finished rows clamp to EOS in-graph)
        self.block_steps = max(1, int(
            block_steps if block_steps is not None
            else _flags.get_flag("serving_decode_block_steps")
        ))

        # weight bundle (PR-2 fused extraction, shared with beam stepping)
        gp = generator.net.materialize_shared(generator.params.params)
        self._gp = gp
        self._state = generator.params.state
        self._w = generator.fused_decode_weights(gp)
        # weight-only int8 (the serving_int8_weights flag): the RESIDENT
        # decode bundle holds int8 blocks + f32 scales and every dispatch
        # dequantizes in-graph, so HBM carries ~1/4 the weight bytes while
        # biases/vectors (and the host-side sp_b uses) stay full-precision
        # f32 in self._w.  Bit-drift vs the f32 bundle is bounded by the
        # serving_int8_drift_budget flag (tests/bench assert it).
        from paddle_tpu.ops import quantize as _bsq

        if int8_weights is None:
            int8_weights = bool(_flags.get_flag("serving_int8_weights"))
        self.int8_weights = bool(int8_weights)
        self._w_meta: Dict[str, Any] = {}
        if self.int8_weights:
            self._w_arg, self._w_meta = _bsq.quantize_weight_bundle(self._w)
        else:
            self._w_arg = self._w
        self.weight_bytes = _bsq.weight_bundle_bytes(self._w_arg)
        mt = generator._match
        self._acts = {
            "gate_act": mt.gate_act, "act": mt.act, "att_act": mt.att_act,
        }
        self.hidden_dim = int(self._w["w_c"].shape[0])
        self.trg_vocab = int(self._w["head_w"].shape[1])
        d_enc = int(self._w["w_ctx"].shape[0])
        d_ep = int(self._w["v"].shape[0])
        self._dtype = self._w["w_ctx"].dtype
        # which encoder-subgraph outputs feed the two static placeholders
        pmap = dict(zip(
            [p for p, _ in generator._static_info], ["enc", "enc_proj"]
        ))
        self._enc_layer = pmap[mt.enc_name]
        self._ep_layer = pmap[mt.ep_name]

        # feeder over the pruned encoder graph's single source slot, on the
        # canonical ladder (the prefill half of the shape contract)
        from paddle_tpu.reader.feeder import DataFeeder

        dts = generator._enc_net.topology.data_types()
        seq_slots = [n for n, it in dts if it.seq.name != "NONE"]
        if len(seq_slots) != 1:
            raise ValueError(
                f"serving expects one source sequence slot, got {seq_slots}"
            )
        self.src_slot = seq_slots[0]
        self.src_vocab = int(dict(dts)[self.src_slot].dim)
        self._feeder = DataFeeder(dts, ladder=DEFAULT_LADDER, min_seq_len=1)

        # block-paged cache + device pools (+1 scratch row each; the slot
        # plane gets a scratch row too, absorbing padded-lane writes)
        self._pages = BlockPagedCache(
            blk,
            {"enc": d_enc, "ep": d_ep},
            hbm_budget_bytes=int(float(budget_mb) * (1 << 20)),
            dtype_bytes=jnp.dtype(self._dtype).itemsize,
            stats=self._stats,
        )
        self.block_tokens = blk
        self._enc_pool = jnp.zeros(
            (self._pages.pool_rows, blk, d_enc), self._dtype
        )
        self._ep_pool = jnp.zeros(
            (self._pages.pool_rows, blk, d_ep), self._dtype
        )
        self._h = jnp.zeros((self.max_slots + 1, self.hidden_dim), self._dtype)
        self._scratch_slot = self.max_slots
        # page-count rungs mirror the time ladder: P * block_tokens is
        # always a DEFAULT_LADDER extent, so the gathered attention extent
        # matches what the one-shot path pads to (bit-identity)
        self._page_ladder = tuple(sorted({
            max(1, r // blk) for r in DEFAULT_LADDER
        }))

        self._slots: Dict[int, _Slot] = {}
        self._prefilling: Dict[int, _PendingPrefill] = {}
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._admit_seq = 0

        # -- copy-on-write prefix cache (serving_prefix_cache) ------------
        # Full-prompt entries only: the bi-GRU encoder's BACKWARD direction
        # makes every encoded position depend on the prompt SUFFIX, so a
        # cached block is bit-identical for a new request only when the
        # ENTIRE prompt matches — partial-prefix overlap reuses the cached
        # forward-GRU carries on the chunked path (below) instead.  The
        # key chains per-block token-tuple hashes seeded by the engine
        # signature (topology fingerprint + feed dtype + source slot/vocab
        # + special ids + weight precision): two engines that tokenize or
        # compute differently can NEVER alias an entry, and the stored
        # exact token tuple makes hash collisions a miss, not a wrong hit.
        self.prefix_cache_enabled = bool(
            prefix_cache if prefix_cache is not None
            else _flags.get_flag("serving_prefix_cache")
        )
        from paddle_tpu.core import aot_cache as _aotmod

        self._cache_sig = (
            _aotmod.topology_fingerprint(self._gen.net),
            str(jnp.dtype(self._dtype)),
            self.src_slot,
            self.src_vocab,
            int(self._gen.bos_id),
            int(self._gen.eos_id),
            self.int8_weights,
        )
        self._cache_sig_hash = hash(self._cache_sig)
        # key -> {tokens, pages, boot_h, enc_tokens}; block id -> owning key
        self._prefix_cache: Dict[tuple, Dict[str, Any]] = {}
        self._prefix_owner: Dict[int, tuple] = {}
        self._pages.on_evict = self._on_block_evicted
        self.prefix_hits = 0
        self.prefix_misses = 0
        # forward-GRU carry cache for chunked prefills: prompt-prefix (at
        # chunk boundaries, fully inside the true length) -> carried fw
        # state + per-chunk activations; a new long prompt resumes its fw
        # pass at the longest cached boundary (the bw pass always re-runs —
        # it reads the suffix).  Bounded LRU; device arrays are read-only.
        self._fw_cache: "OrderedDict[tuple, Dict[str, Any]]" = OrderedDict()
        self._fw_cache_cap = 8

        # -- speculative decoding (serving_spec_decode) --------------------
        self.spec_decode = bool(
            spec_decode if spec_decode is not None
            else _flags.get_flag("serving_spec_decode")
        )
        self.spec_ngram = max(1, int(
            spec_ngram if spec_ngram is not None
            else _flags.get_flag("serving_spec_ngram")
        ))
        self.spec_proposed = 0
        self.spec_accepted = 0

        # chunked prefill: validate the chunk bound against the block size
        # and the ladder (every taller rung must split into whole chunks),
        # then extract the encoder weight bundle — an unmatched topology
        # fails HERE, not mid-request
        pc = (
            prefill_chunk_tokens if prefill_chunk_tokens is not None
            else _flags.get_flag("serving_prefill_chunk_tokens")
        )
        self.prefill_chunk_tokens = max(0, int(pc))
        self._enc_w = None
        if self.prefill_chunk_tokens:
            c = self.prefill_chunk_tokens
            if c % blk != 0:
                raise ValueError(
                    f"serving_prefill_chunk_tokens={c} must be a multiple "
                    f"of serving_block_tokens={blk}"
                )
            bad = [r for r in DEFAULT_LADDER if r > c and r % c != 0]
            if bad:
                raise ValueError(
                    f"serving_prefill_chunk_tokens={c} must divide every "
                    f"taller shape-ladder rung; {bad} are not multiples"
                )
            self._enc_w = self._extract_encoder_weights()

        # compile accounting: prefill batches observe the same shape-cache
        # contract training feeds use; decode keys are (slot-rung,
        # page-rung) pairs counted through the same StatSet surface
        self.prefill_shapes = CompileShapeCache("serving_prefill", self._stats)
        self.trace_counts = {
            "prefill": 0, "decode": 0, "prefill_chunk": 0, "verify": 0,
            "beam": 0,
        }
        self._prefill_jit = self._make_prefill()
        self._decode_table: Dict[Tuple[int, int], Any] = {}
        self._verify_table: Dict[Tuple[int, int], Any] = {}
        self._beam_table: Dict[Tuple[int, int, int], Any] = {}
        self._prefill_table: Dict[tuple, Any] = {}
        self._ref_table: Dict[tuple, Any] = {}
        self._chunk_jits: Optional[Dict[str, Any]] = (
            self._make_chunk_programs() if self.prefill_chunk_tokens else None
        )

        self._aot = None
        if aot_cache_dir is None:
            aot_cache_dir = _flags.get_flag("aot_cache_dir")
        if aot_cache_dir:
            from paddle_tpu.core.aot_cache import AOTCache

            self._aot = AOTCache(aot_cache_dir, stats=self._stats)

    # ------------------------------------------------------------------
    @property
    def n_live(self) -> int:
        return len(self._slots)

    @property
    def n_prefilling(self) -> int:
        """Slots held by chunked prefills still scanning their prompt."""
        return len(self._prefilling)

    @property
    def n_free_slots(self) -> int:
        return len(self._free_slots)

    @property
    def pages(self) -> BlockPagedCache:
        return self._pages

    def max_src_tokens(self) -> int:
        """Longest admissible source: its pages must fit the whole pool."""
        return self._pages.n_blocks * self.block_tokens

    def outstanding_requests(self) -> List:
        """Every request holding a slot (live decode or chunked prefill)."""
        return (
            [s.request for s in self._slots.values()]
            + [p.request for p in self._prefilling.values()]
        )

    # -- cancellation ----------------------------------------------------
    def cancel(self, request) -> bool:
        """Release ``request``'s slot and pages WITHOUT finishing it (the
        scheduler's timeout/deadline path): decoding for a client that
        gave up is the orphaned-slot leak this closes.  True when the
        request held a slot here."""
        for sid, s in self._slots.items():
            if s.request is request:
                self._slots.pop(sid)
                self._release_slot_pages(s)
                self._free_slots.append(sid)
                self._stats.incr("serving/canceled")
                return True
        for sid, p in self._prefilling.items():
            if p.request is request:
                # mid-chunked-prefill pages are only PARTIALLY written —
                # never cacheable, straight back to the free list
                self._prefilling.pop(sid)
                self._pages.free(p.pages)
                self._free_slots.append(sid)
                self._stats.incr("serving/canceled")
                return True
        return False

    def cancel_by_id(self, req_id: str):
        """Cancel by ``req_id``; returns the released request, or None."""
        for s in list(self._slots.values()):
            if s.request.req_id == req_id:
                self.cancel(s.request)
                return s.request
        for p in list(self._prefilling.values()):
            if p.request.req_id == req_id:
                self.cancel(p.request)
                return p.request
        return None

    # -- copy-on-write prefix cache ---------------------------------------
    def _prefix_key(self, tokens) -> tuple:
        """Cache key of a full prompt: the per-block hash chain seeded by
        the engine signature.  Chaining block-tuple hashes (not one flat
        hash) is what lets the same arithmetic address block-aligned
        prefixes, and the signature seed is the ISSUE's aliasing guard —
        a different topology fingerprint, feed dtype or tokenizer
        (source slot/vocab, special ids) can never produce this key."""
        h = self._cache_sig_hash
        toks = [int(t) for t in tokens]
        for i in range(0, len(toks), self.block_tokens):
            h = hash((h, tuple(toks[i:i + self.block_tokens])))
        return (h, len(toks))

    def _prefix_lookup(self, src):
        """(key, entry) when the FULL prompt is cached, else None.  The
        stored exact token tuple is compared on every hit, so a hash
        collision degrades to a miss — aliasing is structurally off."""
        key = self._prefix_key(src)
        ent = self._prefix_cache.get(key)
        if ent is None or ent["tokens"] != tuple(int(t) for t in src):
            return None
        return key, ent

    def _release_slot_pages(self, s: _Slot) -> None:
        """Retire/cancel/preempt all funnel here — THE prefix-cache
        insertion point.  A slot whose pages ARE the cache entry releases
        with retain (the entry's blocks park refcount-0 in the warm LRU
        pool); a slot with a captured boot state and no entry yet INSERTS
        one (its fully-written pages become the shared copy); anything
        else — cache off, resumed prefill (no clean boot), a COW'd or
        duplicate-prompt slot — frees normally."""
        if not self.prefix_cache_enabled:
            self._pages.free(s.pages)
            return
        key = self._prefix_key(s.request.src_ids)
        ent = self._prefix_cache.get(key)
        if ent is not None and list(ent["pages"]) == list(s.pages):
            self._pages.release(s.pages, retain=True)
            return
        if ent is None and s.boot_h is not None:
            self._prefix_cache[key] = {
                "tokens": tuple(int(t) for t in s.request.src_ids),
                "pages": list(s.pages),
                "boot_h": s.boot_h,
                "enc_tokens": s.enc_tokens,
            }
            for p in s.pages:
                self._prefix_owner[p] = key
            self._pages.release(s.pages, retain=True)
            self._stats.incr("serving/prefix_inserted")
            return
        self._pages.free(s.pages)

    def _on_block_evicted(self, block: int) -> None:
        """Allocator reclaimed a retained block (LRU, under HBM pressure):
        the entry owning it just lost bytes — drop the WHOLE entry so a
        later hit can never map a half-dead prefix.  Its surviving blocks
        stay in the retained pool as plain reclaimable capacity."""
        key = self._prefix_owner.pop(block, None)
        if key is None:
            return
        ent = self._prefix_cache.pop(key, None)
        if ent is not None:
            for p in ent["pages"]:
                self._prefix_owner.pop(p, None)
            self._stats.incr("serving/prefix_evicted")

    def ensure_private_pages(self, s: _Slot) -> bool:
        """The copy-on-write barrier: called before ANY write into a
        slot's encoder pages, it swaps every block the slot shares with
        another reader (refcount >= 2) for a fresh private copy — pool
        rows copied FIRST, page table remapped after — so a write can
        never mutate bytes another sequence is attending over.  False =
        no blocks for the copies (caller must wait; state untouched).

        The shipped decode/verify/beam programs write only the slot plane
        (``_h``) and READ the encoder pools, so shared pages are safe
        during decode by construction; this barrier is the mandatory
        gate for any pool-writing path (and what the COW-safety tests
        pin)."""
        if all(self._pages.refcount(p) == 1 for p in s.pages):
            return True
        new_pages, copies = self._pages.cow(s.pages)
        if new_pages is None:
            return False
        src = jnp.asarray([a for a, _ in copies], jnp.int32)
        dst = jnp.asarray([b for _, b in copies], jnp.int32)
        self._enc_pool = self._enc_pool.at[dst].set(self._enc_pool[src])
        self._ep_pool = self._ep_pool.at[dst].set(self._ep_pool[src])
        s.pages = new_pages
        self._stats.incr("serving/cow_copies", len(copies))
        return True

    @property
    def prefix_cache_len(self) -> int:
        return len(self._prefix_cache)

    def spec_accept_rate(self) -> float:
        """Fraction of drafted tokens the target model confirmed (0.0
        before any speculative dispatch ran)."""
        if not self.spec_proposed:
            return 0.0
        return self.spec_accepted / float(self.spec_proposed)

    # -- chunked-prefill weight extraction --------------------------------
    def _extract_encoder_weights(self):
        """Weight bundle + activation names of the bi-GRU encoder idiom
        (embedding -> per-direction gate fc -> gru / reversed gru ->
        concat -> identity projection fc; boot = fc over first_seq(enc)):
        the chunk programs re-run exactly this chain with carried state.
        A topology outside the idiom raises — chunked prefill has no
        interpreted fallback, matching the decode-side contract."""
        topo = self._gen._enc_net.topology
        gp_sub = self._gp

        def conf(name):
            return topo.layers[name]

        enc_c = conf(self._enc_layer)
        if enc_c.type != "concat" or len(enc_c.inputs) != 2:
            raise ValueError(
                "chunked prefill requires enc = concat(fwd GRU, bwd GRU); "
                f"got {enc_c.type} over {enc_c.inputs}"
            )
        dirs = {}
        emb_name = None
        for gname in enc_c.inputs:
            g = conf(gname)
            if g.type != "gru":
                raise ValueError(
                    f"chunked prefill: encoder branch {gname} is {g.type}, "
                    "expected a fused grumemory"
                )
            t = conf(g.inputs[0])
            if t.type != "fc" or len(t.inputs) != 1:
                raise ValueError(
                    f"chunked prefill: gate projection {g.inputs[0]} must "
                    "be a single-input fc"
                )
            e = conf(t.inputs[0])
            if e.type != "embedding":
                raise ValueError(
                    f"chunked prefill: encoder input {t.inputs[0]} must be "
                    "an embedding"
                )
            if emb_name is None:
                emb_name = e.name
            elif emb_name != e.name:
                raise ValueError(
                    "chunked prefill: both GRU directions must share one "
                    "source embedding"
                )
            key = "bw" if g.attr("reverse", False) else "fw"
            if key in dirs:
                raise ValueError(
                    "chunked prefill: expected one forward and one "
                    "reversed GRU direction"
                )
            dirs[key] = (gname, t.name, g)
        if set(dirs) != {"fw", "bw"}:
            raise ValueError(
                "chunked prefill: encoder must pair a forward and a "
                "reversed GRU"
            )
        ep_c = conf(self._ep_layer)
        if (ep_c.type != "fc" or ep_c.inputs != (enc_c.name,)
                or ep_c.act not in ("identity", "linear", "")):
            raise ValueError(
                "chunked prefill: encoded projection must be an identity "
                f"fc over {enc_c.name}"
            )
        boot_names = [
            n for n in topo.output_names
            if n not in (self._enc_layer, self._ep_layer)
        ]
        if len(boot_names) != 1:
            raise ValueError(
                f"chunked prefill: expected one boot output, got {boot_names}"
            )
        boot_c = conf(boot_names[0])
        first_c = conf(boot_c.inputs[0]) if boot_c.inputs else None
        if (boot_c.type != "fc" or first_c is None
                or first_c.type != "seqlastins"
                or not first_c.attr("select_first", False)
                or first_c.inputs != (enc_c.name,)):
            raise ValueError(
                "chunked prefill: decoder boot must be fc(first_seq(enc))"
            )

        net = self._gen._enc_net
        lp = lambda n: net.layer_params(gp_sub, n)
        out = {"emb_w": lp(emb_name)["w"]}
        for key in ("fw", "bw"):
            gname, tname, g = dirs[key]
            tp, gpr = lp(tname), lp(gname)
            out[f"{key}_gates_w"] = tp["w0"]
            out[f"{key}_gates_b"] = tp.get("b")
            out[f"{key}_w_h"] = gpr["w_h"]
            out[f"{key}_w_c"] = gpr["w_c"]
            out[f"{key}_b"] = gpr.get("b")
        pp, bp = lp(ep_c.name), lp(boot_c.name)
        out["proj_w"] = pp["w0"]
        out["proj_b"] = pp.get("b")
        out["boot_w"] = bp["w0"]
        out["boot_b"] = bp.get("b")
        gf, gb = dirs["fw"][2], dirs["bw"][2]
        self._enc_acts = {
            "fw": (gf.attr("gate_act", "sigmoid"),
                   gf.attr("active_type", gf.act or "tanh")),
            "bw": (gb.attr("gate_act", "sigmoid"),
                   gb.attr("active_type", gb.act or "tanh")),
            "boot": boot_c.act or "identity",
        }
        return out

    def _make_chunk_programs(self):
        """The four fixed-shape chunk jits.  Scan splitting preserves
        bit-identity: each chunk executes the identical per-step ops the
        unsplit encoder scan would, from the carried state."""
        from paddle_tpu.layers.base import take_rows_or_zero
        from paddle_tpu.ops.activations import get_activation
        from paddle_tpu.ops.rnn import gru_scan

        acts = self._enc_acts
        blk = self.block_tokens
        c_tokens = self.prefill_chunk_tokens

        def chunk_dir(key, reverse):
            gate_act, act = acts[key]

            def run(w, ids, lk, h):
                self.trace_counts["prefill_chunk"] += 1
                emb = take_rows_or_zero(w["emb_w"], ids)
                gates = acc_matmul(emb, w[f"{key}_gates_w"])
                if w[f"{key}_gates_b"] is not None:
                    gates = gates + w[f"{key}_gates_b"]
                return gru_scan(
                    gates, w[f"{key}_w_h"], w[f"{key}_w_c"], w[f"{key}_b"],
                    lk, gate_act=gate_act, act=act, reverse=reverse, h0=h,
                )

            return jax.jit(run)

        def scatter(enc_pool, ep_pool, fw_hs, bw_hs, rows, w, sp_b):
            self.trace_counts["prefill_chunk"] += 1
            enc = jnp.concatenate([fw_hs, bw_hs], axis=-1)  # [1, C, 2H]
            ep = acc_matmul(enc, w["proj_w"])
            if w["proj_b"] is not None:
                ep = ep + w["proj_b"]
            if sp_b is not None:
                ep = ep + sp_b  # score-key bias folds in at prefill time
            nb = c_tokens // blk
            enc_pool = enc_pool.at[rows].set(
                enc.reshape(nb, blk, enc.shape[-1])
            )
            ep_pool = ep_pool.at[rows].set(ep.reshape(nb, blk, ep.shape[-1]))
            return enc_pool, ep_pool

        boot_act = get_activation(acts["boot"])

        def boot_write(h_state, slot_rows, fw0, bw0, boot_mask, h_override,
                       w):
            self.trace_counts["prefill_chunk"] += 1
            enc0 = jnp.concatenate([fw0, bw0], axis=-1)  # [1, 2H]
            boot = acc_matmul(enc0, w["boot_w"])
            if w["boot_b"] is not None:
                boot = boot + w["boot_b"]
            boot = boot_act(boot)
            h_write = jnp.where(boot_mask[:, None], boot, h_override)
            return h_state.at[slot_rows].set(h_write)

        return {
            "fw": chunk_dir("fw", False),
            "bw": chunk_dir("bw", True),
            "scatter": jax.jit(scatter, donate_argnums=(0, 1)),
            "boot": jax.jit(boot_write, donate_argnums=(0,)),
        }

    # -- compiled program builders --------------------------------------
    def _make_prefill(self):
        enc_net = self._gen._enc_net
        enc_l, ep_l = self._enc_layer, self._ep_layer
        blk = self.block_tokens

        def prefill(gp, state, batch, enc_pool, ep_pool, h_state,
                    page_rows, slot_rows, boot_mask, h_override, sp_b):
            self.trace_counts["prefill"] += 1
            outs, _ = enc_net.apply(gp, batch, state=state, train=False)
            enc = outs[enc_l].data  # [b, S, De]
            ep = outs[ep_l].data
            if sp_b is not None:
                ep = ep + sp_b  # score-key bias folds in at prefill time
            boot = outs["dec_boot"].data
            b, s = enc.shape[0], enc.shape[1]
            nb = s // blk
            flat = page_rows.reshape(-1)
            enc_pool = enc_pool.at[flat].set(
                enc.reshape(b * nb, blk, enc.shape[-1])
            )
            ep_pool = ep_pool.at[flat].set(
                ep.reshape(b * nb, blk, ep.shape[-1])
            )
            # resumed slots keep their saved GRU state instead of the boot
            h_write = jnp.where(boot_mask[:, None], boot, h_override)
            h_state = h_state.at[slot_rows].set(h_write)
            return enc_pool, ep_pool, h_state

        return jax.jit(prefill, donate_argnums=(3, 4, 5))

    def _make_decode(self, b_rung: int, p_rung: int):
        blk = self.block_tokens
        eos = self._gen.eos_id
        acts = self._acts
        w_meta = self._w_meta

        k_steps = self.block_steps

        def decode(h_state, enc_pool, ep_pool, slot_idx, tables, enc_len,
                   ids, live, w):
            self.trace_counts["decode"] += 1
            if w_meta:
                # int8-resident weights: one in-graph dequantize per
                # dispatch (amortized over K tokens x B slots); XLA keeps
                # the f32 materialization in the dispatch working set
                from paddle_tpu.ops import quantize as _bsq

                w = _bsq.dequantize_weight_bundle(w, w_meta)
            h = h_state[slot_idx]  # [B, H]
            enc = enc_pool[tables].reshape(b_rung, p_rung * blk, -1)
            ep = ep_pool[tables].reshape(b_rung, p_rung * blk, -1)
            emask = (
                jnp.arange(p_rung * blk, dtype=jnp.int32)[None, :]
                < enc_len[:, None]
            )

            def inner(carry, _):
                h_p, ids_p, fin = carry
                xg = jnp.take(w["emb_w"], ids_p, axis=0) @ w["w_emb"]
                if w["xg_bias"] is not None:
                    xg = xg + w["xg_bias"]
                h_t = attention_gru_step(
                    xg, h_p, enc, ep, emask, w["w1"], w["v"], w["w_ctx"],
                    w["w_c"], **acts,
                )
                logits = h_t @ w["head_w"]
                if w["head_b"] is not None:
                    logits = logits + w["head_b"]
                # the exact ops/beam greedy chain, for bit-identity
                _, nxt = greedy_token_chain(logits)
                # dead lanes and finished rows only re-emit EOS, and a
                # finished row's state freezes — the host reads tokens up
                # to the FIRST eos, so every visible token rode the exact
                # one-shot chain
                dead = fin | ~live
                nxt = jnp.where(dead, eos, nxt)
                h_n = jnp.where(dead[:, None], h_p, h_t)
                return (h_n, nxt, fin | (nxt == eos)), nxt

            fin0 = jnp.zeros(ids.shape, bool)
            (h_f, _, _), toks = jax.lax.scan(
                inner, (h, ids, fin0), None, length=k_steps
            )
            h_state = h_state.at[slot_idx].set(h_f)
            return h_state, jnp.swapaxes(toks, 0, 1)  # [B, K]

        return jax.jit(decode, donate_argnums=(0,))

    def _make_verify(self, b_rung: int, p_rung: int):
        """The speculative verify-K program — the SAME compiled shape
        family as :meth:`_make_decode` (one (slot-rung, page-rung) jit,
        K = ``serving_decode_block_steps`` inner steps per dispatch), but
        the K step inputs are the DRAFT tokens instead of each step's own
        argmax, so position j's input no longer waits on position j-1's
        output: the embedding+projection half of the chain hoists into ONE
        batched [B, K] GEMM before the scan — the sequential-GRU flops a
        draft actually buys back.

        Emission contract (what keeps the fallback bit-identical): the
        draft is a HYPOTHESIS that these are the greedy tokens.  With
        ``m`` = leading positions where the target's own argmax agreed
        with the draft, steps 0..m all consumed correct context, so the
        first m tokens (== the draft's) AND the target's own token at
        position m are exactly the greedy chain — ``n_emit = min(m+1, K)``
        tokens land per row, and the host consumes EXACTLY that many
        (positions past n_emit rode misdrafted context and are garbage by
        contract, never EOS-clamped into looking final).  Full agreement
        emits all K; total disagreement emits 1 — plain greedy pace, same
        tokens, never slower in tokens-per-dispatch."""
        blk = self.block_tokens
        acts = self._acts
        w_meta = self._w_meta
        k_steps = self.block_steps

        def verify(h_state, enc_pool, ep_pool, slot_idx, tables, enc_len,
                   ids, live, draft, w):
            self.trace_counts["verify"] += 1
            if w_meta:
                from paddle_tpu.ops import quantize as _bsq

                w = _bsq.dequantize_weight_bundle(w, w_meta)
            h = h_state[slot_idx]  # [B, H]
            enc = enc_pool[tables].reshape(b_rung, p_rung * blk, -1)
            ep = ep_pool[tables].reshape(b_rung, p_rung * blk, -1)
            emask = (
                jnp.arange(p_rung * blk, dtype=jnp.int32)[None, :]
                < enc_len[:, None]
            )
            # teacher-forced inputs: step 0 consumes the real last token,
            # step j consumes draft[j-1]; all K embeddings in one GEMM
            inp = jnp.concatenate([ids[:, None], draft[:, :-1]], axis=1)
            xg_all = jnp.take(w["emb_w"], inp, axis=0) @ w["w_emb"]
            if w["xg_bias"] is not None:
                xg_all = xg_all + w["xg_bias"]

            def inner(h_p, xg):
                h_t = attention_gru_step(
                    xg, h_p, enc, ep, emask, w["w1"], w["v"], w["w_ctx"],
                    w["w_c"], **acts,
                )
                logits = h_t @ w["head_w"]
                if w["head_b"] is not None:
                    logits = logits + w["head_b"]
                _, nxt = greedy_token_chain(logits)
                return h_t, (nxt, h_t)

            _, (toks, hs) = jax.lax.scan(
                inner, h, jnp.swapaxes(xg_all, 0, 1)
            )
            toks = jnp.swapaxes(toks, 0, 1)      # [B, K]
            hs = jnp.swapaxes(hs, 0, 1)          # [B, K, H]
            match = jnp.cumprod(
                (toks == draft).astype(jnp.int32), axis=1
            )
            m_full = jnp.sum(match, axis=1)      # leading agreement count
            n_emit = jnp.minimum(m_full + 1, k_steps)
            # h after step n_emit-1 consumed only verified context — the
            # exact greedy state after the emitted tokens; dead lanes
            # freeze (and stamp n_emit 0 so the host skips them)
            h_sel = hs[jnp.arange(b_rung), n_emit - 1]
            h_new = jnp.where(live[:, None], h_sel, h)
            n_emit = jnp.where(live, n_emit, 0)
            h_state = h_state.at[slot_idx].set(h_new)
            return h_state, toks, n_emit, m_full

        return jax.jit(verify, donate_argnums=(0,))

    def _make_beam(self, p_rung: int, beam_k: int, max_new: int):
        """The paged whole-sequence beam program: gathers one request's
        encoder memory through its page-table row (exactly like decode —
        page-table contents, never shapes), expands it to the K beam
        rows, and runs ops/beam.beam_search over the SAME fused step the
        one-shot ``Seq2SeqGenerator.generate`` uses
        (models/seq2seq.make_fused_step — one closure, one chain), from
        the slot's booted decoder state.  The pool's score keys already
        carry the folded sp_b, so the per-step math is identical to the
        one-shot path's statics.  One compiled variant per (page-rung,
        beam-width, max-len)."""
        from paddle_tpu.models.seq2seq import make_fused_step
        from paddle_tpu.ops.beam import beam_search

        blk = self.block_tokens
        acts = self._acts
        w_meta = self._w_meta
        gen = self._gen

        def beam(h_state, enc_pool, ep_pool, sid, table, enc_len, w):
            self.trace_counts["beam"] += 1
            if w_meta:
                from paddle_tpu.ops import quantize as _bsq

                w = _bsq.dequantize_weight_bundle(w, w_meta)
            enc = enc_pool[table].reshape(1, p_rung * blk, -1)
            ep = ep_pool[table].reshape(1, p_rung * blk, -1)
            emask = (
                jnp.arange(p_rung * blk, dtype=jnp.int32)[None, :]
                < enc_len[:, None]
            )
            fused = make_fused_step(
                w,
                jnp.repeat(enc, beam_k, axis=0),
                jnp.repeat(ep, beam_k, axis=0),
                jnp.repeat(emask, beam_k, axis=0),
                gate_act=acts["gate_act"], act=acts["act"],
                att_act=acts["att_act"],
            )

            def step_fn(step_ids, carry):
                logp, h_t = fused(step_ids, carry["h"])
                return logp, {"h": h_t}

            return beam_search(
                step_fn,
                {"h": h_state[sid]},  # [1, H]; beam_search repeats to K
                batch_size=1,
                beam_size=beam_k,
                vocab_size=self.trg_vocab,
                bos_id=gen.bos_id,
                eos_id=gen.eos_id,
                max_len=max_new,
                candidate_adjust_fn=gen.candidate_adjust_fn,
                drop_fn=gen.drop_fn,
                norm_fn=gen.norm_fn,
            )

        return jax.jit(beam)

    def _prefill_exe(self, batch, args):
        if self._aot is None:
            # jax.jit dispatches by shape itself; the table only earns its
            # keep routing distinct shapes to deserialized AOT executables
            return self._prefill_jit
        key = batch_shape_key(batch)
        exe = self._prefill_table.get(key)
        if exe is None:
            from paddle_tpu.core import aot_cache as _aot

            exe = self._aot.get_or_compile(
                self._prefill_jit, args,
                {
                    "kind": "serving_prefill",
                    "topology": _aot.topology_fingerprint(self._gen.net),
                    "batch": str(key),
                    "pool_rows": self._pages.pool_rows,
                    "block_tokens": self.block_tokens,
                    "max_slots": self.max_slots,
                },
            )
            self._prefill_table[key] = exe
        return exe

    def _decode_exe(self, b_rung: int, p_rung: int, args):
        key = (b_rung, p_rung)
        exe = self._decode_table.get(key)
        if exe is None:
            self._stats.incr("serving_decode/compile_miss")
            exe = self._make_decode(b_rung, p_rung)
            if self._aot is not None:
                from paddle_tpu.core import aot_cache as _aot

                exe = self._aot.get_or_compile(
                    exe, args,
                    {
                        "kind": "serving_decode",
                        "topology": _aot.topology_fingerprint(self._gen.net),
                        "slot_rung": b_rung,
                        "page_rung": p_rung,
                        "pool_rows": self._pages.pool_rows,
                        "block_tokens": self.block_tokens,
                        "max_slots": self.max_slots,
                    },
                )
            self._decode_table[key] = exe
        else:
            self._stats.incr("serving_decode/compile_hit")
        return exe

    def _verify_exe(self, b_rung: int, p_rung: int, args):
        key = (b_rung, p_rung)
        exe = self._verify_table.get(key)
        if exe is None:
            self._stats.incr("serving_verify/compile_miss")
            exe = self._make_verify(b_rung, p_rung)
            if self._aot is not None:
                from paddle_tpu.core import aot_cache as _aot

                exe = self._aot.get_or_compile(
                    exe, args,
                    {
                        "kind": "serving_verify",
                        "topology": _aot.topology_fingerprint(self._gen.net),
                        "slot_rung": b_rung,
                        "page_rung": p_rung,
                        "pool_rows": self._pages.pool_rows,
                        "block_tokens": self.block_tokens,
                        "max_slots": self.max_slots,
                    },
                )
            self._verify_table[key] = exe
        else:
            self._stats.incr("serving_verify/compile_hit")
        return exe

    # -- admission -------------------------------------------------------
    def _chunked_extent(self, src_len: int) -> Optional[int]:
        """Padded extent when ``src_len`` takes the chunked-prefill path
        (its rung exceeds the chunk bound), else None (one-shot batch
        prefill — short prompts keep the fused group dispatch)."""
        if not self.prefill_chunk_tokens:
            return None
        s_pad = ladder_len(src_len, DEFAULT_LADDER)
        return s_pad if s_pad > self.prefill_chunk_tokens else None

    def _admit_chunked(self, r, sid: int, pages, s_pad: int) -> None:
        """Register one long prompt for chunk-at-a-time prefill: pad its
        ids through the same feeder contract the batch path uses, lay out
        its page rows over the padded extent (scratch past its real
        pages), and queue it behind any prefill already in flight."""
        batch = self._feeder([(list(r.src_ids),)])
        ids = np.asarray(batch[self.src_slot].data, np.int32)
        if ids.ndim >= 2 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        length = np.asarray(batch[self.src_slot].lengths, np.int32)
        rows = np.full((s_pad // self.block_tokens,), self._pages.scratch,
                       np.int32)
        rows[: len(pages)] = pages
        resume = getattr(r, "_resume", None)
        if resume is not None:
            r._resume = None
        self._prefilling[sid] = _PendingPrefill(
            request=r,
            pages=pages,
            enc_tokens=len(r.src_ids),
            max_new=min(
                r.max_new_tokens or self.default_max_new_tokens,
                self._gen.max_length,
            ),
            admit_seq=self._admit_seq,
            ids=ids,
            length=length,
            rows=rows,
            n_chunks=s_pad // self.prefill_chunk_tokens,
            h0=jnp.zeros(
                (1, self._enc_w["fw_w_h"].shape[0]), self._dtype
            ),
            resume=resume,
        )
        self._admit_seq += 1
        self._stats.incr("serving/chunked_prefills")
        if not self.prefix_cache_enabled:
            return
        # partial-prefix reuse: resume the FORWARD pass at the longest
        # cached chunk boundary fully inside the true prompt (fw carries
        # depend only on the prefix, so they are bit-exact for any
        # continuation; the bw pass reads the suffix and always re-runs)
        p = self._prefilling[sid]
        C = self.prefill_chunk_tokens
        true_len = int(length[0])
        for j in range(p.n_chunks - 1, -1, -1):
            if (j + 1) * C > true_len:
                continue
            key = (self._cache_sig_hash,
                   tuple(int(t) for t in ids[0, :(j + 1) * C]))
            ent = self._fw_cache.get(key)
            if ent is None:
                continue
            p.cursor = j + 1
            p.h = ent["h"]
            for i in range(j + 1):
                p.fw_chunks[i] = ent["chunks"][i]
            self._fw_cache.move_to_end(key)
            self._stats.incr("serving/prefix_fw_reuse", j + 1)
            if p.cursor == p.n_chunks:
                p.phase = "bw"
                p.cursor = p.n_chunks - 1
                p.h = jnp.zeros_like(ent["h"])
            break

    def admit(self, requests: Sequence) -> List:
        """Admit a FIFO prefix of ``requests`` (free slot + pages for each;
        the first misfit stops admission — strict FCFS, no starvation):
        short prompts prefill as ONE bucketed batch; prompts past the
        chunked-prefill bound register for chunk-at-a-time prefill
        instead.  Returns the admitted list, submission order."""
        group = []  # (slot_id, request, pages)
        admitted = []
        for r in requests:
            if not self._free_slots:
                break
            src = r.src_ids
            beam_k = int(getattr(r, "beam_size", None) or 0)
            if beam_k <= 1:
                beam_k = 0  # beam of one IS greedy — the cheaper loop
            max_new = min(
                r.max_new_tokens or self.default_max_new_tokens,
                self._gen.max_length,
            )
            resume = getattr(r, "_resume", None)
            hit = (
                self._prefix_lookup(src)
                if self.prefix_cache_enabled else None
            )
            if hit is not None:
                # prefill-once: the cached blocks map straight into this
                # request's page table (refcount +1, zero new blocks, ZERO
                # prefill dispatches) and the decoder boots from the
                # entry's captured state — bit-identical because the
                # entry's pages hold exactly what prefilling this prompt
                # would write (same tokens, same engine signature)
                key, ent = hit
                self._pages.share(ent["pages"])
                sid = self._free_slots.pop()
                admitted.append(r)
                self.prefix_hits += 1
                self._stats.incr("serving/prefix_hits")
                if resume is not None:
                    h_row = jnp.asarray(resume["h"], self._dtype)
                    r._resume = None
                else:
                    h_row = jnp.asarray(ent["boot_h"], self._dtype)
                self._h = self._h.at[sid].set(h_row)
                self._slots[sid] = _Slot(
                    request=r,
                    pages=list(ent["pages"]),
                    enc_tokens=ent["enc_tokens"],
                    last_id=(
                        resume["last_id"] if resume is not None
                        else self._gen.bos_id
                    ),
                    tokens=(
                        list(resume["tokens"]) if resume is not None else []
                    ),
                    max_new=max_new,
                    admit_seq=self._admit_seq,
                    beam=beam_k,
                    boot_h=None,
                )
                self._admit_seq += 1
                r.t_admit = self._clock()
                continue
            pages = self._pages.alloc(self._pages.pages_for_tokens(len(src)))
            if pages is None:
                break
            if self.prefix_cache_enabled:
                self.prefix_misses += 1
                self._stats.incr("serving/prefix_misses")
            sid = self._free_slots.pop()
            admitted.append(r)
            chunk_extent = self._chunked_extent(len(src))
            if chunk_extent is not None:
                self._admit_chunked(r, sid, pages, chunk_extent)
                r.t_admit = self._clock()
                continue
            slot = _Slot(
                request=r,
                pages=pages,
                enc_tokens=len(src),
                last_id=(
                    resume["last_id"] if resume is not None
                    else self._gen.bos_id
                ),
                tokens=list(resume["tokens"]) if resume is not None else [],
                max_new=max_new,
                admit_seq=self._admit_seq,
                beam=beam_k,
            )
            self._admit_seq += 1
            self._slots[sid] = slot
            group.append((sid, r, pages))
        if admitted:
            self._stats.incr("serving/admitted", len(admitted))
        if not group:
            return admitted

        batch = self._feeder([(list(r.src_ids),) for _, r, _ in group])
        b_rung = ladder_len(len(group), DEFAULT_BATCH_LADDER)
        batch = pad_batch_rows(batch, b_rung)
        s_pad = batch[self.src_slot].data.shape[1]
        nb = s_pad // self.block_tokens
        scratch = self._pages.scratch
        page_rows = np.full((b_rung, nb), scratch, np.int32)
        slot_rows = np.full((b_rung,), self._scratch_slot, np.int32)
        boot_mask = np.zeros((b_rung,), bool)
        h_override = np.zeros((b_rung, self.hidden_dim), self._dtype)
        for k, (sid, r, pages) in enumerate(group):
            page_rows[k, : len(pages)] = pages
            slot_rows[k] = sid
            resume = getattr(r, "_resume", None)
            if resume is None:
                boot_mask[k] = True
            else:
                h_override[k] = resume["h"]
                r._resume = None
        args = (
            self._gp, self._state, batch, self._enc_pool, self._ep_pool,
            self._h, page_rows, slot_rows, boot_mask, h_override,
            self._w["sp_b"],
        )
        self.prefill_shapes.observe(batch)
        exe = self._prefill_exe(batch, args)
        with _obs.span(
            "prefill", cat="serving", n=len(group), src_pad=int(s_pad),
            reqs=[r.req_id for _, r, _ in group],
        ):
            self._enc_pool, self._ep_pool, self._h = exe(*args)
        if self.prefix_cache_enabled:
            # capture each cleanly-booted slot's decoder boot state (tiny
            # [H] row) — at retire its fully-written pages + this state
            # become the prefix-cache entry; resumed slots carry a mid-
            # decode h, not the boot, so they never seed an entry
            h_host = np.asarray(self._h)
            for k, (sid, _, _) in enumerate(group):
                if boot_mask[k]:
                    self._slots[sid].boot_h = h_host[sid].copy()
        now = self._clock()
        for _, r, _ in group:
            r.t_admit = now
        return admitted

    # -- chunked prefill advance ------------------------------------------
    def _advance_prefill(self) -> None:
        """Run ONE chunk dispatch of the oldest pending chunked prefill:
        the forward pass ascends the chunks carrying fwd GRU state; the
        backward pass descends carrying bwd state, scattering each
        completed span's pages as it goes; the final (leftmost) backward
        chunk writes the decoder boot state and the slot goes live."""
        sid, p = next(iter(self._prefilling.items()))
        jits = self._chunk_jits
        w = self._enc_w
        C = self.prefill_chunk_tokens
        k = p.cursor
        _obs.instant(
            "prefill_chunk", cat="serving", req=p.request.req_id,
            phase=p.phase, chunk=k, n_chunks=p.n_chunks,
        )
        ids = jnp.asarray(p.ids[:, k * C:(k + 1) * C])
        lk = jnp.asarray(np.clip(p.length - k * C, 0, C).astype(np.int32))
        if p.phase == "fw":
            hs, h = jits["fw"](w, ids, lk, p.h)
            p.fw_chunks[k] = hs
            p.h = h
            if (self.prefix_cache_enabled
                    and (k + 1) * C <= int(p.length[0])):
                # chunk fully inside the true length: its activations and
                # the carried state are prefix-determined — cacheable for
                # any future prompt sharing this chunk-aligned prefix
                key = (self._cache_sig_hash,
                       tuple(int(t) for t in p.ids[0, :(k + 1) * C]))
                self._fw_cache.pop(key, None)
                self._fw_cache[key] = {
                    "h": h, "chunks": list(p.fw_chunks[:k + 1]),
                }
                while len(self._fw_cache) > self._fw_cache_cap:
                    self._fw_cache.popitem(last=False)
            p.cursor += 1
            if p.cursor == p.n_chunks:
                p.phase = "bw"
                p.cursor = p.n_chunks - 1
                p.h = jnp.zeros_like(h)
            return
        hs, h = jits["bw"](w, ids, lk, p.h)
        nb = C // self.block_tokens
        rows = jnp.asarray(p.rows[k * nb:(k + 1) * nb])
        self._enc_pool, self._ep_pool = jits["scatter"](
            self._enc_pool, self._ep_pool, p.fw_chunks[k], hs, rows, w,
            self._w["sp_b"],
        )
        if k > 0:
            p.h = h
            p.cursor -= 1
            return
        # leftmost span scattered: write the boot state (or the saved GRU
        # state of a resumed preemption victim) and promote to decode
        boot_mask = np.asarray([p.resume is None])
        h_override = np.zeros((1, self.hidden_dim), self._dtype)
        if p.resume is not None:
            h_override[0] = p.resume["h"]
        self._h = jits["boot"](
            self._h, np.asarray([sid], np.int32), p.fw_chunks[0][:, 0],
            hs[:, 0], jnp.asarray(boot_mask), jnp.asarray(h_override), w,
        )
        self._prefilling.pop(sid)
        boot_h = None
        if self.prefix_cache_enabled and p.resume is None:
            boot_h = np.asarray(self._h[sid]).copy()
        beam_k = int(getattr(p.request, "beam_size", None) or 0)
        if beam_k <= 1:
            beam_k = 0
        self._slots[sid] = _Slot(
            request=p.request,
            pages=p.pages,
            enc_tokens=p.enc_tokens,
            last_id=(
                p.resume["last_id"] if p.resume is not None
                else self._gen.bos_id
            ),
            tokens=list(p.resume["tokens"]) if p.resume is not None else [],
            max_new=p.max_new,
            admit_seq=p.admit_seq,
            beam=beam_k,
            boot_h=boot_h,
        )

    # -- decode ----------------------------------------------------------
    def step(self) -> List:
        """Advance one chunked-prefill dispatch (if any long prompt is mid-
        prefill — the decode interleave that bounds its head-of-line
        stall), then one decode step for every live slot; returns the
        requests that finished this step (EOS emitted or
        ``max_new_tokens`` reached), their pages freed and slots
        recycled."""
        if self._prefilling:
            self._advance_prefill()
        if not self._slots:
            return []
        finished = []
        # beam slots: each one whole-sequence paged beam dispatch, retired
        # immediately (beam requests deliver a complete best hypothesis,
        # not a token stream)
        for sid in sorted(self._slots):
            if self._slots[sid].beam:
                finished.append(self._finish_beam(sid))
        live_ids = sorted(self._slots)
        if not live_ids:
            if finished:
                self._stats.incr("serving/decode_steps")
            return finished
        b_rung = ladder_len(len(live_ids), DEFAULT_BATCH_LADDER)
        max_pages = max(len(self._slots[s].pages) for s in live_ids)
        p_rung = ladder_len(max_pages, self._page_ladder)
        scratch = self._pages.scratch
        slot_idx = np.full((b_rung,), self._scratch_slot, np.int32)
        tables = np.full((b_rung, p_rung), scratch, np.int32)
        enc_len = np.zeros((b_rung,), np.int32)
        ids = np.full((b_rung,), self._gen.eos_id, np.int32)
        live = np.zeros((b_rung,), bool)
        for k, sid in enumerate(live_ids):
            s = self._slots[sid]
            slot_idx[k] = sid
            tables[k, : len(s.pages)] = s.pages
            enc_len[k] = s.enc_tokens
            ids[k] = s.last_id
            live[k] = True
        k_steps = self.block_steps
        if self.spec_decode:
            draft = np.full((b_rung, k_steps), self._gen.eos_id, np.int32)
            for k, sid in enumerate(live_ids):
                draft[k] = self._draft_tokens(self._slots[sid], k_steps)
            args = (
                self._h, self._enc_pool, self._ep_pool, slot_idx, tables,
                enc_len, ids, live, draft, self._w_arg,
            )
            exe = self._verify_exe(b_rung, p_rung, args)
            self._h, toks, n_emit, m_full = exe(*args)
            toks_host = np.asarray(toks)
            n_emit_host = np.asarray(n_emit)
            m_full_host = np.asarray(m_full)
        else:
            args = (
                self._h, self._enc_pool, self._ep_pool, slot_idx, tables,
                enc_len, ids, live, self._w_arg,
            )
            exe = self._decode_exe(b_rung, p_rung, args)
            self._h, toks = exe(*args)
            toks_host = np.asarray(toks)  # [B,K]: ONE host sync per K tokens
            n_emit_host = None
        now = self._clock()
        for k, sid in enumerate(live_ids):
            s = self._slots[sid]
            r = s.request
            if r.t_first_token is None:
                r.t_first_token = now
            done = False
            # spec mode: consume EXACTLY the verified tokens — positions
            # past n_emit rode misdrafted context and never reach a client
            limit = (
                int(n_emit_host[k]) if n_emit_host is not None
                else toks_host.shape[1]
            )
            for j in range(limit):
                tok = int(toks_host[k, j])
                if tok == self._gen.eos_id:
                    done = True
                    break
                s.tokens.append(tok)
                s.last_id = tok
                r.token_times.append(now)
                if len(s.tokens) >= s.max_new:
                    done = True
                    break
            if n_emit_host is not None:
                self.spec_proposed += k_steps
                self.spec_accepted += int(m_full_host[k])
            if done:
                finished.append(self._retire(sid))
        self._stats.incr("serving/decode_steps")
        return finished

    def _draft_tokens(self, s: _Slot, k: int) -> List[int]:
        """Prompt-lookup n-gram draft (the flagged draft model): match the
        request's trailing ``serving_spec_ngram`` GENERATED tokens against
        its own earlier generation and propose the continuation after the
        most recent match, padding by repetition.  Draws only from target-
        vocab tokens the request itself emitted — no second network, no
        extra weights, and a wrong guess costs nothing: the verify
        dispatch emits the true greedy tokens either way."""
        n = self.spec_ngram
        hist = s.tokens
        out: List[int] = []
        if len(hist) > n:
            key = tuple(hist[-n:])
            for i in range(len(hist) - n - 1, -1, -1):
                if tuple(hist[i:i + n]) == key:
                    out = list(hist[i + n:i + n + k])
                    break
        fill = out[-1] if out else s.last_id
        while len(out) < k:
            out.append(fill)
        return out[:k]

    def _finish_beam(self, sid: int):
        """Run one beam slot to completion: one paged beam dispatch, best
        hypothesis trimmed at EOS onto the request, slot retired."""
        s = self._slots[sid]
        p_rung = ladder_len(len(s.pages), self._page_ladder)
        table = np.full((1, p_rung), self._pages.scratch, np.int32)
        table[0, : len(s.pages)] = s.pages
        key = (p_rung, s.beam, s.max_new)
        exe = self._beam_table.get(key)
        if exe is None:
            self._stats.incr("serving_beam/compile_miss")
            exe = self._make_beam(p_rung, s.beam, s.max_new)
            self._beam_table[key] = exe
        else:
            self._stats.incr("serving_beam/compile_hit")
        with _obs.span(
            "beam", cat="serving", req=s.request.req_id, beam=s.beam,
        ):
            seqs, scores = exe(
                self._h, self._enc_pool, self._ep_pool,
                np.asarray([sid], np.int32), table,
                np.asarray([s.enc_tokens], np.int32), self._w_arg,
            )
        best = np.asarray(seqs)[0, 0]
        toks: List[int] = []
        for t in best:
            t = int(t)
            if t == self._gen.eos_id:
                break
            toks.append(t)
        s.tokens = toks[: s.max_new]
        now = self._clock()
        r = s.request
        if r.t_first_token is None:
            r.t_first_token = now
        r.token_times.extend([now] * len(s.tokens))
        r.beam_score = float(np.asarray(scores)[0, 0])
        self._stats.incr("serving/beam_requests")
        return self._retire(sid)

    def _retire(self, sid: int):
        s = self._slots.pop(sid)
        self._release_slot_pages(s)
        self._free_slots.append(sid)
        s.request.tokens = s.tokens
        self._stats.incr("serving/completed")
        return s.request

    # -- eviction / preemption -------------------------------------------
    def preempt(self):
        """Evict the NEWEST-admitted live sequence (least progress lost):
        free its pages, save its tiny GRU state + generated prefix on the
        request, and hand it back for re-queueing.  Re-admission re-runs
        prefill (the paged encoder state recomputes deterministically) and
        restores the saved state, so the final tokens stay bit-identical
        to an uninterrupted decode.  Returns the request, or None when
        nothing is live."""
        if not self._slots:
            return None
        sid = max(self._slots, key=lambda s: self._slots[s].admit_seq)
        s = self._slots.pop(sid)
        self._release_slot_pages(s)
        self._free_slots.append(sid)
        s.request._resume = {
            "h": np.asarray(self._h[sid]),
            "last_id": s.last_id,
            "tokens": list(s.tokens),
        }
        self._stats.incr("serving/preempted")
        return s.request

    # -- the one-shot reference path --------------------------------------
    def reference_decode(self, src_ids, max_new_tokens: Optional[int] = None
                         ) -> List[int]:
        """The UNBATCHED one-shot ``Seq2SeqGenerator.generate_greedy`` path
        for one request, through the same bucketed feeder and jitted per
        source rung (the one-shot serving baseline done right, weights as
        arguments per T102) — the bench's one-shot arm AND the golden the
        serving output is bit-compared against."""
        mx = (
            max_new_tokens if max_new_tokens is not None
            else self.default_max_new_tokens
        )
        batch = self._feeder([(list(src_ids),)])
        key = (batch_shape_key(batch), mx)
        exe = self._ref_table.get(key)
        if exe is None:
            exe = jax.jit(
                lambda p, bt: self._gen.generate_greedy(
                    bt, params=p, max_new_tokens=mx
                )
            )
            self._ref_table[key] = exe
        toks, lengths = exe(self._gen.params.params, batch)
        n = int(np.asarray(lengths)[0])
        return [int(t) for t in np.asarray(toks)[0, :n]]

    def weight_drift(self) -> float:
        """Bit-drift of the resident quantized bundle vs its f32 source:
        max over quantized keys of ``max|dequant(q) - w| / max|w|`` — the
        explicit budget the serving_int8_drift_budget flag bounds (0.0 on
        the f32 path)."""
        if not self._w_meta:
            return 0.0
        from paddle_tpu.ops import quantize as _bsq

        deq = _bsq.dequantize_weight_bundle(self._w_arg, self._w_meta)
        worst = 0.0
        for k in self._w_meta:
            a = np.asarray(self._w[k], np.float32)
            d = np.asarray(deq[k], np.float32)
            denom = float(np.max(np.abs(a))) or 1.0
            worst = max(worst, float(np.max(np.abs(d - a))) / denom)
        return worst

    def slots_per_gb(self, src_tokens: Optional[int] = None) -> float:
        """Capacity arithmetic the serving bench gates on: concurrent
        decode slots one GB of HBM holds AFTER the resident weight bundle,
        at the per-slot footprint of a ``src_tokens``-token source (default
        one page).  Weight-only int8 shrinks ``weight_bytes`` ~4x, so this
        rises under the same ``serving_hbm_budget_mb``."""
        pages = (
            self._pages.pages_for_tokens(src_tokens)
            if src_tokens is not None else 1
        )
        per_slot = (
            pages * self._pages.bytes_per_block
            + self.hidden_dim * jnp.dtype(self._dtype).itemsize
        )
        free = max((1 << 30) - self.weight_bytes, 0)
        return free / float(per_slot)

    def summary(self) -> Dict[str, Any]:
        return {
            "live": self.n_live,
            "prefilling": self.n_prefilling,
            "free_slots": self.n_free_slots,
            "pages": self._pages.summary(),
            "prefill_shapes": self.prefill_shapes.n_shapes,
            "decode_shapes": len(self._decode_table),
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "trace_counts": dict(self.trace_counts),
            "int8_weights": self.int8_weights,
            "weight_bytes": self.weight_bytes,
            "slots_per_gb": self.slots_per_gb(),
            "prefix_cache": self.prefix_cache_enabled,
            "prefix_entries": self.prefix_cache_len,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "spec_decode": self.spec_decode,
            "spec_accept_rate": self.spec_accept_rate(),
        }
