"""Continuous-batching scheduler — request queue + step/delivery threads.

The serving loop the reference never had (its capi inference is
call-and-wait, paddle/capi/gradient_machine.h): clients ``submit()``
requests from any thread; ONE step thread owns the
:class:`~paddle_tpu.serving.engine.ServingEngine` and, every iteration,
(1) drains newly submitted requests, validates them (a poisoned request is
REJECTED with an error result — it never reaches the batch), (2) admits a
FIFO prefix into free slots/pages (prefill), and (3) runs one decode step
for every live sequence — sequences admit and retire mid-flight with zero
recompiles (continuous batching).

Completion is two-phase so a slow client can never stall decoding:
``Request.wait()`` unblocks the moment the STEP thread finalizes the
request; user callbacks run on a separate delivery thread (a slow
callback delays only later callbacks, never the batch).  Chaos points
``nan_request`` (poison an incoming request at submit) and
``serve_slow_client`` (freeze the delivery thread mid-callback) drill
exactly these two isolation boundaries (robustness/chaos.py;
tests/test_serving_e2e.py proves the batch keeps stepping).

Concurrency discipline: both threads are daemon ``paddle-serve-*``
threads joined by :meth:`ServingScheduler.close`; the one shared lock is
built by the :mod:`~paddle_tpu.analysis.lock_sanitizer` factory (armed
drills watch it); every blocking wait is a bounded-timeout poll; clocks
and sleeps are injectable (the C-rules, analysis/concurrency_lint.py).
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from paddle_tpu.analysis.lock_sanitizer import THREAD_PREFIX, make_lock
from paddle_tpu.robustness import chaos

__all__ = ["Request", "ServingScheduler"]

_log = logging.getLogger("paddle_tpu.serving")

_req_counter = itertools.count()


class Request:
    """One generation request and its result/latency record.

    ``src_ids``: source token ids; ``max_new_tokens``: per-request decode
    cap (None = the engine's default); ``callback(request)`` runs on the
    delivery thread after completion.  Timing fields (``t_submit``,
    ``t_admit``, ``t_first_token``, ``t_done``, per-token ``token_times``)
    are stamped by the scheduler/engine clock — the raw material of the
    bench's sustained-req/s and p50/p99 per-token metrics."""

    def __init__(
        self,
        src_ids: Sequence,
        max_new_tokens: Optional[int] = None,
        req_id: Optional[str] = None,
        callback: Optional[Callable[["Request"], Any]] = None,
    ):
        self.req_id = req_id if req_id is not None else f"r{next(_req_counter)}"
        self.src_ids = list(src_ids)
        self.max_new_tokens = max_new_tokens
        self.callback = callback
        self.tokens: Optional[List[int]] = None
        self.error: Optional[str] = None
        self.t_submit: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.token_times: List[float] = []
        self._resume = None  # engine preemption save-state
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until finalized (bounded); True when done."""
        return self._event.wait(timeout)

    def result(self) -> List[int]:
        """Generated tokens; raises on a rejected/failed request."""
        if not self._event.is_set():
            raise RuntimeError(f"request {self.req_id} not finished")
        if self.error is not None:
            raise RuntimeError(f"request {self.req_id}: {self.error}")
        return list(self.tokens or [])

    def __repr__(self) -> str:  # pragma: no cover
        state = "done" if self.done() else "pending"
        return f"Request({self.req_id}, {state}, err={self.error!r})"


class ServingScheduler:
    """Request queue + continuous-batching step loop over one engine."""

    def __init__(
        self,
        engine,
        *,
        clock=time.perf_counter,
        sleep=time.sleep,
        idle_poll_s: float = 0.02,
        stats=None,
    ):
        from paddle_tpu.utils.timers import global_stats

        self._engine = engine
        self._clock = clock
        self._sleep = sleep  # injectable per the C306 discipline
        self._idle_poll_s = idle_poll_s
        self._stats = stats if stats is not None else global_stats
        self._q: "queue.Queue[Request]" = queue.Queue()
        self._deliver_q: "queue.Queue[Request]" = queue.Queue()
        self._stop = threading.Event()
        self._lock = make_lock("serving-scheduler")
        self._closed = False  # guarded by _lock
        self._step_thread = threading.Thread(
            target=self._loop, name=THREAD_PREFIX + "serve-step", daemon=True
        )
        self._deliver_thread = threading.Thread(
            target=self._delivery_loop,
            name=THREAD_PREFIX + "serve-deliver",
            daemon=True,
        )
        self._step_thread.start()
        self._deliver_thread.start()

    # -- client surface --------------------------------------------------
    def submit(self, request: Request) -> Request:
        """Enqueue a request (any thread).  The ``nan_request`` chaos point
        fires here — a poisoned submission must be caught by validation on
        the step thread, not crash the batch."""
        if chaos.fire("nan_request"):
            request.src_ids = list(request.src_ids) + [float("nan")]
        request.t_submit = self._clock()
        # the put rides INSIDE the closed-check critical section so close()
        # (which sets _closed under this lock, then stops and drains) can
        # never miss a request that passed the check — an unbounded
        # queue.Queue.put never blocks, so nothing sleeps under the lock
        with self._lock:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            self._q.put(request)  # lock: allow[C304] UNBOUNDED queue — put never blocks; the hold closes the submit-vs-close race (close sets _closed and drains under the same lock ordering)
        self._stats.incr("serving/submitted")
        return request

    def generate(self, src_ids, max_new_tokens: Optional[int] = None,
                 timeout: float = 60.0) -> List[int]:
        """Submit-and-wait convenience: tokens, or raises on reject/timeout."""
        r = self.submit(Request(src_ids, max_new_tokens))
        if not r.wait(timeout):
            raise TimeoutError(f"request {r.req_id} not served in {timeout}s")
        return r.result()

    def close(self, timeout: float = 10.0) -> None:
        """Stop both threads; outstanding requests finalize with an error so
        no client waits forever.  Safe to call repeatedly."""
        with self._lock:
            self._closed = True
        self._stop.set()
        self._step_thread.join(timeout)
        self._deliver_thread.join(timeout)
        # a submit that raced past the closed check lands here: finalize it
        # (callback inline — the delivery thread is gone) so no client hangs
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if r._event.is_set():
                continue
            r.error = "scheduler closed"
            r.tokens = []
            r.t_done = self._clock()
            r._event.set()
            if r.callback is not None:
                try:
                    r.callback(r)
                except Exception:
                    self._stats.incr("serving/callback_errors")

    def __enter__(self) -> "ServingScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- validation ------------------------------------------------------
    def _validate(self, r: Request) -> Optional[str]:
        """Admission-time request validation: a malformed/poisoned request
        is rejected (error result) instead of poisoning the shared batch."""
        eng = self._engine
        if not r.src_ids:
            return "empty source"
        if len(r.src_ids) > eng.max_src_tokens():
            return (
                f"source length {len(r.src_ids)} exceeds the page budget "
                f"({eng.max_src_tokens()} tokens)"
            )
        for t in r.src_ids:
            f = float(t) if isinstance(t, (int, float, np.floating, np.integer)) else None
            if f is None or not np.isfinite(f) or f != int(f):
                return f"non-integral source token {t!r}"
            if not 0 <= int(f) < eng.src_vocab:
                return f"source token {int(f)} outside vocab [0, {eng.src_vocab})"
        if r.max_new_tokens is not None:
            m = r.max_new_tokens
            f = (
                float(m)
                if isinstance(m, (int, float, np.floating, np.integer))
                else None
            )
            if f is None or not np.isfinite(f) or f != int(f) or int(f) < 1:
                return f"max_new_tokens must be a positive integer, got {m!r}"
        return None

    # -- step thread -----------------------------------------------------
    def _finalize(self, r: Request, error: Optional[str] = None) -> None:
        # idempotent: a crash between engine registration and the waiting-
        # list trim can surface one request on BOTH shutdown paths — it
        # must finalize (and deliver its callback) exactly once
        if r._event.is_set():
            return
        r.t_done = self._clock()
        if error is not None:
            r.error = error
            self._stats.incr("serving/rejected")
        if r.tokens is None:
            r.tokens = []
        r._event.set()  # wait() unblocks NOW, before any callback runs
        if r.callback is not None:
            self._deliver_q.put(r)

    def _drain_submissions(self, waiting: List[Request],
                           block_s: float = 0.0) -> None:
        try:
            got = self._q.get(timeout=block_s) if block_s > 0 else (
                self._q.get_nowait()
            )
        except queue.Empty:
            return
        while True:
            err = self._validate(got)
            if err is not None:
                self._finalize(got, error=err)
            else:
                got.src_ids = [int(t) for t in got.src_ids]
                if got.max_new_tokens is not None:
                    got.max_new_tokens = int(got.max_new_tokens)
                waiting.append(got)
            try:
                got = self._q.get_nowait()
            except queue.Empty:
                return

    def _loop(self) -> None:
        waiting: List[Request] = []  # validated, awaiting slot/pages
        crash: Optional[str] = None
        try:
            while not self._stop.is_set():
                # idle (nothing live, nothing waiting): block briefly on
                # the queue instead of spinning
                idle = not waiting and self._engine.n_live == 0
                self._drain_submissions(
                    waiting, block_s=self._idle_poll_s if idle else 0.0
                )
                if waiting:
                    admitted = self._engine.admit(waiting)
                    if admitted:
                        del waiting[: len(admitted)]
                if self._engine.n_live:
                    for r in self._engine.step():
                        self._finalize(r)
        except Exception as e:  # engine bug: fail loudly, strand NO client
            _log.exception("serving step loop crashed; scheduler closes")
            crash = f"serving loop crashed: {e!r}"
            with self._lock:
                self._closed = True  # further submits raise, not hang
            self._stop.set()
            self._stats.incr("serving/loop_crashes")
        # shutdown: nothing new executes; unblock every outstanding client
        error = crash or "scheduler closed"
        self._drain_submissions(waiting)
        for r in waiting:
            self._finalize(r, error=error)
        try:
            while self._engine.n_live:
                r = self._engine.preempt()
                if r is None:
                    break
                r._resume = None
                self._finalize(r, error=error)
        except Exception:  # a corrupted engine can't block the unblocking
            _log.exception("engine teardown failed; finalizing live slots")
            for s in list(self._engine._slots.values()):
                self._finalize(s.request, error=error)

    # -- delivery thread -------------------------------------------------
    def _delivery_loop(self) -> None:
        while not (self._stop.is_set() and self._deliver_q.empty()):
            try:
                r = self._deliver_q.get(timeout=0.05)
            except queue.Empty:
                continue
            if chaos.fire("serve_slow_client"):
                chaos.hang()  # the slow-consumer drill: only callbacks stall
            try:
                r.callback(r)
            except Exception:  # client bug must not kill delivery
                self._stats.incr("serving/callback_errors")
