"""Continuous-batching scheduler — request queue + step/delivery threads.

The serving loop the reference never had (its capi inference is
call-and-wait, paddle/capi/gradient_machine.h): clients ``submit()``
requests from any thread; ONE step thread owns the
:class:`~paddle_tpu.serving.engine.ServingEngine` and, every iteration,
(1) drains newly submitted requests, validates them (a poisoned request is
REJECTED with an error result — it never reaches the batch), (2) admits a
FIFO prefix into free slots/pages (prefill), and (3) runs one decode step
for every live sequence — sequences admit and retire mid-flight with zero
recompiles (continuous batching).

Overload is a first-class input (ROADMAP item 5), not an accident:

* every request may carry a **deadline** (``Request(deadline_s=...)``,
  or the ``serving_default_deadline_s`` flag); its absolute form
  ``t_deadline = t_submit + deadline_s`` is the SLO the scheduler honors;
* the pre-admission queue is **bounded** (``serving_queue_limit``): a
  submit beyond the bound is REJECTED immediately (``rejected`` status —
  backpressure the client sees now, not a timeout it sees later);
* admission is **deadline-aware**: a request whose predicted queue wait
  (EWMA of recent per-token step time x queued-token depth / slot
  concurrency, plus its own expected service time) already blows its
  deadline is finalized immediately with the distinct ``shed`` status —
  at 2x saturation the plane sheds the infeasible excess and keeps
  serving the SLO-feasible subset instead of collapsing into universal
  timeouts (the shed-not-collapse gate, robustness/scenarios.py);
* abandoned work is **canceled**: ``cancel(req_id)`` (and a timed-out
  ``generate()``) frees the request's slot and pages instead of decoding
  to ``max_new_tokens`` for nobody, and a live request whose deadline
  passes mid-decode is canceled the same way;
* shutdown can be **graceful**: :meth:`drain` stops admitting, finishes
  everything in flight, then closes — the `paddle-tpu serve` SIGTERM
  path; :meth:`close` (the kill path) still finalizes every outstanding
  request with an error so no client waits forever.

Completion is two-phase so a slow client can never stall decoding:
``Request.wait()`` unblocks the moment the STEP thread finalizes the
request; user callbacks run on a separate delivery thread (a slow
callback delays only later callbacks, never the batch).  Chaos points
``nan_request`` (poison an incoming request at submit) and
``serve_slow_client`` (freeze the delivery thread mid-callback) drill
exactly these two isolation boundaries (robustness/chaos.py;
tests/test_serving_e2e.py proves the batch keeps stepping).

Concurrency discipline: both threads are daemon ``paddle-serve-*``
threads joined by :meth:`ServingScheduler.close`; the one shared lock is
built by the :mod:`~paddle_tpu.analysis.lock_sanitizer` factory (armed
drills watch it); every blocking wait is a bounded-timeout poll; clocks
and sleeps are injectable (the C-rules, analysis/concurrency_lint.py).
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu import obs as _obs
from paddle_tpu.analysis.diagnostics import protocol_error
from paddle_tpu.analysis.lock_sanitizer import THREAD_PREFIX, make_lock
from paddle_tpu.robustness import chaos

__all__ = ["Request", "ServingScheduler", "TERMINAL_STATUSES",
           "percentile", "status_counts"]

_log = logging.getLogger("paddle_tpu.serving")

_req_counter = itertools.count()

# terminal request statuses (the disjoint categories every summary/scenario
# reports): served | rejected (validation or queue backpressure) | shed
# (deadline-infeasible before admission) | timeout (canceled: client
# timeout, explicit cancel, or deadline exceeded mid-decode) | closed
# (scheduler shut down underneath it)
_EWMA_DECAY = 0.8  # weight of history in the step-time/token-count EWMAs
# admission headroom on the request's own expected service: service times
# are token-count ragged (p95 runs 2-3x the mean), and admitting a request
# that then times out mid-decode WASTES a slot for its whole residency —
# worse for goodput than shedding it up front
_SERVICE_SAFETY = 1.5


def _parse_class_spec(spec: str) -> dict:
    """Parse a per-class spec string ``"0:0.25,2:1.5"`` (priority ->
    float) — the grammar of ``serving_class_deadline_s`` and
    ``serving_class_shed_slack``."""
    out: dict = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        prio, _, val = part.partition(":")
        out[int(prio)] = float(val)
    return out


def _rung_of(n_live: int) -> int:
    """Concurrency ladder rung of a batch: the smallest power of two
    >= ``n_live`` — the same rung the engine's compiled decode variants
    quantize to, so one EWMA per rung observes one compiled shape."""
    n = max(1, int(n_live))
    return 1 << (n - 1).bit_length()


def percentile(xs, p: float):
    """Nearest-rank percentile (None when empty) — the ONE indexing rule
    every serving/bench/scenario latency metric shares, so p50/p95/p99
    never drift between the CLI summary, the bench and the harness."""
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p * len(xs)))]


# The ONE declared disjoint set of terminal request statuses.  Every
# transition site in the serving planes must land on a member (lint
# P503 cross-checks assignments, status= keywords and comparisons in
# scheduler.py/router.py against this tuple); "pending" is the sole
# transient state.
TERMINAL_STATUSES = ("served", "shed", "rejected", "timeout", "closed")


def status_counts(requests) -> dict:
    """The disjoint status ledger over finalized requests (every summary
    reports exactly these keys, zero-filled)."""
    out = {s: 0 for s in TERMINAL_STATUSES}
    for r in requests:
        out[r.status] = out.get(r.status, 0) + 1
    return out


class Request:
    """One generation request and its result/latency/SLO record.

    ``src_ids``: source token ids; ``max_new_tokens``: per-request decode
    cap (None = the engine's default); ``deadline_s``: end-to-end SLO in
    seconds from submit (None = the ``serving_default_deadline_s`` flag;
    0/unset = no deadline); ``callback(request)`` runs on the delivery
    thread after completion.  ``status`` lands on exactly one of
    served/rejected/shed/timeout/closed.  Timing fields (``t_submit``,
    ``t_admit``, ``t_first_token``, ``t_done``, per-token ``token_times``)
    are stamped by the scheduler/engine clock — the raw material of the
    bench's sustained-req/s and p50/p99 per-token metrics and the
    scenario harness's goodput-under-SLO."""

    def __init__(
        self,
        src_ids: Sequence,
        max_new_tokens: Optional[int] = None,
        req_id: Optional[str] = None,
        callback: Optional[Callable[["Request"], Any]] = None,
        deadline_s: Optional[float] = None,
        beam_size: Optional[int] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
    ):
        self.req_id = req_id if req_id is not None else f"r{next(_req_counter)}"
        self.src_ids = list(src_ids)
        self.max_new_tokens = max_new_tokens
        self.callback = callback
        self.deadline_s = deadline_s
        # priority class: LOWER numbers are more urgent (0 = interactive,
        # 1 = the default, bigger = batch/background).  The scheduler
        # dequeues strict-priority-with-aging and sheds per class; the
        # class label ``p<priority>`` keys the per-class ledger counters
        # and Prometheus labels.
        self.priority = 1 if priority is None else int(priority)
        # conversation/session handle: the fleet router's affinity key —
        # requests sharing a session (and so, in production, a prompt
        # head) concentrate on the engine whose prefix cache already
        # holds their blocks.  Opaque to the single-engine scheduler.
        self.session_id = session_id
        # beam decode as a serving citizen: > 1 routes the request through
        # the engine's paged whole-sequence beam program (one dispatch,
        # best hypothesis in ``tokens`` + its ``beam_score``); None/1 =
        # the continuous greedy/speculative loop
        self.beam_size = beam_size
        self.beam_score: Optional[float] = None
        self.status = "pending"
        self.tokens: Optional[List[int]] = None
        self.error: Optional[str] = None
        self.t_submit: Optional[float] = None
        self.t_deadline: Optional[float] = None  # absolute, set at submit
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.token_times: List[float] = []
        self._resume = None  # engine preemption save-state
        self._event = threading.Event()

    @property
    def class_label(self) -> str:
        """The priority class label (``p0``/``p1``/...) — the ``class``
        dimension of the per-class ledger and Prometheus series."""
        return f"p{self.priority}"

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until finalized (bounded); True when done."""
        return self._event.wait(timeout)

    def result(self) -> List[int]:
        """Generated tokens; raises on a rejected/shed/failed request."""
        if not self._event.is_set():
            raise protocol_error(
                "P509",
                f"result() on request {self.req_id} before it finished",
                source="serving/scheduler.py",
                hint="wait() for the request (it sets the done event) "
                     "before reading result()",
            )
        if self.error is not None:
            raise RuntimeError(f"request {self.req_id}: {self.error}")
        return list(self.tokens or [])

    def __repr__(self) -> str:  # pragma: no cover
        state = self.status if self.done() else "pending"
        return f"Request({self.req_id}, {state}, err={self.error!r})"


class ServingScheduler:
    """Request queue + continuous-batching step loop over one engine."""

    def __init__(
        self,
        engine,
        *,
        clock=time.perf_counter,
        sleep=time.sleep,
        idle_poll_s: float = 0.02,
        queue_limit: Optional[int] = None,
        default_deadline_s: Optional[float] = None,
        priority_aging_s: Optional[float] = None,
        class_deadline_s: Optional[dict] = None,
        class_shed_slack: Optional[dict] = None,
        stats=None,
    ):
        from paddle_tpu.utils import flags as _flags
        from paddle_tpu.utils.timers import global_stats

        self._engine = engine
        self._clock = clock
        self._sleep = sleep  # injectable per the C306 discipline
        self._idle_poll_s = idle_poll_s
        self._stats = stats if stats is not None else global_stats
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else _flags.get_flag("serving_queue_limit")
        )
        self.default_deadline_s = float(
            default_deadline_s if default_deadline_s is not None
            else _flags.get_flag("serving_default_deadline_s")
        )
        # per-class SLO policy: default deadline and shed-safety slack
        # per priority class (flag spec "prio:value,..."), plus the aging
        # rate of the strict-priority-with-aging dequeue — every
        # ``priority_aging_s`` seconds of queue wait promote a request
        # one priority level, so batch traffic ages into urgency instead
        # of starving behind a steady interactive stream (0 = pure
        # strict priority, starvation is the operator's explicit choice)
        self.priority_aging_s = float(
            priority_aging_s if priority_aging_s is not None
            else _flags.get_flag("serving_priority_aging_s")
        )
        self.class_deadline_s = dict(
            class_deadline_s if class_deadline_s is not None
            else _parse_class_spec(_flags.get_flag(
                "serving_class_deadline_s"))
        )
        self.class_shed_slack = dict(
            class_shed_slack if class_shed_slack is not None
            else _parse_class_spec(_flags.get_flag(
                "serving_class_shed_slack"))
        )
        self._q: "queue.Queue[Request]" = queue.Queue()
        self._deliver_q: "queue.Queue[Request]" = queue.Queue()
        self._cancel_q: "queue.Queue[tuple]" = queue.Queue()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._lock = make_lock("serving-scheduler")
        self._closed = False  # guarded by _lock
        self._depth = 0  # pre-admission queue depth; guarded by _lock
        # step-thread-only SLO predictor state (never shared, no lock):
        # per-ladder-rung step-time EWMAs (rung = smallest power of two
        # >= live batch size, the compiled-shape quantization) replace
        # the single global EWMA — a step at batch 8 and a step at batch
        # 1 are different compiled programs with different per-token
        # costs, and folding them into one average mispredicts BOTH
        self._rung_token_s: dict = {}  # rung -> EWMA per-token seconds
        self._ewma_tokens: Optional[float] = None
        self._pending_cancels: dict = {}  # req_id -> (reason, ttl)
        # advisory per-class waiting-depth snapshot (step thread writes a
        # fresh dict each iteration; gauge/stats reads are point-in-time)
        self._class_waiting: dict = {}
        self._class_gauges: dict = {}  # label -> gauge fn (for unregister)
        self._step_thread = threading.Thread(
            target=self._loop, name=THREAD_PREFIX + "serve-step", daemon=True
        )
        self._deliver_thread = threading.Thread(
            target=self._delivery_loop,
            name=THREAD_PREFIX + "serve-deliver",
            daemon=True,
        )
        # live SLO gauges (obs/metrics.py): the PR-12 gated quantities,
        # observable while the run is still going.  Reads are advisory
        # snapshots of step-thread state (int/float loads) — stale by at
        # most one scrape period, never blocking the step loop.  The
        # callbacks are retained so close() unregisters only gauges THIS
        # instance still owns (a newer scheduler may have taken the names).
        from paddle_tpu.obs.metrics import register_gauge

        self._gauges = {
            "paddle_tpu_serving_queue_depth": (
                lambda: self._depth,
                "requests queued ahead of admission (serving_queue_limit "
                "rejects past the bound)",
            ),
            "paddle_tpu_serving_pages_in_use": (
                lambda: self._engine.pages.n_used,
                "HBM blocks held by in-flight sequences "
                "(serving_hbm_budget_mb bounds the pool)",
            ),
            "paddle_tpu_serving_predicted_wait_seconds": (
                lambda: self._predicted_wait_s(self._depth) or 0.0,
                "EWMA-predicted queue wait of a request arriving now — "
                "the shed predictor's own estimate",
            ),
            "paddle_tpu_serving_prefix_cache_hits": (
                lambda: self._engine.prefix_hits,
                "admissions whose full prompt mapped cached blocks — "
                "zero prefill dispatches each (serving_prefix_cache)",
            ),
            "paddle_tpu_serving_prefix_cache_misses": (
                lambda: self._engine.prefix_misses,
                "admissions that prefilled fresh pages (prefix cache "
                "enabled but no full-prompt entry matched)",
            ),
            "paddle_tpu_serving_pages_shared": (
                lambda: self._engine.pages.n_shared,
                "HBM blocks currently mapped by MORE than one page table "
                "(copy-on-write prefix sharing)",
            ),
            "paddle_tpu_serving_spec_accept_rate": (
                lambda: self._engine.spec_accept_rate(),
                "fraction of speculative draft tokens the target model "
                "confirmed (serving_spec_decode; 0.0 until armed)",
            ),
        }
        for name, (fn, help_) in self._gauges.items():
            register_gauge(name, fn, help_)
        self._step_thread.start()
        self._deliver_thread.start()

    # -- client surface --------------------------------------------------
    def submit(self, request: Request) -> Request:
        """Enqueue a request (any thread).  The ``nan_request`` chaos point
        fires here — a poisoned submission must be caught by validation on
        the step thread, not crash the batch.  Backpressure fires here
        too: beyond ``queue_limit`` (or while draining) the request
        finalizes immediately as ``rejected`` instead of queueing."""
        if chaos.fire("nan_request"):
            request.src_ids = list(request.src_ids) + [float("nan")]
        request.t_submit = self._clock()
        if request.deadline_s is None:
            # per-class default first (serving_class_deadline_s), then
            # the global serving_default_deadline_s fallback
            cls_dl = self.class_deadline_s.get(
                int(getattr(request, "priority", 1))
            )
            if cls_dl is not None and cls_dl > 0:
                request.deadline_s = cls_dl
            elif self.default_deadline_s > 0:
                request.deadline_s = self.default_deadline_s
        if request.deadline_s is not None and request.deadline_s > 0:
            request.t_deadline = request.t_submit + float(request.deadline_s)
        # AFTER deadline defaulting: the timeline must show the EFFECTIVE
        # deadline the shed/timeout decisions below will be judged against
        _obs.instant(
            "serving/submit", cat="serving", req=request.req_id,
            src_tokens=len(request.src_ids), deadline_s=request.deadline_s,
        )
        refuse = None
        # the put rides INSIDE the closed-check critical section so close()
        # (which sets _closed under this lock, then stops and drains) can
        # never miss a request that passed the check — an unbounded
        # queue.Queue.put never blocks, so nothing sleeps under the lock
        with self._lock:
            if self._closed:
                raise protocol_error(
                    "P509",
                    f"submit({request.req_id}) on a closed scheduler — "
                    "close() already finalized every outstanding request",
                    source="serving/scheduler.py",
                    hint="submit before close(); a closed scheduler must "
                    "be re-constructed, not reused",
                )
            if self._draining.is_set():
                refuse = "rejected: scheduler draining"
            elif self.queue_limit and self._depth >= self.queue_limit:
                refuse = (
                    f"rejected: queue full ({self._depth} >= "
                    f"queue_limit {self.queue_limit})"
                )
            else:
                self._depth += 1
                self._q.put(request)  # lock: allow[C304] UNBOUNDED queue — put never blocks; the hold closes the submit-vs-close race (close sets _closed and drains under the same lock ordering)
        self._stats.incr("serving/submitted")
        if refuse is not None:
            self._finalize(request, error=refuse, status="rejected")
        return request

    def generate(self, src_ids, max_new_tokens: Optional[int] = None,
                 timeout: float = 60.0,
                 deadline_s: Optional[float] = None) -> List[int]:
        """Submit-and-wait convenience: tokens, or raises on
        reject/shed/timeout.  A timed-out wait CANCELS the in-flight
        request — its slot and pages free immediately instead of decoding
        to ``max_new_tokens`` for a client that already gave up."""
        r = self.submit(Request(src_ids, max_new_tokens,
                                deadline_s=deadline_s))
        if not r.wait(timeout):
            self.cancel(r, reason=f"timeout: client gave up after {timeout}s")
            # bounded grace: the step loop processes the cancel on its next
            # iteration and finalizes the request (frees slot + pages)
            r.wait(10.0)
            raise TimeoutError(f"request {r.req_id} not served in {timeout}s")
        return r.result()

    def cancel(self, request: Union[Request, str],
               reason: str = "timeout: canceled") -> None:
        """Cancel a submitted request by object or ``req_id`` (any
        thread).  The step thread frees its slot/pages and finalizes it
        with ``timeout`` status on its next iteration; already-finished
        requests are untouched."""
        req_id = request.req_id if isinstance(request, Request) else request
        self._cancel_q.put((req_id, reason))

    def export_stats(self) -> dict:
        """One plain-dict snapshot of the SLO gauges — the engine side of
        the fleet router's single typed stats RPC (serving/router.py).
        Same quantities the Prometheus gauges expose, but shipped as one
        wire-codec payload (the ``write_stats_json`` record shape), so the
        router never scrapes text.  Advisory reads of step-thread state,
        exactly like the gauge callbacks: stale by at most one poll."""
        eng = self._engine
        with self._lock:
            depth = self._depth
        return {
            "queue_depth": int(depth),
            "pages_in_use": int(eng.pages.n_used),
            "predicted_wait_s": float(self._predicted_wait_s(depth) or 0.0),
            "est_service_s": float(self._est_service_s() or 0.0),
            "prefix_cache_hits": int(eng.prefix_hits),
            "prefix_cache_misses": int(eng.prefix_misses),
            "pages_shared": int(eng.pages.n_shared),
            "spec_accept_rate": float(eng.spec_accept_rate()),
            "n_live": int(eng.n_live),
            "n_prefilling": int(getattr(eng, "n_prefilling", 0)),
            "n_free_slots": int(eng.n_free_slots),
            "max_slots": int(eng.max_slots),
            "draining": bool(self._draining.is_set()),
            # per-class queue depths + the per-rung service model — the
            # router's dispatch scores stay on the scalar fields above;
            # these ride along for dashboards and the scenario gates
            "class_waiting": dict(self._class_waiting),
            "rung_token_s": {
                str(k): float(v) for k, v in self._rung_token_s.items()
            },
        }

    def drain(self, timeout: float = 60.0) -> bool:
        """Graceful shutdown: stop admitting (further submits are
        rejected), let every already-submitted request finish, then
        close.  Returns True when everything in flight completed within
        ``timeout`` (the `paddle-tpu serve` SIGTERM contract: drain clean
        -> exit 0); on False the close path finalized the stragglers with
        errors."""
        self._draining.set()
        deadline = self._clock() + timeout
        clean = False
        while self._clock() < deadline:
            with self._lock:
                depth = self._depth
            if (depth == 0 and self._engine.n_live == 0
                    and getattr(self._engine, "n_prefilling", 0) == 0
                    and self._deliver_q.empty()):
                clean = True
                break
            if self._stop.is_set():  # crashed loop: close() reports the rest
                break
            self._sleep(0.02)
        self.close()
        return clean

    def close(self, timeout: float = 10.0) -> None:
        """Stop both threads; outstanding requests finalize with an error so
        no client waits forever.  Safe to call repeatedly."""
        from paddle_tpu.obs.metrics import unregister_gauge

        for name, (fn, _help) in self._gauges.items():
            unregister_gauge(name, fn)
        for label, (depth_fn, wait_fn) in self._class_gauges.items():
            unregister_gauge(
                "paddle_tpu_serving_class_queue_depth", depth_fn,
                labels={"class": label},
            )
            unregister_gauge(
                "paddle_tpu_serving_class_predicted_wait_seconds",
                wait_fn, labels={"class": label},
            )
        with self._lock:
            self._closed = True
        self._stop.set()
        self._step_thread.join(timeout)
        self._deliver_thread.join(timeout)
        # a submit that raced past the closed check lands here: finalize it
        # (callback inline — the delivery thread is gone) so no client hangs
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if r._event.is_set():
                continue
            r.error = "scheduler closed"
            r.status = "closed"
            r.tokens = []
            r.t_done = self._clock()
            r._event.set()
            if r.callback is not None:
                try:
                    r.callback(r)
                except Exception:
                    self._stats.incr("serving/callback_errors")

    def __enter__(self) -> "ServingScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- validation ------------------------------------------------------
    def _validate(self, r: Request) -> Optional[str]:
        """Admission-time request validation: a malformed/poisoned request
        is rejected (error result) instead of poisoning the shared batch."""
        eng = self._engine
        if not r.src_ids:
            return "empty source"
        if len(r.src_ids) > eng.max_src_tokens():
            return (
                f"source length {len(r.src_ids)} exceeds the page budget "
                f"({eng.max_src_tokens()} tokens)"
            )
        for t in r.src_ids:
            f = float(t) if isinstance(t, (int, float, np.floating, np.integer)) else None
            if f is None or not np.isfinite(f) or f != int(f):
                return f"non-integral source token {t!r}"
            if not 0 <= int(f) < eng.src_vocab:
                return f"source token {int(f)} outside vocab [0, {eng.src_vocab})"
        if r.max_new_tokens is not None:
            m = r.max_new_tokens
            f = (
                float(m)
                if isinstance(m, (int, float, np.floating, np.integer))
                else None
            )
            if f is None or not np.isfinite(f) or f != int(f) or int(f) < 1:
                return f"max_new_tokens must be a positive integer, got {m!r}"
        p = getattr(r, "priority", 1)
        f = (
            float(p)
            if isinstance(p, (int, float, np.floating, np.integer))
            else None
        )
        if f is None or not np.isfinite(f) or f != int(f) or int(f) < 0:
            return f"priority must be a non-negative integer, got {p!r}"
        if r.beam_size is not None:
            b = r.beam_size
            f = (
                float(b)
                if isinstance(b, (int, float, np.floating, np.integer))
                else None
            )
            if f is None or not np.isfinite(f) or f != int(f) or int(f) < 1:
                return f"beam_size must be a positive integer, got {b!r}"
            if int(f) > eng.trg_vocab:
                return (
                    f"beam_size {int(f)} exceeds the target vocab "
                    f"({eng.trg_vocab} candidates per step)"
                )
        return None

    # -- SLO predictor (step thread only) --------------------------------
    def _token_s_at(self, rung: int) -> Optional[float]:
        """Per-token step time at a concurrency rung: the rung's own
        EWMA, else the NEAREST calibrated rung (log-distance) — a cold
        rung borrows its neighbor's estimate instead of predicting
        blind.  None until any rung calibrates."""
        if not self._rung_token_s:
            return None
        got = self._rung_token_s.get(rung)
        if got is not None:
            return got
        nearest = min(
            self._rung_token_s,
            key=lambda k: (abs(k.bit_length() - rung.bit_length()), k),
        )
        return self._rung_token_s[nearest]

    def _est_service_s(self, rung: Optional[int] = None) -> Optional[float]:
        """Expected wall service time of one request once admitted: EWMA
        generated-token count x the per-token step time AT the rung the
        request will decode in (default: the full house — under queueing
        pressure admission happens into a saturated batch).  None until
        the first decode dispatch calibrates the model (no shedding
        blind)."""
        if rung is None:
            rung = _rung_of(self._engine.max_slots)
        token_s = self._token_s_at(rung)
        if token_s is None:
            return None
        est_tokens = (
            self._ewma_tokens if self._ewma_tokens is not None
            else float(self._engine.default_max_new_tokens)
        )
        return max(est_tokens, 1.0) * token_s

    def _predicted_wait_s(self, n_ahead: int) -> Optional[float]:
        """Predicted queue wait for a request with ``n_ahead`` requests
        queued before it: the backlog drains one admission per service
        completion, ``max_slots`` of which run concurrently."""
        per_req = self._est_service_s()
        if per_req is None:
            return None
        backlog = n_ahead
        if self._engine.n_free_slots == 0:
            # a full house drains first — including slots still held by
            # chunked prefills (occupied but not yet decoding)
            backlog += self._engine.n_live + self._engine.n_prefilling
        return per_req * backlog / max(1, self._engine.max_slots)

    def _eff_priority(self, r: Request, now: float) -> float:
        """Effective priority under aging: every ``priority_aging_s``
        seconds of queue wait promote one level (smaller = served
        sooner).  0 disables aging — pure strict priority."""
        p = float(getattr(r, "priority", 1))
        if self.priority_aging_s > 0 and r.t_submit is not None:
            p -= (now - r.t_submit) / self.priority_aging_s
        return p

    def _n_ahead_of(self, r: Request, waiting: List[Request],
                    now: float) -> int:
        """How many waiting requests dequeue BEFORE ``r`` under the
        priority-with-aging order — the per-class replacement for the
        FIFO queue position the shed predictor used to read."""
        pr = self._eff_priority(r, now)
        n = 0
        for w in waiting:
            wp = self._eff_priority(w, now)
            if wp < pr or (wp == pr and (w.t_submit or 0.0)
                           <= (r.t_submit or 0.0)):
                n += 1
        return n

    def _shed_verdict(self, r: Request, n_ahead: int,
                      now: float) -> Optional[str]:
        """The deadline-aware admission decision: shed when the predicted
        queue wait plus the request's own expected service already lands
        past its deadline.  ``n_ahead`` counts only the requests that
        would dequeue before this one, so a high-priority arrival is
        judged against ITS queue, not the whole backlog — at 2x
        saturation the low classes shed first, by construction."""
        if r.t_deadline is None:
            return None
        wait = self._predicted_wait_s(n_ahead)
        if wait is None:
            return None
        slack = self.class_shed_slack.get(
            int(getattr(r, "priority", 1)), 1.0
        )
        per_req = (self._est_service_s() or 0.0) * _SERVICE_SAFETY * slack
        eta = now + wait + per_req
        if eta > r.t_deadline:
            # the predictor's INPUTS ride the shed instant: a merged
            # timeline answers "why was this request shed" without a repro
            _obs.instant(
                "serving/shed", cat="serving", req=r.req_id,
                predicted_wait_s=round(wait, 6),
                est_service_s=round(per_req, 6),
                n_ahead=n_ahead,
                rung_token_s={
                    str(k): round(v, 6)
                    for k, v in self._rung_token_s.items()
                },
                ewma_tokens=self._ewma_tokens,
                deadline_s=r.deadline_s,
                priority=getattr(r, "priority", 1),
            )
            return (
                f"shed: predicted completion {eta - r.t_submit:.3f}s after "
                f"submit blows the {r.deadline_s:.3f}s deadline "
                f"(queue wait ~{wait * 1e3:.0f} ms ahead of "
                f"{n_ahead} queued)"
            )
        return None

    # -- step thread -----------------------------------------------------
    def _class_wait_s(self, priority: int) -> float:
        """Advisory per-class predicted wait: the backlog a NEW arrival
        of this class would dequeue behind (classes at or above its
        urgency), through the same rung-model predictor — the per-class
        Prometheus gauge callback."""
        ahead = 0
        for label, n in dict(self._class_waiting).items():
            try:
                p = int(label[1:])
            except (ValueError, IndexError):
                continue
            if p <= priority:
                ahead += int(n)
        return float(self._predicted_wait_s(ahead) or 0.0)

    def _snapshot_classes(self, waiting: List[Request]) -> None:
        """Publish the per-class waiting depths (fresh dict per
        iteration — advisory reads see one consistent snapshot) and
        lazily register the per-class labeled gauges the first time a
        class appears (unregistered by close)."""
        snap: dict = {}
        for r in waiting:
            label = getattr(r, "class_label", "p1")
            snap[label] = snap.get(label, 0) + 1
        self._class_waiting = snap
        from paddle_tpu.obs.metrics import register_gauge

        for label in snap:
            if label in self._class_gauges:
                continue
            try:
                prio = int(label[1:])
            except ValueError:
                continue
            depth_fn = (
                lambda lbl=label: int(self._class_waiting.get(lbl, 0))
            )
            wait_fn = (lambda p=prio: self._class_wait_s(p))
            register_gauge(
                "paddle_tpu_serving_class_queue_depth", depth_fn,
                "requests queued ahead of admission, by priority class",
                labels={"class": label},
            )
            register_gauge(
                "paddle_tpu_serving_class_predicted_wait_seconds",
                wait_fn,
                "predicted queue wait of a new arrival, by priority "
                "class (the per-class shed predictor's own estimate)",
                labels={"class": label},
            )
            self._class_gauges[label] = (depth_fn, wait_fn)

    def _finalize(self, r: Request, error: Optional[str] = None,
                  status: Optional[str] = None) -> None:
        # idempotent: a crash between engine registration and the waiting-
        # list trim can surface one request on BOTH shutdown paths — it
        # must finalize (and deliver its callback) exactly once
        if r._event.is_set():
            return
        r.t_done = self._clock()
        if error is not None:
            r.error = error
        r.status = status if status is not None else (
            "served" if r.error is None else "rejected"
        )
        if r.status != "served":
            self._stats.incr("serving/" + r.status)
        # the per-class ledger: serving/class/<label>/<status> counters
        # (EVERY status including served) feed the class-labeled
        # paddle_tpu_serving_requests_total series (obs/metrics.py)
        self._stats.incr(
            f"serving/class/{getattr(r, 'class_label', 'p1')}/{r.status}"
        )
        if r.tokens is None:
            r.tokens = []
        _obs.instant(
            "serving/" + ("done" if r.status == "served" else r.status),
            cat="serving", req=r.req_id, status=r.status,
            tokens=len(r.tokens), error=r.error,
        )
        r._event.set()  # wait() unblocks NOW, before any callback runs
        if r.callback is not None:
            self._deliver_q.put(r)

    def _dec_depth(self, n: int = 1) -> None:
        with self._lock:
            self._depth -= n

    def _drain_submissions(self, waiting: List[Request],
                           block_s: float = 0.0) -> None:
        try:
            got = self._q.get(timeout=block_s) if block_s > 0 else (
                self._q.get_nowait()
            )
        except queue.Empty:
            return
        now = self._clock()
        while True:
            err = self._validate(got)
            shed = None if err is not None else self._shed_verdict(
                got, self._n_ahead_of(got, waiting, now), now
            )
            if err is not None:
                self._finalize(got, error=err, status="rejected")
                self._dec_depth()
            elif shed is not None:
                self._finalize(got, error=shed, status="shed")
                self._dec_depth()
            else:
                got.src_ids = [int(t) for t in got.src_ids]
                if got.max_new_tokens is not None:
                    got.max_new_tokens = int(got.max_new_tokens)
                _obs.instant(
                    "serving/queued", cat="serving", req=got.req_id,
                    n_ahead=len(waiting),
                )
                waiting.append(got)
            try:
                got = self._q.get_nowait()
            except queue.Empty:
                return

    def _process_cancels(self, waiting: List[Request]) -> None:
        """Resolve queued cancellations (step thread): waiting requests
        finalize in place; live/prefilling ones release their slot and
        pages through the engine.  A cancel racing its own submit retries
        for a bounded number of iterations."""
        while True:
            try:
                req_id, reason = self._cancel_q.get_nowait()
            except queue.Empty:
                break
            self._pending_cancels[req_id] = (reason, 200)
        if not self._pending_cancels:
            return
        resolved = []
        for req_id, (reason, ttl) in self._pending_cancels.items():
            hit = None
            for r in waiting:
                if r.req_id == req_id:
                    hit = r
                    waiting.remove(r)
                    self._dec_depth()
                    break
            if hit is None:
                hit = self._engine.cancel_by_id(req_id)
            if hit is not None:
                self._finalize(hit, error=reason, status="timeout")
                resolved.append(req_id)
            elif ttl <= 1:
                resolved.append(req_id)  # unknown/finished id: drop
            else:
                self._pending_cancels[req_id] = (reason, ttl - 1)
        for req_id in resolved:
            self._pending_cancels.pop(req_id, None)

    def _sweep_deadlines(self, waiting: List[Request]) -> None:
        """Expire deadlines: a QUEUED request whose remaining budget can no
        longer cover its expected service is shed before it burns a slot
        (the arrival-time prediction re-checked against reality — queues
        drain slower than predicted under overload); a LIVE request past
        its deadline is canceled — slot and pages free for feasible
        work."""
        now = self._clock()
        floor = (self._est_service_s() or 0.0) * _SERVICE_SAFETY
        expired = [
            r for r in waiting
            if r.t_deadline is not None and now + floor > r.t_deadline
        ]
        for r in expired:
            waiting.remove(r)
            self._dec_depth()
            _obs.instant(
                "serving/shed", cat="serving", req=r.req_id,
                est_service_s=round(floor, 6),
                remaining_budget_s=round(r.t_deadline - now, 6),
                deadline_s=r.deadline_s,
            )
            self._finalize(
                r, error=(
                    "shed: remaining deadline budget "
                    f"{max(0.0, (r.t_deadline - now)) * 1e3:.0f} ms below "
                    "the expected service time"
                    if now <= r.t_deadline
                    else "shed: deadline expired while queued"
                ),
                status="shed",
            )
        for r in list(self._engine.outstanding_requests()):
            if r.t_deadline is not None and now > r.t_deadline:
                if self._engine.cancel(r):
                    self._finalize(
                        r, error="timeout: deadline exceeded mid-decode",
                        status="timeout",
                    )

    def _observe_step(self, dt: float, n_live: int, finished) -> None:
        """Feed the SLO predictor: per-token step time from this dispatch
        folded into ITS concurrency rung's EWMA, generated-token counts
        from the requests it finished."""
        per_token = dt / max(1, getattr(self._engine, "block_steps", 1))
        rung = _rung_of(n_live)
        prev = self._rung_token_s.get(rung)
        self._rung_token_s[rung] = per_token if prev is None else (
            _EWMA_DECAY * prev + (1 - _EWMA_DECAY) * per_token
        )
        for r in finished:
            n = float(len(r.tokens or [])) or 1.0
            self._ewma_tokens = n if self._ewma_tokens is None else (
                _EWMA_DECAY * self._ewma_tokens + (1 - _EWMA_DECAY) * n
            )

    def _loop(self) -> None:
        waiting: List[Request] = []  # validated, awaiting slot/pages
        crash: Optional[str] = None
        try:
            while not self._stop.is_set():
                # idle (nothing live, nothing waiting): block briefly on
                # the queue instead of spinning
                idle = (
                    not waiting and self._engine.n_live == 0
                    and self._engine.n_prefilling == 0
                    and self._cancel_q.empty()
                    and not self._pending_cancels
                )
                self._drain_submissions(
                    waiting, block_s=self._idle_poll_s if idle else 0.0
                )
                self._process_cancels(waiting)
                self._sweep_deadlines(waiting)
                self._snapshot_classes(waiting)
                if waiting:
                    # strict-priority-with-aging dequeue: the engine
                    # admits a strict prefix, so ORDERING the waiting
                    # list IS the dequeue policy (sort is stable —
                    # submit order breaks ties within a class)
                    now = self._clock()
                    waiting.sort(key=lambda r: self._eff_priority(r, now))
                    admitted = self._engine.admit(waiting)
                    if admitted:
                        for r in admitted:
                            _obs.instant(
                                "serving/admit", cat="serving",
                                req=r.req_id,
                                priority=getattr(r, "priority", 1),
                            )
                        del waiting[: len(admitted)]
                        self._dec_depth(len(admitted))
                if self._engine.n_live or self._engine.n_prefilling:
                    traces0 = dict(self._engine.trace_counts)
                    # a step that advanced a chunked-prefill dispatch, or
                    # traced a new compiled variant, spent its wall time on
                    # something other than decode — feeding it to the EWMA
                    # would poison the shed predictor into shedding
                    # feasible requests until the outlier washes out
                    clean_sample = self._engine.n_prefilling == 0
                    n_live0 = self._engine.n_live
                    t0 = self._clock()
                    with _obs.span(
                        "decode_step", cat="serving",
                        live=n_live0,
                        prefilling=self._engine.n_prefilling,
                    ):
                        finished = self._engine.step()
                    dt = self._clock() - t0
                    if clean_sample and self._engine.trace_counts == traces0:
                        self._observe_step(dt, n_live0, finished)
                    for r in finished:
                        self._finalize(r)
        except Exception as e:  # engine bug: fail loudly, strand NO client
            _log.exception("serving step loop crashed; scheduler closes")
            # postmortem BEFORE the teardown below mutates anything: the
            # last N events show what the step loop was doing when it died
            _obs.flight_dump(f"serving-crash-guard: {e!r}")
            crash = f"serving loop crashed: {e!r}"
            with self._lock:
                self._closed = True  # further submits raise, not hang
            self._stop.set()
            self._stats.incr("serving/loop_crashes")
        # shutdown: nothing new executes; unblock every outstanding client
        error = crash or "scheduler closed"
        status = "closed"
        self._drain_submissions(waiting)
        for r in waiting:
            self._finalize(r, error=error, status=status)
        try:
            while self._engine.n_live:
                r = self._engine.preempt()
                if r is None:
                    break
                r._resume = None
                self._finalize(r, error=error, status=status)
            for r in list(self._engine.outstanding_requests()):
                self._engine.cancel(r)
                self._finalize(r, error=error, status=status)
        except Exception:  # a corrupted engine can't block the unblocking
            _log.exception("engine teardown failed; finalizing live slots")
            for r in list(self._engine.outstanding_requests()):
                self._finalize(r, error=error, status=status)

    # -- delivery thread -------------------------------------------------
    def _delivery_loop(self) -> None:
        while not (self._stop.is_set() and self._deliver_q.empty()):
            try:
                r = self._deliver_q.get(timeout=0.05)
            except queue.Empty:
                continue
            if chaos.fire("serve_slow_client"):
                chaos.hang()  # the slow-consumer drill: only callbacks stall
            with _obs.span("deliver", cat="serving", req=r.req_id):
                try:
                    r.callback(r)
                except Exception:  # client bug must not kill delivery
                    self._stats.incr("serving/callback_errors")
