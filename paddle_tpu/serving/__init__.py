"""TPU-native serving plane — continuous (in-flight) batching over a
block-paged decode-state cache.

The reference serves inference through the one-shot C API
(paddle/capi/gradient_machine.h forward + the gserver
RecurrentGradientMachine beam path): one request, one forward, full
recompile cost per new shape.  This package is the "serve millions of
users" replacement (ROADMAP item 1; the Ragged Paged Attention kernel
paper, arXiv:2604.15464, is the blueprint for sharing one compiled decode
step across ragged in-flight sequences; the Gemma-on-TPU serving
comparison, arXiv:2605.25645, sets the metric vocabulary):

* :mod:`~paddle_tpu.serving.pages` — fixed-size HBM blocks + page table
  under an explicit budget (the PR-3 pass-cache accounting discipline);
* :mod:`~paddle_tpu.serving.engine` — prefill/decode split: prefill rides
  the bucketed ``CompileShapeCache``/AOT-cache contract, decode is the
  PR-2 fused attention-GRU step gathering encoder state through the page
  table, ONE compiled step per (slot-rung, page-rung) pair;
* :mod:`~paddle_tpu.serving.scheduler` — request queue + continuous
  batching: sequences admit and retire every step, no recompiles; plus
  the production SLO plane (ISSUE 12): per-request deadlines, bounded-
  queue backpressure, deadline-aware shedding, ``cancel``/``drain``;
* :mod:`~paddle_tpu.serving.router` — the fleet tier (ISSUE 18): an
  SLO-aware, affinity-routing frontend over N engine processes on
  heartbeat leases, speaking the typed wire codec, with a journal-backed
  idempotent request ledger (zero double-serve across router failover)
  and drain-aware rolling restart.
"""

from paddle_tpu.serving.engine import ServingEngine
from paddle_tpu.serving.pages import BlockPagedCache
from paddle_tpu.serving.router import (
    EngineAgent,
    FleetClient,
    Router,
    affinity_key,
    rendezvous_pick,
)
from paddle_tpu.serving.scheduler import (
    Request,
    ServingScheduler,
    percentile,
    status_counts,
)

__all__ = [
    "BlockPagedCache",
    "EngineAgent",
    "FleetClient",
    "Request",
    "Router",
    "ServingEngine",
    "ServingScheduler",
    "affinity_key",
    "percentile",
    "rendezvous_pick",
    "status_counts",
]
