"""Serving-fleet router — SLO-aware, affinity-routing frontend over N engines.

One scheduler over one engine caps the serving plane at single-process
throughput.  This module is ROADMAP item 2's router tier: the reference's
control/data-plane split (Go master over C++ pservers) applied to serving —
a router process owns ADMISSION (deadlines, bounded queue, shed: the PR-12
``ServingScheduler`` semantics lifted one tier) and dispatches over N
``ServingEngine`` processes, each wrapped by an :class:`EngineAgent`.

The planes, and what each reuses:

* **control plane** — engines register on heartbeat LEASES (the master
  cluster plane's worker-registry discipline, ``master.Service
  register_worker/heartbeat/_prune_workers``): an engine silent past
  ``router_lease_timeout_s`` is pruned and its traffic re-routes to the
  survivors — a SIGKILLed engine costs one lease timeout, not the fleet.
* **data plane** — every RPC (register/heartbeat, serve, stats, drain)
  rides the PR-15 typed wire codec through ``master.Server``/``Client``
  (their ``methods=`` whitelists): requests and results are typed arrays,
  hostile frames are structured rejects, and the netem/chaos transport
  injects faults for free.
* **routing policy** — least-predicted-wait: each engine's scheduler
  exports its queue depth, pages in use and EWMA predicted wait over ONE
  typed stats RPC (``ServingScheduler.export_stats``, the
  ``write_stats_json`` record shape — no Prometheus scrape); the router
  polls these and scores candidates as ``predicted_wait + inflight *
  est_service / slots`` (router-side in-flight count covers staleness
  between polls).  PREFIX/SESSION AFFINITY: the request's session id (or
  the PR-17 prefix-cache block-chain key of its prompt) rendezvous-hashes
  to a preferred engine, so shared-prefix traffic concentrates where the
  COW blocks already live — a direct hit-rate multiplier.  Affinity is
  overridden when the preferred engine's score trails the best by more
  than ``router_affinity_slack_s``: affinity must never defeat balance.
* **idempotent ack plane** — a journal-backed request LEDGER (per-request
  ids, JSON lines, append + flush) makes finalization first-writer-wins:
  a duplicate result delivery (an at-least-once re-route whose first
  attempt actually executed, a replayed ack) is counted and DISCARDED —
  zero double-served requests, across router failover too (a new router
  recovering the journal refuses to re-serve finalized ids).
* **drain-aware rolling restart** — :meth:`Router.drain_engine` marks the
  engine excluded-from-routing, forwards the PR-12 ``drain()`` protocol
  over the wire, and waits out the router-side in-flight count, so an
  operator can drain+replace every engine one at a time with the fleet
  never below N-1 serving members.
* **autoscaling hook** — sustained shed rate over a sliding window calls
  a ``spawn`` callback; a sustained-idle fleet above the floor calls
  ``retire`` (the callbacks own process management; the router only
  decides WHEN).

Fast units drive the policy in-process (``address=None``,
``client_factory=`` fakes); the e2e drills and ``bench_fleet_serving``
run real engine subprocesses (`paddle-tpu serve --register`).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import master as _master
from paddle_tpu import obs as _obs
from paddle_tpu.analysis.diagnostics import protocol_error
from paddle_tpu.analysis.lock_sanitizer import THREAD_PREFIX, make_lock
from paddle_tpu.serving.scheduler import (
    TERMINAL_STATUSES, Request, percentile, status_counts,
)

__all__ = [
    "ROUTER_METHODS",
    "ENGINE_METHODS",
    "affinity_key",
    "rendezvous_pick",
    "Router",
    "EngineAgent",
    "FleetClient",
]

_log = logging.getLogger("paddle_tpu.serving")

# RPC whitelists (the master ``_METHODS`` discipline, one per plane):
# engines + operators call the router's surface; the router calls the
# engine agent's.  Anything else is a structured reject, never a dispatch.
ROUTER_METHODS = (
    "register_engine", "heartbeat", "deregister_engine", "live_engines",
    "serve", "fleet_stats", "drain_engine", "ping",
)
ENGINE_METHODS = ("serve", "stats", "drain", "ping")

# terminal statuses the fleet ledger counts — a REFERENCE to the
# scheduler's declared disjoint set, never a copied literal (lint P503
# flags any parallel status-set literal that drifts from the declaration)
_TERMINAL = TERMINAL_STATUSES


def affinity_key(src_ids: Sequence, session_id: Optional[str] = None,
                 block_tokens: int = 16) -> Optional[str]:
    """The affinity-routing key of a request: its ``session_id`` when
    present (conversation stickiness), else the PREFIX BLOCK-CHAIN key of
    the prompt — the PR-17 prefix-cache arithmetic (chained per-block
    hashes over whole ``block_tokens`` blocks) with a process-independent
    hash, so every router incarnation maps the same prompt head to the
    same engine.  Prompts shorter than one block key on their full
    tokens; a malformed prompt (validation will reject it) keys None."""
    if session_id:
        return f"sess:{session_id}"
    try:
        toks = [int(t) for t in src_ids]
    except (TypeError, ValueError, OverflowError):
        return None
    if not toks:
        return None
    head = toks[:block_tokens * max(1, len(toks) // block_tokens)] or toks
    h = 0
    for b in range(0, len(head), block_tokens):
        block = head[b:b + block_tokens]
        h = zlib.crc32(",".join(map(str, block)).encode(), h)
    return f"blk:{h:08x}"


def rendezvous_pick(key: str, engine_ids: Sequence[str]) -> Optional[str]:
    """Highest-random-weight (rendezvous) choice of the preferred engine
    for ``key``: stable per (key, engine set), and an engine joining or
    leaving only moves the keys that hashed to it — no global reshuffle
    of warm prefix caches."""
    if not engine_ids:
        return None
    return max(
        engine_ids,
        key=lambda e: (zlib.crc32(f"{key}|{e}".encode()), e),
    )


class _EngineHandle:
    """Router-side view of one registered engine: address, lease, the
    latest polled stats snapshot, and the router's own in-flight count
    (covers snapshot staleness between polls)."""

    def __init__(self, engine_id: str, address: Tuple[str, int]):
        self.engine_id = engine_id
        self.address = (str(address[0]), int(address[1]))
        self.lease_deadline = 0.0
        self.draining = False
        self.stats: Dict[str, Any] = {}
        self.inflight = 0
        self.served = 0

    def view(self) -> Dict[str, Any]:
        return {
            "engine_id": self.engine_id,
            "address": list(self.address),
            "draining": bool(self.draining),
            "inflight": int(self.inflight),
            "served": int(self.served),
            "stats": dict(self.stats),
        }


class Router:
    """The fleet frontend.  RPC surface = :data:`ROUTER_METHODS` (served
    by ``master.Server`` when ``address`` is given; fast units call the
    methods in-process with ``address=None``).

    ``client_factory(address, call_timeout_s)`` builds the router->engine
    data-plane client (default: ``master.Client`` with the
    :data:`ENGINE_METHODS` whitelist) — injectable, so the policy units
    run against fake engines with scripted stats and no sockets.

    ``journal_path``: append-only JSON-lines routing journal.  Passing a
    path holding a previous incarnation's journal RECOVERS the request
    ledger first — the HA-failover half of the zero-double-serve
    contract."""

    def __init__(
        self,
        *,
        address: Optional[Tuple[str, int]] = ("127.0.0.1", 0),
        authkey: bytes = b"paddle-tpu",
        lease_timeout_s: Optional[float] = None,
        queue_limit: Optional[int] = None,
        default_deadline_s: Optional[float] = None,
        affinity: Optional[bool] = None,
        affinity_slack_s: Optional[float] = None,
        stats_poll_s: Optional[float] = None,
        call_timeout_s: Optional[float] = None,
        journal_path: Optional[str] = None,
        clock=time.monotonic,
        sleep=time.sleep,
        stats=None,
        client_factory: Optional[Callable] = None,
    ):
        from paddle_tpu.utils import flags as _flags
        from paddle_tpu.utils.timers import global_stats

        def _flag(v, name):
            return v if v is not None else _flags.get_flag(name)

        self.lease_timeout_s = float(
            _flag(lease_timeout_s, "router_lease_timeout_s"))
        self.queue_limit = int(_flag(queue_limit, "router_queue_limit"))
        self.default_deadline_s = float(
            _flag(default_deadline_s, "serving_default_deadline_s"))
        self.affinity = bool(_flag(affinity, "router_affinity"))
        self.affinity_slack_s = float(
            _flag(affinity_slack_s, "router_affinity_slack_s"))
        self.stats_poll_s = float(_flag(stats_poll_s, "router_stats_poll_s"))
        self.call_timeout_s = float(
            _flag(call_timeout_s, "router_call_timeout_s"))
        self._block_tokens = int(_flags.get_flag("serving_block_tokens"))
        self._clock = clock
        self._sleep = sleep  # injectable per the C306 discipline
        self._stats = stats if stats is not None else global_stats
        self._authkey = authkey
        self._lock = make_lock("serving-router")
        self._engines: Dict[str, _EngineHandle] = {}
        # req_id -> the FULL terminal result record: a duplicate delivery
        # (an at-least-once client retry whose first attempt executed)
        # gets the original tokens back, not just a refusal
        self._ledger: Dict[str, Dict[str, Any]] = {}
        self._depth = 0  # requests inside admission/dispatch; guarded
        self._latencies_ms: deque = deque(maxlen=4096)
        self._shed_times: deque = deque(maxlen=1024)
        self._closed = False
        self.reroutes = 0
        self.duplicates_discarded = 0
        # autoscaling hook state (set_autoscaler arms it)
        self._scale: Optional[Dict[str, Any]] = None
        self._scale_last = 0.0
        self._client_factory = (
            client_factory if client_factory is not None
            else self._default_client_factory
        )
        # journal: recover BEFORE opening for append — a failed-over
        # router must refuse to double-serve ids its predecessor settled
        self._jlock = make_lock("serving-router-journal")
        self._jfile = None
        if journal_path:
            self._recover_journal(journal_path)
            self._jfile = open(journal_path, "a")
        # federation gauges: fleet size once, per-engine series on join
        from paddle_tpu.obs.metrics import register_gauge

        self._fleet_gauge = lambda: float(len(self._engines))
        register_gauge(
            "paddle_tpu_fleet_engines", self._fleet_gauge,
            "serving engines currently holding a live router lease",
        )
        self._engine_gauges: Dict[str, List] = {}
        self._stop = threading.Event()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name=THREAD_PREFIX + "router-poll",
            daemon=True,
        )
        self._poll_thread.start()
        self._server = None
        if address is not None:
            self._server = _master.Server(
                self, address=address, authkey=authkey,
                methods=ROUTER_METHODS, backlog=128,
            )
            self.address = self._server.address
        else:
            self.address = None

    # -- plumbing ---------------------------------------------------------
    def _default_client_factory(self, address, call_timeout_s):
        return _master.Client(
            tuple(address), authkey=self._authkey,
            methods=ENGINE_METHODS, call_timeout_s=call_timeout_s,
            reconnect_tries=1,
        )

    def _journal(self, rec: Dict[str, Any]) -> None:
        if self._jfile is None:
            return
        with self._jlock:
            try:
                self._jfile.write(json.dumps(rec) + "\n")
                self._jfile.flush()
                os.fsync(self._jfile.fileno())  # lock: allow[C304] ledger ordering: the fsync must serialize with the write under _jlock, else a crash can reorder "done" records and break exactly-once recovery; records are one short line each
            except (OSError, ValueError):
                # a torn journal write must not take routing down; the
                # recovery path tolerates a truncated tail line
                self._stats.incr("fleet/journal_errors")

    def _recover_journal(self, path: str) -> None:
        if not os.path.exists(path):
            return
        recovered = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # truncated tail (torn final write)
                if rec.get("t") == "done" and rec.get("req"):
                    # the journal keeps status, not payload: a failed-over
                    # router refuses to re-serve the id (zero double-serve)
                    # but cannot replay the tokens
                    self._ledger[rec["req"]] = {
                        "req_id": rec["req"],
                        "status": rec.get("status", "served"),
                        "tokens": [],
                        "error": "recovered from journal "
                                 "(result payload not retained)",
                        "engine": rec.get("engine"),
                    }
                    recovered += 1
        if recovered:
            _log.info(
                "router: recovered %d finalized request id(s) from %s",
                recovered, path,
            )

    # -- control plane: the heartbeat-lease engine registry ---------------
    def register_engine(self, engine_id: str, host: str,
                        port: int) -> Dict[str, Any]:
        """Join (or rejoin) the engine registry under a heartbeat lease —
        the master plane's ``register_worker`` discipline.  Idempotent:
        an engine that outlived a router failover just re-registers."""
        from paddle_tpu.obs.metrics import register_gauge

        engine_id = str(engine_id)
        with self._lock:
            self._prune_engines()
            h = self._engines.get(engine_id)
            if h is None or h.address != (str(host), int(port)):
                h = _EngineHandle(engine_id, (host, port))
                self._engines[engine_id] = h
                self._journal({
                    "t": "join", "engine": engine_id,
                    "host": str(host), "port": int(port),
                })
            h.lease_deadline = self._clock() + self.lease_timeout_s
            h.draining = False
            if engine_id not in self._engine_gauges:
                gauges = []
                for family, field, help_ in (
                    ("paddle_tpu_fleet_queue_depth", "queue_depth",
                     "per-engine pre-admission queue depth (federated "
                     "from the engine's typed stats RPC)"),
                    ("paddle_tpu_fleet_pages_in_use", "pages_in_use",
                     "per-engine HBM blocks held by in-flight sequences"),
                    ("paddle_tpu_fleet_predicted_wait_seconds",
                     "predicted_wait_s",
                     "per-engine EWMA-predicted queue wait — the routing "
                     "score's base term"),
                ):
                    fn = (lambda hh=h, ff=field:
                          float(hh.stats.get(ff, 0.0)))
                    register_gauge(fn=fn, name=family, help_=help_,
                                   labels={"engine": engine_id})
                    gauges.append((family, fn))
                self._engine_gauges[engine_id] = gauges
            _obs.instant("fleet/join", cat="serving", engine=engine_id)
            return {
                "timeout_s": self.lease_timeout_s,
                "engines": sorted(self._engines),
            }

    def heartbeat(self, engine_id: str) -> bool:
        """Renew the lease; False = expired (or router failover) — the
        engine must ``register_engine`` again."""
        with self._lock:
            self._prune_engines()
            h = self._engines.get(str(engine_id))
            if h is None:
                return False
            h.lease_deadline = self._clock() + self.lease_timeout_s
            return True

    def deregister_engine(self, engine_id: str) -> bool:
        """Graceful leave (the drain/rolling-restart path): no failure
        event, traffic simply stops routing there."""
        with self._lock:
            return self._drop_engine(str(engine_id), pruned=False)

    def live_engines(self) -> List[str]:
        with self._lock:
            self._prune_engines()
            return sorted(self._engines)

    def ping(self) -> str:
        return "router"

    def _drop_engine(self, engine_id: str, pruned: bool) -> bool:
        """Remove one engine (callers hold the lock)."""
        from paddle_tpu.obs.metrics import unregister_gauge

        h = self._engines.pop(engine_id, None)
        if h is None:
            return False
        for family, fn in self._engine_gauges.pop(engine_id, ()):
            unregister_gauge(family, fn, labels={"engine": engine_id})
        self._journal({"t": "leave", "engine": engine_id, "pruned": pruned})
        _obs.instant(
            "fleet/leave", cat="serving", engine=engine_id, pruned=pruned,
        )
        if pruned:
            self._stats.incr("fleet/engines_pruned")
            _log.warning(
                "router: engine %s lease expired — pruned; traffic "
                "re-routes to %d survivor(s)", engine_id, len(self._engines),
            )
        return True

    def _prune_engines(self) -> None:
        """Expire silent engines NOW (callers hold the lock) — the
        kill-one-of-N path: a dead engine costs one lease timeout."""
        now = self._clock()
        for e in [e for e, h in self._engines.items()
                  if h.lease_deadline < now]:
            self._drop_engine(e, pruned=True)

    # -- routing policy ---------------------------------------------------
    def _score(self, h: _EngineHandle) -> float:
        """Predicted wait of a request routed to ``h`` NOW: the engine's
        own EWMA prediction, plus the router's in-flight count amortized
        over its slots (covers snapshot staleness between polls)."""
        st = h.stats
        per_req = float(st.get("est_service_s", 0.0) or 0.0)
        slots = max(1, int(st.get("max_slots", 1) or 1))
        return float(st.get("predicted_wait_s", 0.0) or 0.0) + (
            h.inflight * per_req / slots
        )

    def pick_engine(self, key: Optional[str] = None,
                    exclude: Sequence[str] = ()) -> Optional[str]:
        """One routing decision: least-predicted-wait over live,
        non-draining engines, with rendezvous affinity for ``key`` unless
        the preferred engine trails the best by more than
        ``affinity_slack_s``.  Returns the engine id (None = no candidate
        — empty fleet, or every engine excluded/draining)."""
        with self._lock:
            self._prune_engines()
            cands = [
                h for e, h in self._engines.items()
                if not h.draining and e not in exclude
            ]
            if not cands:
                return None
            best = min(cands, key=lambda h: (self._score(h), h.engine_id))
            if self.affinity and key is not None and len(cands) > 1:
                pref_id = rendezvous_pick(key, [h.engine_id for h in cands])
                pref = self._engines[pref_id]
                if self._score(pref) <= (
                    self._score(best) + self.affinity_slack_s
                ):
                    return pref_id
            return best.engine_id

    # -- data plane: admission + dispatch ---------------------------------
    def serve(
        self,
        req_id: str,
        src_ids: Sequence,
        max_new_tokens: Optional[int] = None,
        deadline_s: Optional[float] = None,
        beam_size: Optional[int] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Dict[str, Any]:
        """One request through the fleet: dedup (idempotent ack plane) ->
        frontend validation -> bounded-queue admission -> deadline shed ->
        least-predicted-wait/affinity dispatch with transparent re-route
        around a dying engine.  Blocks until terminal; returns the result
        record ``{req_id, status, tokens, error, engine}``.  Runs on the
        caller's thread — ``master.Server`` gives each client connection
        its own handler thread, so concurrency comes free."""
        req_id = str(req_id)
        t0 = self._clock()
        with self._lock:
            prior = self._ledger.get(req_id)
        if prior is not None:
            # already finalized (this incarnation or a journal-recovered
            # predecessor): a client retry must NOT re-serve it — it gets
            # the ORIGINAL result record back, flagged as a duplicate
            self._stats.incr("fleet/duplicate_submits")
            return dict(prior, duplicate=True)
        if deadline_s is None and self.default_deadline_s > 0:
            deadline_s = self.default_deadline_s
        # frontend validation BEFORE any network hop (satellite: reject
        # at the router with the same disjoint ledger semantics)
        err = _validate_frontend(src_ids, max_new_tokens, deadline_s,
                                 beam_size, priority)
        if err is not None:
            return self._finalize(req_id, "rejected", error=err, t0=t0)
        refuse = None
        with self._lock:
            if self._closed:
                refuse = "rejected: router closed"
            elif self.queue_limit and self._depth >= self.queue_limit:
                refuse = (
                    f"rejected: router queue full ({self._depth} >= "
                    f"queue_limit {self.queue_limit})"
                )
            else:
                self._depth += 1
        if refuse is not None:
            return self._finalize(req_id, "rejected", error=refuse, t0=t0)
        try:
            return self._dispatch(
                req_id, src_ids, max_new_tokens, deadline_s, beam_size,
                session_id, priority, t0,
            )
        finally:
            with self._lock:
                self._depth -= 1

    def _dispatch(self, req_id, src_ids, max_new_tokens, deadline_s,
                  beam_size, session_id, priority, t0) -> Dict[str, Any]:
        key = affinity_key(src_ids, session_id, self._block_tokens)
        t_deadline = (
            t0 + float(deadline_s)
            if deadline_s is not None and deadline_s > 0 else None
        )
        tried: set = set()
        attempts = 0
        sweeps = 0
        while True:
            attempts += 1
            engine_id = self.pick_engine(key, exclude=tried)
            if engine_id is None and tried:
                # every live engine failed this request's transport:
                # start over on whatever the registry holds NOW (a
                # replacement may have joined mid-flight).  Found by the
                # interleave explorer (analysis/interleave.py): without
                # the sweep bound + backoff below, a no-deadline request
                # against a leased-but-unreachable engine (partial
                # partition: heartbeats land, the data plane doesn't)
                # re-routed in a ZERO-DELAY infinite loop — no timeout
                # path at all (the P505 hazard, dynamic edition).
                sweeps += 1
                if sweeps > 8:
                    status = (
                        "timeout" if t_deadline is not None else "rejected"
                    )
                    return self._finalize(
                        req_id, status, t0=t0,
                        error="no reachable serving engine (every live "
                              "engine failed transport across "
                              f"{sweeps - 1} full sweeps)",
                    )
                # back off so lease expiry / the deadline can fire
                self._sleep(min(0.05, self.stats_poll_s))
                if (t_deadline is not None
                        and self._clock() >= t_deadline):
                    return self._finalize(
                        req_id, "timeout", t0=t0,
                        error="timeout: every live engine failed "
                              "transport and the deadline passed",
                    )
                tried = set()
                engine_id = self.pick_engine(key)
            if engine_id is None:
                # empty fleet: wait out (bounded by the deadline or one
                # lease timeout) for an engine to (re)register rather
                # than failing the request during a rolling bounce
                wait_until = min(
                    t_deadline if t_deadline is not None else float("inf"),
                    t0 + max(self.lease_timeout_s * 2, 1.0) * attempts,
                )
                if self._clock() >= wait_until or attempts > 8:
                    status = "timeout" if t_deadline is not None else "rejected"
                    return self._finalize(
                        req_id, status, t0=t0,
                        error="no live serving engine (fleet empty)",
                    )
                self._sleep(min(0.05, self.stats_poll_s))
                continue
            with self._lock:
                h = self._engines.get(engine_id)
                if h is None:
                    continue
                # shed at the frontend: the chosen (= least-wait) engine's
                # predicted completion already blows the deadline
                if t_deadline is not None:
                    eta = self._clock() + self._score(h) + float(
                        h.stats.get("est_service_s", 0.0) or 0.0)
                    if h.stats and eta > t_deadline:
                        return self._finalize(
                            req_id, "shed", t0=t0,
                            error=(
                                f"shed: fleet-predicted completion "
                                f"{eta - t0:.3f}s after submit blows the "
                                f"{float(deadline_s):.3f}s deadline"
                            ),
                        )
                h.inflight += 1
                address = h.address
            self._journal({"t": "route", "req": req_id, "engine": engine_id})
            _obs.instant(
                "fleet/route", cat="serving", req=req_id, engine=engine_id,
                attempt=attempts,
            )
            remaining = (
                None if t_deadline is None else t_deadline - self._clock()
            )
            call_timeout = self.call_timeout_s if remaining is None else min(
                self.call_timeout_s, max(remaining, 0.0) + 5.0
            )
            try:
                client = self._client_factory(address, call_timeout)
                try:
                    res = client.serve(
                        req_id, list(src_ids), max_new_tokens,
                        None if deadline_s is None else float(deadline_s),
                        beam_size, session_id,
                        None if priority is None else int(priority),
                    )
                finally:
                    try:
                        client.close()
                    except (OSError, AttributeError):
                        pass
            except (_master.MasterTimeoutError, _master.MasterTransportError,
                    _master.MasterRPCError, OSError, EOFError) as exc:
                # the engine died (or froze) under this request: it will
                # be pruned on lease expiry; re-route NOW.  The attempt
                # may have executed engine-side — the first-writer-wins
                # ledger keeps delivery single either way.
                with self._lock:
                    h2 = self._engines.get(engine_id)
                    if h2 is not None:
                        h2.inflight = max(0, h2.inflight - 1)
                tried.add(engine_id)
                self.reroutes += 1
                self._stats.incr("fleet/reroutes")
                _log.warning(
                    "router: engine %s failed request %s (%s) — "
                    "re-routing", engine_id, req_id, type(exc).__name__,
                )
                if (t_deadline is not None
                        and self._clock() >= t_deadline):
                    return self._finalize(
                        req_id, "timeout", t0=t0,
                        error=f"timeout: engine transport failed and the "
                              f"deadline passed ({exc!r})",
                    )
                continue
            with self._lock:
                h2 = self._engines.get(engine_id)
                if h2 is not None:
                    h2.inflight = max(0, h2.inflight - 1)
                    if res.get("status") == "served":
                        h2.served += 1
            return self._finalize(
                req_id, str(res.get("status", "rejected")),
                tokens=res.get("tokens"), error=res.get("error"),
                engine=engine_id, t0=t0,
                beam_score=res.get("beam_score"),
            )

    def _finalize(self, req_id: str, status: str, *, tokens=None, error=None,
                  engine=None, t0=None, beam_score=None) -> Dict[str, Any]:
        """First-writer-wins terminal record for ``req_id`` — the
        idempotent ack plane.  A second finalization (duplicate result
        delivery, re-route race) is counted and DISCARDED: the ledger
        keeps exactly one terminal status per request id, so nothing is
        ever double-served."""
        if status not in _TERMINAL:
            status = "rejected"
        out = {
            "req_id": req_id, "status": status,
            "tokens": [int(t) for t in tokens] if tokens else [],
            "error": error, "engine": engine,
        }
        if beam_score is not None:
            out["beam_score"] = float(beam_score)
        with self._lock:
            prior = self._ledger.get(req_id)
            if prior is not None:
                self.duplicates_discarded += 1
                self._stats.incr("fleet/duplicate_results")
                return dict(prior, duplicate=True)
            self._ledger[req_id] = out
            if status == "shed":
                self._shed_times.append(self._clock())
            if status == "served" and t0 is not None:
                self._latencies_ms.append((self._clock() - t0) * 1e3)
        self._stats.incr(f"fleet/{status}")
        self._journal({
            "t": "done", "req": req_id, "status": status,
            "engine": engine,
        })
        _obs.instant(
            "fleet/done", cat="serving", req=req_id, status=status,
            engine=engine,
        )
        return out

    # -- federation / observability --------------------------------------
    def fleet_stats(self) -> Dict[str, Any]:
        """The federated fleet snapshot: per-engine gauges (latest typed-
        RPC poll + router-side in-flight), the disjoint request ledger
        (scheduler ``status_counts`` REUSED over the ledger — not a third
        copy), and served-latency percentiles (scheduler ``percentile``,
        same nearest-rank rule as every serving metric)."""
        with self._lock:
            engines = {e: h.view() for e, h in self._engines.items()}
            ledger = status_counts(
                SimpleNamespace(status=rec["status"])
                for rec in self._ledger.values()
            )
            lats = sorted(self._latencies_ms)
            depth = self._depth
            reroutes = self.reroutes
            dups = self.duplicates_discarded
        return {
            "n_engines": len(engines),
            "engines": engines,
            "router_queue_depth": int(depth),
            "ledger": ledger,
            "reroutes": int(reroutes),
            "duplicates_discarded": int(dups),
            "latency_ms": {
                "p50": percentile(lats, 0.50),
                "p95": percentile(lats, 0.95),
                "p99": percentile(lats, 0.99),
            },
        }

    # -- drain-aware rolling restart --------------------------------------
    def drain_engine(self, engine_id: str, timeout_s: float = 30.0) -> bool:
        """Rolling-restart primitive: exclude ``engine_id`` from routing,
        forward the PR-12 ``drain()`` protocol over the wire (the engine
        finishes everything in flight, rejects new admissions), wait out
        the router-side in-flight count, then deregister.  True = clean
        (everything in flight completed)."""
        engine_id = str(engine_id)
        with self._lock:
            h = self._engines.get(engine_id)
            if h is None:
                return False
            h.draining = True
            address = h.address
        _obs.instant("fleet/drain", cat="serving", engine=engine_id)
        clean = False
        try:
            client = self._client_factory(address, timeout_s + 10.0)
            try:
                clean = bool(client.drain(timeout_s))
            finally:
                try:
                    client.close()
                except (OSError, AttributeError):
                    pass
        except (_master.MasterTimeoutError, _master.MasterTransportError,
                _master.MasterRPCError, OSError, EOFError):
            clean = False  # it died instead of draining; lease will expire
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            with self._lock:
                h = self._engines.get(engine_id)
                if h is None or h.inflight == 0:
                    break
            self._sleep(0.02)
        self.deregister_engine(engine_id)
        return clean

    # -- autoscaling hook --------------------------------------------------
    def set_autoscaler(
        self,
        spawn: Optional[Callable[["Router"], Any]] = None,
        retire: Optional[Callable[["Router", str], Any]] = None,
        *,
        shed_rate_threshold: float = 1.0,
        window_s: float = 5.0,
        min_engines: int = 1,
        max_engines: int = 8,
        cooldown_s: float = 5.0,
    ) -> None:
        """Arm the autoscaling hook: sustained shed rate (sheds/s over the
        sliding ``window_s``) above ``shed_rate_threshold`` calls
        ``spawn(router)``; a shed-free window with a fleet above
        ``min_engines`` calls ``retire(router, idlest_engine_id)``.  The
        callbacks own process management (the scenario/ops layer); the
        router only decides WHEN, at most once per ``cooldown_s``."""
        self._scale = {
            "spawn": spawn, "retire": retire,
            "threshold": float(shed_rate_threshold),
            "window_s": float(window_s),
            "min": int(min_engines), "max": int(max_engines),
            "cooldown_s": float(cooldown_s),
        }

    def maybe_autoscale(self, now: Optional[float] = None) -> Optional[str]:
        """One autoscale evaluation (the poll loop calls this; units call
        it directly with a virtual clock).  Returns "spawn"/"retire" when
        a callback fired, else None."""
        cfg = self._scale
        if cfg is None:
            return None
        now = self._clock() if now is None else now
        if now - self._scale_last < cfg["cooldown_s"]:
            return None
        with self._lock:
            n = len(self._engines)
            recent = [t for t in self._shed_times
                      if t >= now - cfg["window_s"]]
            idlest = min(
                (h for h in self._engines.values() if not h.draining),
                key=lambda h: (h.inflight, self._score(h), h.engine_id),
                default=None,
            )
        rate = len(recent) / cfg["window_s"]
        if rate > cfg["threshold"] and n < cfg["max"] and cfg["spawn"]:
            self._scale_last = now
            self._stats.incr("fleet/autoscale_spawns")
            _obs.instant("fleet/autoscale", cat="serving", action="spawn",
                         shed_rate=round(rate, 3))
            try:
                cfg["spawn"](self)
            except Exception:  # noqa: BLE001 — ops callback must not kill routing
                _log.exception("router: autoscale spawn callback failed")
            return "spawn"
        if (rate == 0.0 and n > cfg["min"] and cfg["retire"]
                and idlest is not None and idlest.inflight == 0):
            self._scale_last = now
            self._stats.incr("fleet/autoscale_retires")
            _obs.instant("fleet/autoscale", cat="serving", action="retire",
                         engine=idlest.engine_id)
            try:
                cfg["retire"](self, idlest.engine_id)
            except Exception:  # noqa: BLE001 — ops callback must not kill routing
                _log.exception("router: autoscale retire callback failed")
            return "retire"
        return None

    # -- stats poll loop ---------------------------------------------------
    def _poll_loop(self) -> None:
        """Per-engine stats poll: ONE typed RPC per engine per period
        (scheduler.export_stats over the wire codec).  A failing poll is
        ignored — the lease plane, not the poll, decides liveness."""
        while not self._stop.wait(self.stats_poll_s):
            with self._lock:
                targets = [
                    (e, h.address) for e, h in self._engines.items()
                ]
            for engine_id, address in targets:
                if self._stop.is_set():
                    return
                try:
                    client = self._client_factory(address, 5.0)
                    try:
                        st = client.stats()
                    finally:
                        try:
                            client.close()
                        except (OSError, AttributeError):
                            pass
                except (_master.MasterTimeoutError,
                        _master.MasterTransportError,
                        _master.MasterRPCError, OSError, EOFError):
                    continue
                if not isinstance(st, dict):
                    continue
                with self._lock:
                    h = self._engines.get(engine_id)
                    if h is not None:
                        h.stats = st
                        if st.get("draining"):
                            h.draining = True
            self.maybe_autoscale()

    # -- lifecycle ---------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """The final run record (`paddle-tpu route` writes this via
        ``write_stats_json``): the fleet ledger + latency percentiles."""
        out = self.fleet_stats()
        out["statuses"] = out.pop("ledger")
        return out

    def close(self) -> None:
        from paddle_tpu.obs.metrics import unregister_gauge

        with self._lock:
            if self._closed:
                return
            self._closed = True
            for engine_id in list(self._engines):
                self._drop_engine(engine_id, pruned=False)
        self._stop.set()
        self._poll_thread.join(timeout=10)
        if self._server is not None:
            self._server.close()
            self._server = None
        unregister_gauge("paddle_tpu_fleet_engines", self._fleet_gauge)
        if self._jfile is not None:
            with self._jlock:
                try:
                    self._jfile.close()
                except OSError:
                    pass
                self._jfile = None

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _validate_frontend(src_ids, max_new_tokens, deadline_s,
                       beam_size, priority=None) -> Optional[str]:
    """Router-side admission validation — the subset of the scheduler's
    ``_validate`` that needs no engine (vocab/page bounds re-check
    engine-side): a malformed request is rejected BEFORE paying a network
    hop, with the same disjoint ledger semantics."""
    try:
        n = len(src_ids)
    except TypeError:
        return f"source ids must be a sequence, got {type(src_ids).__name__}"
    if n == 0:
        return "empty source"
    for t in src_ids:
        f = (
            float(t)
            if isinstance(t, (int, float, np.floating, np.integer))
            else None
        )
        if f is None or not np.isfinite(f) or f != int(f) or int(f) < 0:
            return f"non-integral source token {t!r}"
    for name, v in (("max_new_tokens", max_new_tokens),
                    ("beam_size", beam_size)):
        if v is None:
            continue
        f = (
            float(v)
            if isinstance(v, (int, float, np.floating, np.integer))
            else None
        )
        if f is None or not np.isfinite(f) or f != int(f) or int(f) < 1:
            return f"{name} must be a positive integer, got {v!r}"
    if deadline_s is not None:
        f = (
            float(deadline_s)
            if isinstance(deadline_s, (int, float, np.floating, np.integer))
            else None
        )
        if f is None or not np.isfinite(f) or f < 0:
            return (
                f"deadline_s must be a finite non-negative number, got "
                f"{deadline_s!r}"
            )
    if priority is not None:
        f = (
            float(priority)
            if isinstance(priority, (int, float, np.floating, np.integer))
            else None
        )
        if f is None or not np.isfinite(f) or f != int(f) or int(f) < 0:
            return (
                f"priority must be a non-negative integer, got {priority!r}"
            )
    return None


class EngineAgent:
    """One engine process's fleet plumbing: the data-plane RPC surface
    (:data:`ENGINE_METHODS` served by ``master.Server`` over the wire
    codec) wrapping a ``ServingScheduler``, plus the register+heartbeat
    lease loop against the router (``router_addr``; None = data plane
    only, the router is told about us some other way — units do this).

    ``serve`` blocks its (per-connection) handler thread on the
    scheduler: concurrency across requests comes from the server's
    thread-per-connection model, and the scheduler's continuous batching
    does the rest."""

    def __init__(
        self,
        scheduler,
        engine_id: str,
        router_addr: Optional[Tuple[str, int]] = None,
        *,
        address: Tuple[str, int] = ("127.0.0.1", 0),
        authkey: bytes = b"paddle-tpu",
        advertise_host: Optional[str] = None,
        clock=time.monotonic,
        sleep=time.sleep,
        default_wait_s: float = 110.0,
    ):
        self._sched = scheduler
        self.engine_id = str(engine_id)
        self._clock = clock
        self._sleep = sleep  # injectable per the C306 discipline
        self.default_wait_s = float(default_wait_s)
        self._server = _master.Server(
            self, address=address, authkey=authkey, methods=ENGINE_METHODS,
            backlog=128,
        )
        self.address = self._server.address
        self._advertise = (
            advertise_host if advertise_host is not None
            else self.address[0]
        )
        self._stop = threading.Event()
        self.registered = threading.Event()
        self._router_addr = (
            tuple(router_addr) if router_addr is not None else None
        )
        self._authkey = authkey
        self._client = None  # dialed lazily: the engine may outrun the router
        self._hb_thread = None
        if self._router_addr is not None:
            self._hb_thread = threading.Thread(
                target=self._lease_loop,
                name=THREAD_PREFIX + "engine-lease", daemon=True,
            )
            self._hb_thread.start()

    # -- RPC surface (the router calls these) ------------------------------
    def serve(self, req_id, src_ids, max_new_tokens=None, deadline_s=None,
              beam_size=None, session_id=None,
              priority=None) -> Dict[str, Any]:
        """One request end-to-end on this engine: submit to the scheduler,
        wait out finalization (bounded by the deadline + grace), return
        the terminal record.  A request the wait outlives is CANCELED —
        its slot and pages free instead of decoding for a router that
        already re-routed."""
        r = Request(
            src_ids, max_new_tokens, req_id=str(req_id),
            deadline_s=deadline_s, beam_size=beam_size,
            session_id=session_id, priority=priority,
        )
        try:
            self._sched.submit(r)
        except RuntimeError as exc:
            return {
                "req_id": r.req_id, "status": "closed", "tokens": [],
                "error": str(exc), "engine": self.engine_id,
            }
        wait_s = (
            float(deadline_s) + 5.0
            if deadline_s is not None and deadline_s > 0
            else self.default_wait_s
        )
        if not r.wait(wait_s):
            self._sched.cancel(
                r, reason=f"timeout: engine wait exceeded {wait_s:.1f}s",
            )
            r.wait(10.0)
        out = {
            "req_id": r.req_id,
            "status": r.status if r.done() else "timeout",
            "tokens": [int(t) for t in (r.tokens or [])],
            "error": r.error,
            "engine": self.engine_id,
        }
        if r.beam_score is not None:
            out["beam_score"] = float(r.beam_score)
        return out

    def stats(self) -> Dict[str, Any]:
        """The ONE typed stats RPC the router polls: the scheduler's SLO
        gauge snapshot (``write_stats_json`` record shape) + identity."""
        st = self._sched.export_stats()
        st["engine_id"] = self.engine_id
        return st

    def drain(self, timeout_s: float = 30.0) -> bool:
        """The PR-12 drain protocol over the wire: finish everything in
        flight, reject new admissions, then close.  True = clean."""
        return bool(self._sched.drain(float(timeout_s)))

    def ping(self) -> str:
        return self.engine_id

    # -- lease loop ---------------------------------------------------------
    def _lease_loop(self) -> None:
        """Register, then heartbeat at a third of the lease timeout;
        a False heartbeat (lease expired / router failed over) or a
        transport error re-registers with bounded backoff."""
        period = 0.2
        backoff = 0.1
        while not self._stop.is_set():
            try:
                if self._client is None:
                    # lazy dial with backoff: an engine that starts before
                    # its router (or outlives a router bounce) keeps
                    # retrying instead of dying at construction
                    self._client = _master.Client(
                        self._router_addr, authkey=self._authkey,
                        methods=ROUTER_METHODS, call_timeout_s=10.0,
                        reconnect_tries=1,
                    )
                got = self._client.register_engine(
                    self.engine_id, self._advertise, int(self.address[1]),
                )
                period = max(0.05, float(got.get("timeout_s", 1.0)) / 3.0)
                self.registered.set()
                backoff = 0.1
            except (_master.MasterTimeoutError, _master.MasterTransportError,
                    _master.MasterRPCError, OSError, EOFError):
                self.registered.clear()
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 2.0)
                continue
            while not self._stop.wait(period):
                try:
                    if not self._client.heartbeat(self.engine_id):
                        break  # expired: re-register
                except (_master.MasterTimeoutError,
                        _master.MasterTransportError,
                        _master.MasterRPCError, OSError, EOFError):
                    break
            self.registered.clear()

    def close(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        if self._client is not None:
            if deregister:
                try:
                    self._client.deregister_engine(self.engine_id)
                except (_master.MasterTimeoutError,
                        _master.MasterTransportError,
                        _master.MasterRPCError, OSError, EOFError):
                    pass
            try:
                self._client.close()
            except (OSError, EOFError):
                pass
            self._client = None
        self._server.close()

    def __enter__(self) -> "EngineAgent":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FleetClient:
    """Scheduler-shaped client over the router's serve RPC: ``submit``
    returns the (local) ``Request`` immediately; a bounded worker thread
    performs the blocking typed-RPC exchange and finalizes it — callback,
    ``wait()``/``result()``, status, exactly the ``ServingScheduler``
    surface, so the loadgen/scenario/bench harnesses drive a fleet and a
    single engine with the same code."""

    def __init__(
        self,
        router_addr: Tuple[str, int],
        *,
        authkey: bytes = b"paddle-tpu",
        call_timeout_s: Optional[float] = None,
        max_inflight: int = 64,
        clock=time.perf_counter,
    ):
        from paddle_tpu.utils import flags as _flags

        self._addr = tuple(router_addr)
        self._authkey = authkey
        self.call_timeout_s = float(
            call_timeout_s if call_timeout_s is not None
            else _flags.get_flag("router_call_timeout_s")
        )
        self._clock = clock
        self._sem = threading.Semaphore(int(max_inflight))
        self._threads_lock = make_lock("serving-fleet-client")
        self._threads: List[threading.Thread] = []
        self._closed = False

    def submit(self, request: Request) -> Request:
        request.t_submit = self._clock()
        with self._threads_lock:
            if self._closed:
                raise protocol_error(
                    "P509",
                    f"submit({request.req_id}) on a closed FleetClient — "
                    "the client joined its workers and will finalize "
                    "nothing",
                    source="serving/router.py",
                    hint="submit before close(); a drained client must be "
                    "re-constructed, not reused",
                )
            t = threading.Thread(
                target=self._run, args=(request,),
                name=THREAD_PREFIX + "fleet-submit", daemon=True,
            )
            self._threads.append(t)
        t.start()
        return request

    def _run(self, r: Request) -> None:
        self._sem.acquire()
        try:
            client = _master.Client(
                self._addr, authkey=self._authkey, methods=ROUTER_METHODS,
                call_timeout_s=self.call_timeout_s,
            )
            try:
                res = client.serve(
                    r.req_id, list(r.src_ids), r.max_new_tokens,
                    r.deadline_s, r.beam_size, r.session_id,
                    getattr(r, "priority", None),
                )
            finally:
                client.close()
            r.tokens = [int(t) for t in res.get("tokens", [])]
            r.error = res.get("error")
            r.status = str(res.get("status", "rejected"))
            if res.get("beam_score") is not None:
                r.beam_score = float(res["beam_score"])
        except (_master.MasterTimeoutError, _master.MasterTransportError,
                _master.MasterRPCError, OSError, EOFError) as exc:
            r.error = f"router unreachable: {exc!r}"
            r.status = "rejected"
        finally:
            r.t_done = self._clock()
            self._sem.release()
            r._event.set()
            if r.callback is not None:
                try:
                    r.callback(r)
                except Exception:  # noqa: BLE001 — client callback boundary
                    _log.exception("fleet client callback failed")

    def close(self, timeout: float = 30.0) -> None:
        with self._threads_lock:
            self._closed = True
            threads = list(self._threads)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
