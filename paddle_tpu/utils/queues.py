"""Stop-aware bounded queue puts — the one definition of the teardown
contract every background producer in the package follows: never park
forever on a full queue; poll with a timeout and re-check the stop signal,
so close()/abandon can always wake and join the thread
(analysis/concurrency_lint.py C305's runtime counterpart)."""

from __future__ import annotations

import queue as _queue
from typing import Callable

__all__ = ["bounded_put"]


def bounded_put(q: "_queue.Queue", item, stopped: Callable[[], bool],
                timeout: float = 0.1) -> bool:
    """Put ``item`` unless ``stopped()`` turns true first; returns False
    when the producer should exit instead."""
    while not stopped():
        try:
            q.put(item, timeout=timeout)
            return True
        except _queue.Full:
            continue
    return False
