"""Plot training/testing curves from a trainer log — the
``python -m paddle.utils.plotcurve`` tool (reference:
python/paddle/utils/plotcurve.py; the demo train.sh scripts pipe their
training log straight into it).

Reads a log from a file or stdin, extracts ``key=value``-style metrics from
pass/batch lines (both this package's CLI output and the reference's
``AvgCost`` style), and writes a matplotlib PNG (or, without matplotlib, a
plain-text table).

usage: python -m paddle_tpu.utils.plotcurve -i train.log -o plot.png [key ...]
"""

from __future__ import annotations

import argparse
import re
import sys
from typing import Dict, List

# "Pass 3: mean cost 0.123456" (paddle_tpu cli) / "AvgCost=0.123" (reference
# logs) / "cost 0.123" mid-line
_PATTERNS = (
    re.compile(r"Pass\s+(?P<p>\d+):\s+mean\s+(?P<key>\w+)\s+(?P<v>[-\d.eE]+)"),
    re.compile(r"(?P<key>[A-Za-z_][\w/]*)=(?P<v>-?\d+\.?\d*(?:[eE][-+]?\d+)?)"),
    re.compile(r"\b(?P<key>cost)\s+(?P<v>-?\d+\.\d+)"),
)


def parse_log(lines) -> Dict[str, List[float]]:
    curves: Dict[str, List[float]] = {}
    for line in lines:
        for pat in _PATTERNS:
            for m in pat.finditer(line):
                try:
                    v = float(m.group("v"))
                except ValueError:
                    continue
                curves.setdefault(m.group("key"), []).append(v)
            if pat.search(line):
                break
    return curves


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Plot training curves from a paddle-tpu/paddle log."
    )
    ap.add_argument("-i", "--input", default=None,
                    help="log file (default: stdin)")
    ap.add_argument("-o", "--output", default=None,
                    help="output PNG (default: stdout text table)")
    ap.add_argument("--format", default="png")
    ap.add_argument("key", nargs="*",
                    help="metric keys to plot (default: every cost-like key)")
    args = ap.parse_args(argv)

    lines = open(args.input) if args.input else sys.stdin
    curves = parse_log(lines)
    if args.input:
        lines.close()
    keys = args.key or [
        k for k in curves if "cost" in k.lower()
    ] or sorted(curves)
    keys = [k for k in keys if curves.get(k)]
    if not keys:
        print("no metrics found in log", file=sys.stderr)
        return 1

    if args.output:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            print("matplotlib unavailable; writing text table", file=sys.stderr)
        else:
            fig, ax = plt.subplots()
            for k in keys:
                ax.plot(curves[k], label=k)
            ax.set_xlabel("record")
            ax.legend()
            fig.savefig(args.output, format=args.format)
            print(f"wrote {args.output}")
            return 0
    for k in keys:
        vals = curves[k]
        print(f"{k}: n={len(vals)} first={vals[0]:.6g} last={vals[-1]:.6g} "
              f"min={min(vals):.6g}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
