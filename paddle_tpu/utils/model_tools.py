"""Model tooling — the ``python/paddle/utils`` surface that matters on TPU:

* :func:`make_diagram` — graphviz dot rendering of a Topology (reference
  make_model_diagram.py:40 walks the proto; here the typed LayerConf graph).
* :func:`merge_model` / :func:`load_merged_model` — bundle a topology +
  trained parameters into ONE deployable file (reference merge_model.py
  gzips proto + param blobs for the C inference API; here a tar of the
  serialized topology text, a JSON manifest, and the reference-format
  parameter tar so the file also interoperates with Parameters.from_tar).
* :func:`dump_config` — print the resolved topology of a v1 config file
  (reference dump_config.py, protobuf text dump).
"""

from __future__ import annotations

import io
import json
import tarfile
from typing import Optional

from paddle_tpu.core.topology import Topology

__all__ = ["make_diagram", "merge_model", "load_merged_model", "dump_config"]


def _dot_escape(s: str) -> str:
    return s.replace('"', '\\"')


def make_diagram(topology: Topology, dot_file: Optional[str] = None) -> str:
    """Graphviz dot text for the layer graph; writes `dot_file` when given.
    Data layers are boxes, costs are double octagons, everything else an
    ellipse — the reference's visual convention."""
    lines = ["digraph model {", "  rankdir=TB;"]
    for name in topology.order:
        c = topology.layers[name]
        if c.type == "data":
            shape = "box"
        elif "cost" in c.type or c.type in ("cross_entropy", "crf", "multibox_loss"):
            shape = "doubleoctagon"
        else:
            shape = "ellipse"
        label = f"{name}\\n{c.type} [{c.size}]"
        lines.append(
            f'  "{_dot_escape(name)}" [shape={shape}, label="{_dot_escape(label)}"];'
        )
    for name in topology.order:
        for parent in topology.layers[name].inputs:
            lines.append(f'  "{_dot_escape(parent)}" -> "{_dot_escape(name)}";')
    lines.append("}")
    dot = "\n".join(lines)
    if dot_file:
        with open(dot_file, "w") as f:
            f.write(dot)
    return dot


def merge_model(parameters, path: str) -> None:
    """One-file deployment bundle: topology text + manifest + the
    reference-format parameter tar (reference merge_model.py gzips
    proto+params for paddle_capi)."""
    topo_text = parameters.network.topology.serialize()
    manifest = {
        "format": "paddle-tpu-merged-model",
        "version": 1,
        "outputs": list(parameters.network.topology.output_names),
        "params": sorted(parameters.names()),
    }
    buf = io.BytesIO()
    parameters.to_tar(buf)

    def add(tar, name, data: bytes):
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))

    with tarfile.open(path, "w:gz") as tar:
        add(tar, "manifest.json", json.dumps(manifest, indent=1).encode())
        add(tar, "topology.txt", topo_text.encode())
        add(tar, "parameters.tar", buf.getvalue())


def load_merged_model(path: str, parameters) -> dict:
    """Load a merged bundle's parameters into `parameters` (whose topology
    must serialize identically) and return the manifest."""
    with tarfile.open(path, "r:gz") as tar:
        manifest = json.load(tar.extractfile("manifest.json"))
        topo_text = tar.extractfile("topology.txt").read().decode()
        want = parameters.network.topology.serialize()
        if topo_text != want:
            raise ValueError(
                "merged model topology does not match the target parameters' "
                "network (build the same model before loading)"
            )
        parameters.from_tar(io.BytesIO(tar.extractfile("parameters.tar").read()))
    return manifest


def dump_config(config_file: str, config_arg_str: str = "") -> str:
    """Resolved-topology text of a v1 config file (reference
    dump_config.py prints the TrainerConfig proto)."""
    from paddle_tpu.v1_compat import parse_config

    return parse_config(config_file, config_arg_str).serialize()
