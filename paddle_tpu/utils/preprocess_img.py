"""Image-dataset preprocessing — the ``paddle/utils/preprocess_img.py`` +
``preprocess_util.py`` capability (reference: resize_image:25, DiskImage:38,
ImageClassificationDatasetCreater:78; DatasetCreater/DataBatcher in
preprocess_util.py:193-343).

Turns a directory tree of labeled images::

    data_path/train/<label>/*.jpg     (or .png/.bmp/.npy)
    data_path/test/<label>/*.jpg

into shuffled pickled batch files + ``train.list``/``test.list`` + a meta
file holding the label set and the training-set mean image — the on-disk
layout the reference's image demos feed from.  A ``batch_reader`` bridges
the batch files into the reader/DataFeeder plane (CHW float vectors, the
v1 "paddle format").
"""

from __future__ import annotations

import os
import pickle
import random
from typing import List, Optional, Sequence, Tuple

import numpy as np

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def resize_image(img, target_size: int):
    """Resize a PIL image so its SHORT side equals target_size (reference
    preprocess_img.resize_image keeps aspect ratio the same way)."""
    w, h = img.size
    if w < h:
        nw, nh = target_size, max(1, int(round(h * target_size / w)))
    else:
        nw, nh = max(1, int(round(w * target_size / h))), target_size
    return img.resize((nw, nh))


def _center_crop(arr: np.ndarray, size: int) -> np.ndarray:
    h, w = arr.shape[:2]
    top = max(0, (h - size) // 2)
    left = max(0, (w - size) // 2)
    return arr[top : top + size, left : left + size]


class DiskImage:
    """One on-disk image: load, resize to target, expose the flattened CHW
    float vector (reference DiskImage.convert_to_paddle_format)."""

    def __init__(self, path: str, target_size: int, color: bool = True):
        self.path = path
        self.target_size = target_size
        self.color = color

    def convert_to_array(self) -> np.ndarray:
        if self.path.endswith(".npy"):
            arr = np.load(self.path)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            if arr.shape[0] != self.target_size or arr.shape[1] != self.target_size:
                if arr.dtype != np.uint8:
                    raise ValueError(
                        f"{self.path}: non-uint8 .npy images must already be "
                        f"{self.target_size}x{self.target_size}, got "
                        f"{arr.shape[:2]}"
                    )
                from PIL import Image

                img = resize_image(
                    Image.fromarray(arr.squeeze(-1) if arr.shape[2] == 1 else arr),
                    self.target_size,
                )
                arr = np.asarray(img)
                if arr.ndim == 2:
                    arr = arr[:, :, None]
        else:
            from PIL import Image

            img = Image.open(self.path)
            img = img.convert("RGB" if self.color else "L")
            img = resize_image(img, self.target_size)
            arr = np.asarray(img)
            if arr.ndim == 2:
                arr = arr[:, :, None]
        out = _center_crop(arr, self.target_size)
        if out.shape[0] != self.target_size or out.shape[1] != self.target_size:
            raise ValueError(
                f"{self.path}: image {arr.shape[:2]} smaller than "
                f"target_size {self.target_size}"
            )
        return out

    def convert_to_paddle_format(self) -> np.ndarray:
        """HWC uint8 -> flattened CHW float32 (the v1 dense_vector layout)."""
        arr = self.convert_to_array().astype(np.float32)
        return arr.transpose(2, 0, 1).reshape(-1)


def list_images(path: str) -> List[str]:
    return sorted(
        os.path.join(path, f)
        for f in os.listdir(path)
        if os.path.splitext(f)[1].lower() in IMAGE_EXTS | {".npy"}
    )


class ImageClassificationDatasetCreater:
    """Scan ``data_path/{train,test}/<label>/`` and emit batch files + lists
    + meta (reference ImageClassificationDatasetCreater.create_batches via
    DataBatcher.create_batches_and_list)."""

    def __init__(
        self,
        data_path: str,
        target_size: int,
        color: bool = True,
        num_per_batch: int = 1024,
        seed: int = 0,
    ):
        self.data_path = data_path
        self.target_size = target_size
        self.color = color
        self.num_per_batch = num_per_batch
        self.seed = seed
        self.output_path = os.path.join(data_path, "batches")

    # -- scanning -------------------------------------------------------
    def _scan_split(
        self, split: str, label_set: Optional[Sequence[str]] = None
    ) -> Tuple[List[np.ndarray], List[int], List[str]]:
        """label_set pins the label->id mapping (the TRAINING label set) so a
        test split with missing/extra label dirs cannot silently remap ids."""
        root = os.path.join(self.data_path, split)
        labels = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)) and not d.startswith(".")
        )
        if label_set is None:
            label_set = labels
        else:
            unknown = sorted(set(labels) - set(label_set))
            if unknown:
                raise ValueError(
                    f"{split} split has labels {unknown} absent from the "
                    f"training label set {list(label_set)}"
                )
        label_id = {lab: i for i, lab in enumerate(label_set)}
        imgs: List[np.ndarray] = []
        ids: List[int] = []
        for lab in labels:
            for f in list_images(os.path.join(root, lab)):
                imgs.append(
                    DiskImage(f, self.target_size, self.color)
                    .convert_to_paddle_format()
                )
                ids.append(label_id[lab])
        return imgs, ids, list(label_set)

    def _write_batches(
        self, split: str, imgs: Sequence[np.ndarray], ids: Sequence[int]
    ) -> List[str]:
        order = list(range(len(imgs)))
        random.Random(self.seed).shuffle(order)
        paths = []
        os.makedirs(self.output_path, exist_ok=True)
        for bi in range(0, len(order), self.num_per_batch):
            sel = order[bi : bi + self.num_per_batch]
            path = os.path.join(
                self.output_path, f"{split}_batch_{bi // self.num_per_batch:03d}"
            )
            with open(path, "wb") as f:
                pickle.dump(
                    {
                        "images": np.stack([imgs[i] for i in sel]),
                        "labels": np.asarray([ids[i] for i in sel], np.int32),
                    },
                    f,
                )
            paths.append(path)
        list_file = os.path.join(self.data_path, f"{split}.list")
        with open(list_file, "w") as f:
            f.write("\n".join(paths) + "\n")
        return paths

    # -- entry ----------------------------------------------------------
    def create_batches(self) -> dict:
        """Process both splits; returns the meta dict (also pickled to
        ``batches/batches.meta`` — label set, mean image, geometry)."""
        tr_imgs, tr_ids, labels = self._scan_split("train")
        self._write_batches("train", tr_imgs, tr_ids)
        te_dir = os.path.join(self.data_path, "test")
        if os.path.isdir(te_dir):
            te_imgs, te_ids, _ = self._scan_split("test", label_set=labels)
            self._write_batches("test", te_imgs, te_ids)
        meta = {
            "label_names": labels,
            "mean_image": np.mean(np.stack(tr_imgs), axis=0),
            "target_size": self.target_size,
            "color": self.color,
            "img_size": tr_imgs[0].shape[0],
        }
        os.makedirs(self.output_path, exist_ok=True)
        with open(os.path.join(self.output_path, "batches.meta"), "wb") as f:
            pickle.dump(meta, f)
        return meta


def load_meta(data_path: str) -> dict:
    with open(os.path.join(data_path, "batches", "batches.meta"), "rb") as f:
        return pickle.load(f)  # wire: allow[A206] meta file this module itself wrote to local disk in process_all (v1 preprocess format parity)


def batch_reader(list_file: str, meta: Optional[dict] = None):
    """Reader factory over a train.list/test.list of batch files, yielding
    (image_vector, label) with optional mean subtraction — feeds
    paddle.batch/DataFeeder like the reference's image providers."""

    def reader():
        with open(list_file) as f:
            paths = [ln.strip() for ln in f if ln.strip()]
        mean = meta["mean_image"] if meta is not None else None
        for p in paths:
            with open(p, "rb") as bf:
                batch = pickle.load(bf)  # wire: allow[A206] batch files this module itself wrote to local disk (v1 preprocess format parity)
            for img, lab in zip(batch["images"], batch["labels"]):
                x = img.astype(np.float32)
                if mean is not None:
                    x = x - mean
                yield x, int(lab)

    return reader
