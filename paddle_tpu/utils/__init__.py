from paddle_tpu.utils.timers import StatSet, global_stats, stat_timer  # noqa: F401
