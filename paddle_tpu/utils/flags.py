"""Global flags plane — the gflags equivalent (reference:
paddle/utils/Flags.{h,cpp} DEFINE_bool/int32/string and the
``paddle.init(use_gpu=..., trainer_count=...)`` surface that forwarded
them).

Typed registry with three override layers, strongest last:
defaults < environment (``PADDLE_TPU_<NAME>``) < explicit ``set_flag`` /
``paddle.init(**kwargs)``.  Unknown names raise — the reference gflags
aborts on unknown flags the same way."""

from __future__ import annotations

import os
from typing import Any, Dict

_DEFS: Dict[str, tuple] = {}  # name -> (type, default, help)
_VALUES: Dict[str, Any] = {}

_ENV_PREFIX = "PADDLE_TPU_"


def define_flag(name: str, default, help_: str = "") -> None:
    """Register a flag.  Re-registering an existing name with the identical
    type+default is an idempotent no-op (module reloads); a CONFLICTING
    re-registration raises — the reference gflags aborts on duplicate
    DEFINE_* the same way.  (Silently letting the last definition win is
    how a plugin's `seed` flag used to steal the trainer's; the self-lint
    rule A204 catches the static cases, this guards the dynamic ones.)"""
    if name in _DEFS:
        old_type, old_default, _ = _DEFS[name]
        if old_type is not type(default) or old_default != default:
            raise ValueError(
                f"flag {name!r} is already defined with default "
                f"{old_default!r} ({old_type.__name__}); re-registering it "
                f"with default {default!r} ({type(default).__name__}) would "
                "silently change behavior — reuse the existing flag or "
                "pick a distinct name"
            )
    _DEFS[name] = (type(default), default, help_)


def _coerce(name: str, value):
    t = _DEFS[name][0]
    if t is bool and isinstance(value, str):
        return value.lower() in ("1", "true", "yes")
    return t(value)


def get_flag(name: str):
    if name not in _DEFS:
        raise KeyError(f"unknown flag {name!r}; defined: {sorted(_DEFS)}")
    if name in _VALUES:
        return _VALUES[name]
    env = os.environ.get(_ENV_PREFIX + name.upper())
    if env is not None:
        return _coerce(name, env)
    return _DEFS[name][1]


def set_flag(name: str, value) -> None:
    if name not in _DEFS:
        raise KeyError(f"unknown flag {name!r}; defined: {sorted(_DEFS)}")
    _VALUES[name] = _coerce(name, value)


def set_flags(**kwargs) -> None:
    for k, v in kwargs.items():
        set_flag(k, v)


def all_flags() -> Dict[str, Any]:
    return {name: get_flag(name) for name in _DEFS}


def reset_flags() -> None:
    _VALUES.clear()


# -- the reference flag set that still means something on TPU ---------------
# (Flags.cpp: use_gpu/trainer_count/log_period/show_parameter_stats_period/
#  seed/beam_size...; pserver networking flags are obsolete — the mesh
#  replaces them.)
define_flag("use_tpu", True, "accepted for surface compat; platform comes from jax")
define_flag("trainer_count", 1, "accepted for surface compat; parallelism comes from the mesh")
define_flag("seed", 0, "global RNG seed")
define_flag("log_period", 100, "log training stats every N batches")
define_flag("show_parameter_stats_period", 0, "log per-parameter stats every N batches (0=off)")
define_flag("beam_size", 5, "default generation beam width")
define_flag("check_nans", False, "enable jax nan-debugging (FP trap equivalent)")
define_flag("compute_dtype", "", "bfloat16 enables mixed precision")
define_flag("profile_dir", "", "write jax profiler traces here when set")
define_flag("use_bucketing", False,
            "length-bucketed feed for variable-length sequence workloads: "
            "the trainer/CLI batch readers route through reader.bucketing."
            "token_budget_batch (batch size scales inversely with bucket "
            "length, tokens/step ~constant) and the DataFeeder pads to the "
            "canonical 16*2^k shape ladder (core.batch.DEFAULT_LADDER) so "
            "jit recompiles stay bounded by the ladder size; reference v1 "
            "configs opt in via this flag with zero config edits")
define_flag("bucketing_token_budget", 0,
            "padded tokens per step for use_bucketing (0 = derive from the "
            "config batch size x the tallest ladder rung of the first "
            "window — the same padded token count the unbucketed feed "
            "would have spent per step)")
define_flag("scan_early_exit", True,
            "recurrent_group scans skip dead steps: when every row of a "
            "step is padding (the batch's true max length sits below the "
            "padded ladder rung), a lax.cond passes the carry through "
            "instead of running the step body — the compiled shape stays "
            "the rung's, the executed trip count shrinks to the bucket "
            "bound")
define_flag("fused_attention_gru", True,
            "recurrent_group decoder steps that match the v1 attention-GRU "
            "idiom (simple_attention + gru_step — the NMT decoder) lower "
            "onto the fused custom-VJP scan core (ops/rnn.py _attgru_core: "
            "state projection + GRU gates share one GEMM, the target-side "
            "input projection hoists out of the scan, weight grads are "
            "post-scan einsums) instead of the generic per-layer scan body; "
            "non-matching steps always use the generic path")
define_flag("cache_pass_in_mem", False,
            "device-resident pass cache (the TPU-native CacheType."
            "CACHE_PASS_IN_MEM, reference PyDataProvider2.cpp:69): epoch 1 "
            "captures every staged batch in its wire form (uint8 stays "
            "uint8 — ~1 byte/px of HBM; normalize stays fused in the step) "
            "and every later epoch replays it from HBM with a reproducible "
            "on-device jax.random.permutation shuffle — zero H2D traffic, "
            "repeat-epoch training goes compute-bound.  @provider(cache="
            "CacheType.CACHE_PASS_IN_MEM) configs opt in with zero edits; "
            "this flag forces it for any reader")
define_flag("data_echo_factor", 1,
            "train each epoch-1 batch N times back-to-back (data echo) so "
            "the H2D-bound first epoch amortizes every transfer N-fold; "
            "1 = off.  Applies whenever the pass cache is enabled")
define_flag("pass_cache_hbm_budget_mb", 4096,
            "PER-DEVICE HBM budget for the device-resident pass cache; a "
            "pass that does not fit falls back to streaming with a "
            "warning.  Sizing rule: budget >= n_samples x bytes_per_sample "
            "in wire form / data-axis size (uint8 224x224x3 ~ 0.15 "
            "MB/image; a batch sharded over n chips counts its largest "
            "per-device shard)")
define_flag("aot_cache_dir", "",
            "persistent AOT executable cache directory (core/aot_cache.py): "
            "every train-step/epoch-program variant the shape ladder "
            "realizes is serialized to disk after its first compile, and a "
            "later process boot DESERIALIZES instead of paying the full XLA "
            "retrace (warm boot).  Entries are keyed by topology "
            "fingerprint, ladder rung, mesh, dtype/donation signature and "
            "jax+backend version — stale or foreign entries are detected "
            "and retraced, never loaded wrong.  Prewarm the full rung set "
            "offline with `paddle-tpu cache warm`; empty = off (today's "
            "retrace path).  jax builds without executable serialization "
            "degrade gracefully to retracing")
define_flag("whole_pass_program", False,
            "whole-pass on-device epoch program: when the device-resident "
            "pass cache holds a sealed single-bucket pass, epochs >= 2 run "
            "as ONE jitted lax.scan over the stacked cache (trainer/step."
            "py make_epoch_program) — O(1) host dispatches per epoch "
            "instead of one per batch, bit-exact against the stepwise "
            "path (sentinel skip semantics included).  Requires "
            "cache_pass_in_mem; falls back to stepwise replay for "
            "bucketed (multi-shape) passes, sample_shuffle, or runs with "
            "a checkpoint/rollback plane (per-step anchors need the host "
            "loop).  Costs one extra stacked copy of the pass in HBM")
define_flag("divergence_sentinel", True,
            "fold a device-side finiteness check of loss + gradient global-"
            "norm into the jitted train step (robustness/): one fused "
            "scalar health flag rides the step's metric outputs, and a "
            "non-finite step is SKIPPED on device (params/opt-state pass "
            "through unchanged) instead of corrupting the run.  The flag "
            "costs one norm reduction per step and no extra host sync")
define_flag("sentinel_check_interval", 1,
            "health-flag fetch cadence for FETCH-FREE dispatch loops "
            "(multi-step scan drivers fold min-health + skip counts per "
            "dispatch, trainer/step.py make_multi_train_step, and check "
            "the fold every N dispatches).  SGD.train ignores this: its "
            "loop syncs on the cost scalar every step anyway, so it "
            "judges every step at zero extra cost")
define_flag("sentinel_skip_limit", 3,
            "consecutive device-skipped (non-finite) steps that declare "
            "divergence and trigger rollback (robustness.recovery)")
define_flag("sentinel_ema_decay", 0.98,
            "decay of the healthy-loss EMA the spike detector compares "
            "against")
define_flag("sentinel_spike_factor", 4.0,
            "a fetched cost above spike_factor x EMA counts as a loss "
            "spike; sentinel_spike_patience consecutive spikes declare "
            "divergence even when every value is finite")
define_flag("sentinel_spike_patience", 3,
            "consecutive EMA spikes before the sentinel declares "
            "divergence")
define_flag("num_sanitizer", False,
            "arm the divergence-localizing numerics sanitizer "
            "(analysis/num_sanitizer.py; env PADDLE_TPU_NUM_SANITIZER "
            "reaches subprocesses): the trainer host-copies each step's "
            "inputs pre-dispatch, and a sentinel-flagged step is re-"
            "executed eqn-by-eqn to name the first non-finite-producing "
            "op (layer + source provenance, input max-abs stats under "
            "StatSet num/<eqn>) in a flight-recorder postmortem.  "
            "Capture costs one host copy per step — debug drills only; "
            "unarmed the train path is untouched")
define_flag("failure_max", 3,
            "rollback retries of the same data window before it is "
            "quarantined and training continues past it — the go/master "
            "processFailedTask discipline (service.go:308) applied to "
            "training-state recovery")
define_flag("checkpoint_period_batches", 50,
            "full-state checkpoint cadence (in batches) when the trainer "
            "runs with checkpoint_dir; each checkpoint is the rollback "
            "anchor AND the preemption/kill -9 resume point, and bounds "
            "the replay window retained on device")
define_flag("chaos", "",
            "chaos fault-point spec, e.g. 'nan_batch@5,kill@12' "
            "(robustness/chaos.py; env PADDLE_TPU_CHAOS reaches "
            "subprocesses) — NEVER set in production")
define_flag("rpc_max_message_mb", 64,
            "hard bound (MB) on one master-RPC wire frame, enforced on "
            "send AND recv (master_wire.py): an over-budget outbound "
            "payload — a too-large gradient tree — fails fast with a "
            "structured WireOversizeError instead of wedging against a "
            "frozen peer's full socket buffer, and an over-budget INBOUND "
            "length prefix is refused before allocation, so a hostile or "
            "damaged frame can never balloon the master's heap")
define_flag("serving_max_slots", 8,
            "in-flight sequence capacity of the serving plane "
            "(serving/engine.py): the continuous-batching decode step is "
            "compiled per slot-count LADDER RUNG up to this many live "
            "sequences; requests beyond it queue")
define_flag("serving_block_tokens", 16,
            "tokens per HBM block of the block-paged decode-state cache "
            "(serving/pages.py).  Must divide the base shape-ladder rung "
            "(16) so every padded source extent splits into whole blocks "
            "and the gathered attention extent stays a ladder rung "
            "(decode outputs bit-identical to the one-shot path)")
define_flag("serving_hbm_budget_mb", 64,
            "PER-DEVICE HBM budget for the block-paged serving cache — "
            "the PR-3 pass-cache accounting discipline applied to decode "
            "state: capacity = budget // bytes_per_block, exhaustion is a "
            "REFUSED admission (request waits in queue), never an OOM.  "
            "Sizing rule: bytes_per_block = block_tokens x (enc 2H + "
            "proj H) x dtype_bytes; a request of S source tokens holds "
            "ceil(S/block_tokens) blocks while in flight")
define_flag("serving_decode_block_steps", 4,
            "tokens decoded per compiled dispatch in the serving plane — "
            "the K-steps-per-dispatch amortization (trainer "
            "make_multi_train_step discipline) applied to decode: an "
            "inner lax.scan emits K tokens per host sync, multiplying "
            "dispatch-bound throughput ~K-fold; admission/retirement "
            "quantize to K-token boundaries (finished rows clamp to EOS "
            "in-graph, so outputs stay bit-identical to the one-shot "
            "path).  1 = sync every token (lowest time-to-first-token)")
define_flag("serving_prefix_cache", False,
            "copy-on-write prefix sharing in the serving plane "
            "(serving/engine.py): finished prompts park their encoder "
            "pages in a refcount-0 LRU pool keyed on token-block hashes + "
            "the engine's topology fingerprint; a request whose FULL "
            "prompt matches maps the same blocks into its page table with "
            "ZERO prefill dispatches (bit-identical — the bi-GRU encoder "
            "reads the whole prompt, so only exact-prompt reuse is sound; "
            "chunked prefills additionally resume mid-prompt from cached "
            "forward-GRU carries).  Blocks free only at refcount 0; "
            "eviction is LRU under the same serving_hbm_budget_mb")
define_flag("serving_spec_decode", False,
            "speculative decoding in the serving plane: an n-gram draft "
            "proposes serving_decode_block_steps tokens and the target "
            "model verifies ALL of them in ONE dispatch (the existing "
            "K-steps compiled shape, drafts as inputs); the emitted "
            "tokens are exactly the greedy argmax chain's — acceptance "
            "only changes how many land per dispatch, never their values "
            "(rejection falls back bit-identically).  Accepted-token "
            "rate rides serving metrics as spec_accept_rate")
define_flag("serving_spec_ngram", 2,
            "context n-gram order of the serving draft proposer: the last "
            "n generated tokens are matched against the request's own "
            "generated history and the continuation after the most recent "
            "match is proposed (prompt-lookup decoding); larger n = "
            "fewer, more precise matches")
define_flag("serving_default_deadline_s", 0.0,
            "default end-to-end deadline (seconds from submit) stamped on "
            "serving requests that carry none of their own; the scheduler "
            "SHEDS a request whose predicted queue wait already blows its "
            "deadline (distinct 'shed' status — at overload the plane "
            "degrades to its SLO-feasible subset instead of collapsing "
            "into universal timeouts) and cancels a live request once its "
            "deadline passes (pages free immediately).  0 = no deadline "
            "(pre-SLO behavior)")
define_flag("serving_queue_limit", 0,
            "bound on requests queued ahead of admission (submitted + "
            "validated-waiting) in the serving scheduler: a submit beyond "
            "it is REJECTED immediately ('rejected: queue full' — open-"
            "loop backpressure, the client retries elsewhere) instead of "
            "growing an unbounded queue whose every occupant times out.  "
            "0 = unbounded (pre-SLO behavior)")
define_flag("serving_prefill_chunk_tokens", 0,
            "chunked prefill: a prompt whose padded source extent exceeds "
            "this many tokens prefills in ladder-rung chunks (carried "
            "bi-GRU state, one bounded dispatch per chunk) interleaved "
            "with decode steps, so a long prompt no longer stalls every "
            "decoding sequence for its whole encoder forward (head-of-"
            "line isolation; outputs stay bit-identical to the one-shot "
            "path).  Must be a multiple of serving_block_tokens and "
            "divide every larger shape-ladder rung.  0 = off (whole-"
            "prompt prefill)")
define_flag("scenario_slo_ms", 0.0,
            "end-to-end latency SLO for the scenario harness "
            "(robustness/scenarios.py): goodput counts requests completed "
            "within this many ms of submit, and per-request deadlines "
            "default to it.  0 = derive from the measured saturation "
            "wave (2.5x its p95 service time, floored at 50 ms)")
define_flag("serving_max_new_tokens", 32,
            "default per-request decode cap of the serving plane (a "
            "request's own max_new_tokens overrides; the generator's "
            "max_length stays the compiled ceiling)")
define_flag("serving_priority_aging_s", 2.0,
            "aging rate of the strict-priority-with-aging dequeue "
            "(serving/scheduler.py): every this-many seconds of queue "
            "wait promote a waiting request one priority level, so "
            "batch-class traffic ages into urgency instead of starving "
            "behind a steady interactive stream; 0 = pure strict "
            "priority (starvation becomes the operator's choice)")
define_flag("serving_class_deadline_s", "",
            "per-class default end-to-end deadlines, 'prio:seconds' "
            "pairs e.g. '0:0.25,2:1.5' (priority 0 is most urgent): a "
            "request of that class submitted without its own deadline "
            "gets the class default; unlisted classes fall back to "
            "serving_default_deadline_s")
define_flag("serving_class_shed_slack", "",
            "per-class multiplier on the shed predictor's service-"
            "safety headroom, 'prio:factor' pairs e.g. '2:2.0': >1 "
            "sheds that class EARLIER under pressure (more headroom "
            "demanded), <1 lets it gamble closer to its deadline; "
            "unlisted classes use 1.0")
define_flag("trace_dir", "",
            "obs plane (paddle_tpu/obs/): arm Chrome-trace export — every "
            "process dumps its span timeline to trace-<role>-<pid>.json "
            "under this directory at exit, and flight-recorder postmortems "
            "land here too.  `paddle-tpu trace merge --dir D` zips the "
            "per-process files into ONE Perfetto-loadable timeline "
            "(clock-skew aligned via the RPC plane's request/response "
            "pairs).  Env PADDLE_TPU_TRACE_DIR reaches subprocess fleets; "
            "empty = no export (the flight-recorder ring still records)")
define_flag("flight_recorder", True,
            "keep the obs span recorder armed at bounded memory (per-"
            "thread rings of trace_ring_events events): SIGUSR1, a firing "
            "chaos point, the divergence sentinel, and the serving "
            "scheduler's crash guard dump the last events to "
            "flight-<pid>.json (under trace_dir, else the system temp "
            "dir) — postmortem timelines survive a kill -9 fleet drill.  "
            "Overhead is gated <= 3% by bench_tracing_overhead; off = "
            "every emit is one attribute read")
define_flag("trace_ring_events", 4096,
            "bounded ring capacity (events) of each thread's obs span "
            "buffer — the flight recorder's memory ceiling is "
            "threads x this x ~100 bytes")
define_flag("metrics_out", "",
            "obs metrics export: periodically snapshot the StatSet plane "
            "+ the registered SLO gauges (serving queue depth, pages in "
            "use, EWMA predicted wait, served/shed/rejected/timeout "
            "ledger) to this file in Prometheus text exposition format "
            "(atomic replace).  Empty = off")
define_flag("metrics_port", 0,
            "serve the same Prometheus exposition on "
            "http://127.0.0.1:<port>/metrics (0 = no endpoint; the "
            "localhost bind is deliberate — this is a scrape surface, "
            "not an API)")
define_flag("metrics_period_s", 5.0,
            "seconds between metrics_out snapshots")
define_flag("use_pallas_attention", False,
            "fused flash-attention Pallas kernel for TPU self-attention: "
            "O(T*dh) attention memory instead of the [T,T] score matrix — "
            "enable for context lengths whose dense scores blow HBM; at "
            "short T XLA's fused dense path is faster")
define_flag("quantized_allreduce", False,
            "block-scaled quantized gradient allreduce (ops/quantize.py "
            "quantized_psum): the data-axis gradient psum rides as an "
            "int8/bf16 payload psum with its f32 block-scale psum beside "
            "it (the N405 structure), cutting per-step allreduce bytes "
            "~4x (EQuARX, arXiv:2506.17615).  OFF (default) keeps the "
            "implicit f32 psum — bit-identical to every prior trajectory")
define_flag("quantize_block_size", 256,
            "elements per block of the block-scaled quantization format "
            "(one f32 max-abs scale per block; shared by the in-graph "
            "allreduce, the elastic wire contributions, and int8 serving "
            "weights).  Smaller blocks track local dynamic range tighter "
            "at more scale overhead (4 bytes per block)")
define_flag("quantize_payload_dtype", "int8",
            "payload dtype of the quantized allreduce: 'int8' (1 "
            "byte/element, rounded into [-127,127]) or 'bfloat16' (2 "
            "bytes/element, no rounding step beyond the bf16 mantissa)")
define_flag("quantize_stochastic_rounding", False,
            "stochastic rounding for int8 quantized-allreduce payloads "
            "(floor(v + u), u~U[0,1), per-shard decorrelated): unbiased "
            "in expectation, trades per-step noise for zero systematic "
            "rounding drift over a long run")
define_flag("elastic_quantized_grads", False,
            "elastic workers submit per-task gradient contributions as "
            "block-scaled (int8 blocks, f32 scales) typed arrays on the "
            "master wire (ops/quantize.py quantize_tree) — ~4x fewer "
            "result-plane bytes per pass; reduce_results dequantizes "
            "BEFORE the sorted-order reduction, so the deterministic-"
            "trajectory contract is unchanged (all workers reduce the "
            "same dequantized bytes).  Env "
            "PADDLE_TPU_ELASTIC_QUANTIZED_GRADS reaches worker "
            "subprocesses")
define_flag("serving_int8_weights", False,
            "weight-only int8 serving decode: the fused decode-weight "
            "bundle's dense matrices live as int8 blocks + f32 scales "
            "and dequantize in-graph per dispatch (~4x smaller resident "
            "weight bytes under serving_hbm_budget_mb -> more concurrent "
            "slots per GB); biases/vectors stay f32, training is "
            "untouched (the certify_precision_plan weight-only ACCEPT "
            "case)")
define_flag("serving_int8_drift_budget", 0.08,
            "max tolerated per-step drift of int8-weight decode vs the "
            "f32 reference, measured as max|logits_int8 - logits_f32| / "
            "max|logits_f32| on a probe batch — the explicit bit-drift "
            "budget the serving bench and tests gate on")
define_flag("router_lease_timeout_s", 2.0,
            "heartbeat-lease timeout of the serving-fleet router's "
            "engine registry (serving/router.py — the master cluster "
            "plane's worker-lease discipline lifted to the serving "
            "tier): an engine silent this long is pruned and its "
            "in-flight requests re-route to the survivors")
define_flag("router_queue_limit", 0,
            "bound on requests concurrently inside the router's "
            "admission/dispatch section (the serving_queue_limit "
            "semantics one tier up): past it a request is REJECTED at "
            "the frontend before paying a network hop; 0 = unbounded")
define_flag("router_stats_poll_s", 0.2,
            "period of the router's per-engine stats poll — one typed "
            "RPC per engine per period (scheduler.export_stats over the "
            "wire codec, not a Prometheus scrape); routing scores read "
            "the latest snapshot")
define_flag("router_affinity", True,
            "prefix/session affinity routing in the fleet router: hash "
            "the request's session id (or its prefix block-chain key) "
            "to a preferred engine by rendezvous hashing, so "
            "shared-prefix traffic concentrates where the COW prefix "
            "cache already holds the blocks.  The preferred engine is "
            "OVERRIDDEN when its predicted wait exceeds the best "
            "engine's by more than router_affinity_slack_s — affinity "
            "must never defeat load balance")
define_flag("router_affinity_slack_s", 0.25,
            "how much worse (seconds of predicted wait) the affinity-"
            "preferred engine may be before the router falls back to "
            "the least-predicted-wait choice")
define_flag("router_call_timeout_s", 120.0,
            "per-request deadline of the router->engine serve RPC "
            "(dial + full decode + reply); requests carrying their own "
            "SLO use min(remaining deadline + grace, this)")
