"""Scoped wall-clock stats — the REGISTER_TIMER equivalent (reference:
paddle/utils/Stat.h:63,114,230 Stat/StatSet/REGISTER_TIMER, printed per
log_period in TrainerInternal.cpp:443).  For on-device profiling use
jax.profiler traces; these timers cover the host-side loop (feed, dispatch,
blocking waits)."""

from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Dict, Iterator


class _Stat:
    __slots__ = ("total", "count", "max", "nonfinite")

    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0
        self.max = 0.0
        self.nonfinite = 0

    def add(self, dt: float) -> None:
        self.total += dt
        self.count += 1
        if dt > self.max:
            self.max = dt

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0


class StatSet:
    """Thread-safe: every mutation (timer/incr/observe) and every read
    (count/summary) takes ``_lock``, so concurrent counters never lose an
    increment (stress-tested in test_lock_sanitizer.py).  The lock stays a
    RAW ``threading.Lock`` deliberately: the lock sanitizer
    (analysis/lock_sanitizer.py) reports held-time stats INTO this class on
    every release — a sanitized StatSet lock would recurse."""

    def __init__(self) -> None:
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats.setdefault(name, _Stat()).add(dt)

    def incr(self, name: str, n: int = 1) -> None:
        """Count-only stat (no wall time) — e.g. the compile-cache hit/miss
        counters (core/compiler.py CompileShapeCache).  Shares the summary /
        print surface with the timers: `count` is the signal, times stay 0."""
        with self._lock:
            self._stats.setdefault(name, _Stat()).count += n

    def observe(self, name: str, value: float) -> None:
        """Value stat: fold a measured scalar (gradient norm, loss EMA)
        into the same summary surface — `total`/`avg`/`max` are over the
        observed values instead of wall seconds.  A non-finite value is
        counted in the stat's own `nonfinite` bucket instead of folding:
        one NaN must not poison the avg/max column the chaos drills (and
        the numerics sanitizer's `num/<eqn>` range stats) assert on."""
        v = float(value)
        with self._lock:
            s = self._stats.setdefault(name, _Stat())
            if math.isfinite(v):
                s.add(v)
            else:
                s.nonfinite += 1

    def count(self, name: str) -> int:
        with self._lock:
            s = self._stats.get(name)
            return s.count if s else 0

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                k: {"total": s.total, "count": s.count, "avg": s.avg,
                    "max": s.max, "nonfinite": s.nonfinite}
                for k, s in self._stats.items()
            }

    def print_all_status(self) -> str:
        """globalStat.printAllStatus() equivalent.  The name column widens
        to the longest stat name (floor 24): names past 24 chars — the
        lock sanitizer's ``lock_held/<name>`` rows, the serving counters —
        used to shear the numeric columns out of alignment."""
        rows = sorted(self.summary().items())
        w = max([24] + [len(k) for k, _ in rows]) + 1
        lines = [
            f"{'name':<{w}}{'count':>8}{'total_s':>12}{'avg_ms':>10}"
            f"{'max_ms':>10}"
        ]
        for k, s in rows:
            lines.append(
                f"{k:<{w}}{s['count']:>8}{s['total']:>12.3f}"
                f"{s['avg'] * 1e3:>10.3f}{s['max'] * 1e3:>10.3f}"
            )
        out = "\n".join(lines)
        print(out)
        return out


global_stats = StatSet()


def stat_timer(name: str):
    return global_stats.timer(name)
