"""Diagnostics: per-layer timing and parameter statistics.

Reference counterparts: the per-layer REGISTER_TIMER_INFO forward/backward
timers of NeuralNetwork.cpp:247,288 and the show_parameter_stats_period
logging of TrainerInternal.cpp:83-110.

Under XLA the jitted step is ONE fused computation, so per-layer wall time
cannot be observed from inside it.  Two complements:

  * every layer traces under ``jax.named_scope("type:name")``
    (core/compiler.py), so ``jax.profiler.trace`` timelines attribute fused
    ops back to layers;
  * :func:`profile_layers` runs the graph layer-at-a-time eagerly with a
    device sync per layer — the debug-mode equivalent of the reference's
    per-layer timers (numbers include dispatch overhead; use for relative
    cost, the profiler for truth).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np


def profile_layers(
    network,
    params,
    batch,
    state=None,
    train: bool = False,
    rng=None,
    repeats: int = 3,
) -> List[Tuple[str, str, float]]:
    """[(layer_name, type, best_ms)] forward cost per layer, eager with a
    sync per layer (reference FwdTimer per layer)."""
    topo = network.topology
    results: List[Tuple[str, str, float]] = []

    # run once through apply() to obtain every layer's output for reuse as
    # the timed layer's inputs (so each layer is timed in isolation)
    outs, _ = network.apply(params, batch, state=state, train=train, rng=rng)

    for name in topo.order:
        conf = topo.layers[name]
        impl = network._impls[name]
        if conf.type in ("data", "step_input", "memory"):
            continue
        # identical param resolution + mixed-precision casts as training
        p, ins = network.resolve_layer_call(
            name, params, [outs[i] for i in conf.inputs]
        )

        def run_once():
            ctx = network.make_context(train=train, rng=rng, state=state)
            ctx.outputs.update(outs)
            out = impl.apply(conf, p, ins, ctx)
            jax.block_until_ready(out.data)
            return out

        run_once()  # compile/warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            run_once()
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        results.append((name, conf.type, best))
    return results


def format_layer_profile(rows: List[Tuple[str, str, float]]) -> str:
    total = sum(r[2] for r in rows)
    lines = [f"{'layer':<32} {'type':<20} {'ms':>9} {'%':>6}"]
    for name, typ, ms in sorted(rows, key=lambda r: -r[2]):
        lines.append(f"{name:<32} {typ:<20} {ms:9.3f} {100 * ms / max(total, 1e-9):6.1f}")
    lines.append(f"{'TOTAL':<32} {'':<20} {total:9.3f}")
    return "\n".join(lines)


def parameter_stats(params) -> Dict[str, Dict[str, float]]:
    """{dotted_name: {min,max,avg,abs_avg,size}} — the
    show_parameter_stats_period payload (TrainerInternal.cpp:83-110)."""
    out: Dict[str, Dict[str, float]] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else k, v)
        else:
            a = np.asarray(node, dtype=np.float64)
            out[prefix] = {
                "min": float(a.min()) if a.size else 0.0,
                "max": float(a.max()) if a.size else 0.0,
                "avg": float(a.mean()) if a.size else 0.0,
                "abs_avg": float(np.abs(a).mean()) if a.size else 0.0,
                "size": int(a.size),
            }

    walk("", params)
    return out


def format_parameter_stats(stats: Dict[str, Dict[str, float]]) -> str:
    lines = [f"{'parameter':<40} {'size':>9} {'avg':>11} {'abs_avg':>11} {'min':>11} {'max':>11}"]
    for name in sorted(stats):
        s = stats[name]
        lines.append(
            f"{name:<40} {s['size']:>9} {s['avg']:>11.4g} {s['abs_avg']:>11.4g} "
            f"{s['min']:>11.4g} {s['max']:>11.4g}"
        )
    return "\n".join(lines)


def gradient_stats(network, params, batch, state=None, rng=None):
    """{layer.param: l2_norm} of d(mean cost)/d(param) — the functional
    replacement for the reference's gradient_printer_evaluator (backward here
    is one jax.grad over the whole network, so per-parameter norms are the
    observable quantity)."""
    import jax.numpy as jnp

    def loss(p):
        c, _ = network.cost(p, batch, state=state, rng=rng, train=True)
        return c

    grads = jax.grad(loss)(params)
    out: Dict[str, float] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else k, v)
        else:
            out[prefix] = float(jnp.linalg.norm(node.astype(jnp.float32)))

    walk("", grads)
    return out
