"""Device profiling + numeric traps.

* :func:`profile` wraps ``jax.profiler.trace``: every layer already runs
  under ``jax.named_scope("type:name")`` (core/compiler.py), so the
  resulting TensorBoard/Perfetto timeline attributes fused XLA ops back to
  layers — the device-side half of the reference's per-layer
  REGISTER_TIMER_INFO (NeuralNetwork.cpp:247,288).  Host-side timers live
  in utils/timers.py, eager per-layer timing in utils/debug.py.

* :func:`enable_nan_checks` is the FP-trap equivalent (the reference
  installs SIGFPE handlers / CHECKs on nan paths): jax re-runs any
  computation that produced a nan un-jitted and raises with the exact
  primitive — combined with the compiler's layer-context notes the error
  names the offending layer.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def profile(logdir: Optional[str] = None) -> Iterator[None]:
    """::

        with paddle.utils.profiler.profile("/tmp/trace"):
            trainer.train(...)

    then `tensorboard --logdir /tmp/trace` (or open the .trace in Perfetto).
    With no argument, the `profile_dir` flag (PADDLE_TPU_PROFILE_DIR) names
    the directory."""
    if logdir is None:
        from paddle_tpu.utils.flags import get_flag

        logdir = get_flag("profile_dir")
        if not logdir:
            raise ValueError(
                "no logdir given and the profile_dir flag is unset"
            )
    # while the device profile is active, every obs host span nests under a
    # jax.profiler.TraceAnnotation of the same name, so the host timeline
    # (obs/tracer.py) and the XLA timeline share a vocabulary.  Injected
    # here so the obs package itself stays jax-free (master.py imports it).
    from paddle_tpu import obs as _obs

    with jax.profiler.trace(logdir):
        _obs.tracer.set_annotation_factory(jax.profiler.TraceAnnotation)
        try:
            yield
        finally:
            _obs.tracer.set_annotation_factory(None)


def start(logdir: str) -> None:
    from paddle_tpu import obs as _obs

    jax.profiler.start_trace(logdir)
    _obs.tracer.set_annotation_factory(jax.profiler.TraceAnnotation)


def stop() -> None:
    from paddle_tpu import obs as _obs

    _obs.tracer.set_annotation_factory(None)
    jax.profiler.stop_trace()


def enable_nan_checks(enable: bool = True) -> None:
    """Trap nans/infs produced by any jitted computation (debug-mode only:
    forces re-execution without jit on failure)."""
    jax.config.update("jax_debug_nans", enable)
