"""recordio: chunked record files + native background prefetch.

The format mirrors the role of the reference's Go recordio (reference:
go/master/service.go:105 partitions datasets by recordio chunk) and the C++
DataProvider's async double-buffer (reference:
paddle/gserver/dataproviders/DataProvider.h):

    chunk := magic:u32 | crc32(body):u32 | body_len:u32 | n_records:u32 | body
    body  := len_i:u32 × n | payload_i × n          (little-endian)

Two interchangeable backends over the same bytes-on-disk: the C++ library
(paddle_tpu/native/recordio.cc, built on demand with g++, threads + ring buffer) and a
pure-Python fallback.  `Prefetcher` always exists; it is native when possible.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import struct
import subprocess
import threading
import time
import queue as _queue
import zlib
from typing import Iterable, List, Optional, Sequence

_MAGIC = 0x7061646C

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# native source ships inside the package (paddle_tpu/native/) so installed
# wheels can build it too
_SRC = os.path.join(_PKG_ROOT, "native", "recordio.cc")
_BUILD_DIR = os.path.join(_PKG_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libpaddle_tpu_io.so")

_lib = None
_lib_tried = False
from paddle_tpu.analysis.lock_sanitizer import make_lock
from paddle_tpu.utils.queues import bounded_put as _bounded_put

_lib_lock = make_lock("io.recordio._lib_lock")


def _load_native():
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        try:
            have_so = os.path.exists(_SO)
            have_src = os.path.exists(_SRC)
            stale = (
                have_so and have_src
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if (not have_so or stale) and have_src:
                os.makedirs(_BUILD_DIR, exist_ok=True)
                # per-pid temp + rename: concurrent processes must never
                # CDLL a half-written .so
                tmp = f"{_SO}.{os.getpid()}.tmp"
                subprocess.run(  # lock: allow[C304] one-time lazy native build; the lock exists to serialize exactly this compile
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", _SRC, "-o", tmp],
                    check=True, capture_output=True,
                )
                os.replace(tmp, _SO)
            elif not have_so:
                return None  # neither a prebuilt .so nor source to build
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError, FileNotFoundError):
            return None
        lib.rio_writer_create.restype = ctypes.c_void_p
        lib.rio_writer_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_reader_open.restype = ctypes.c_void_p
        lib.rio_reader_open.argtypes = [ctypes.c_char_p]
        lib.rio_reader_seek.restype = ctypes.c_int
        lib.rio_reader_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rio_reader_next.restype = ctypes.c_int64
        lib.rio_reader_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.rio_reader_close.restype = None
        lib.rio_reader_close.argtypes = [ctypes.c_void_p]
        lib.rio_scan_chunks.restype = ctypes.c_int64
        lib.rio_scan_chunks.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64,
        ]
        lib.rio_prefetcher_create.restype = ctypes.c_void_p
        lib.rio_prefetcher_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ]
        lib.rio_prefetcher_next.restype = ctypes.c_int64
        lib.rio_prefetcher_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.rio_prefetcher_destroy.restype = None
        lib.rio_prefetcher_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_native() is not None


@dataclasses.dataclass(frozen=True)
class Chunk:
    """One chunk's location inside a recordio file — the master's task unit."""

    path: str
    offset: int
    n_records: int


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

class Writer:
    """Append records (bytes) to a recordio file."""

    def __init__(self, path: str, max_chunk_records: int = 1000,
                 max_chunk_bytes: int = 1 << 20):
        self._path = path
        self._lib = _load_native()
        if self._lib is not None:
            self._h = self._lib.rio_writer_create(
                path.encode(), max_chunk_records, max_chunk_bytes
            )
            if not self._h:
                raise IOError(f"cannot open {path} for writing")
        else:
            self._f = open(path, "wb")
            self._pending: List[bytes] = []
            self._pending_bytes = 0
            self._max_records = max_chunk_records
            self._max_bytes = max_chunk_bytes

    def write(self, record: bytes) -> None:
        if self._lib is not None:
            rc = self._lib.rio_writer_write(self._h, record, len(record))
            if rc != 0:
                raise IOError(f"write failed on {self._path}")
            return
        self._pending.append(bytes(record))
        self._pending_bytes += len(record)
        if (len(self._pending) >= self._max_records
                or self._pending_bytes >= self._max_bytes):
            self._flush()

    def _flush(self) -> None:
        if not self._pending:
            return
        body = b"".join(
            [struct.pack("<I", len(r)) for r in self._pending] + self._pending
        )
        self._f.write(struct.pack("<IIII", _MAGIC, zlib.crc32(body),
                                  len(body), len(self._pending)))
        self._f.write(body)
        self._pending = []
        self._pending_bytes = 0

    def close(self) -> None:
        if self._lib is not None:
            if self._lib.rio_writer_close(self._h) != 0:
                raise IOError(f"close failed on {self._path}")
            return
        self._flush()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

class Reader:
    """Iterate records of one file, optionally from a chunk offset."""

    def __init__(self, path: str, offset: int = 0):
        self._path = path
        self._lib = _load_native()
        if self._lib is not None:
            self._h = self._lib.rio_reader_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open {path}")
            if offset:
                if self._lib.rio_reader_seek(self._h, offset) != 0:
                    self._lib.rio_reader_close(self._h)
                    raise IOError(f"{path}: cannot seek to offset {offset}")
        else:
            self._f = open(path, "rb")
            if offset:
                self._f.seek(offset)
            self._records: List[bytes] = []

    def _load_chunk_py(self) -> bool:
        head = self._f.read(16)
        if len(head) < 16:
            return False
        magic, crc, body_len, n = struct.unpack("<IIII", head)
        if magic != _MAGIC:
            raise IOError(f"{self._path}: bad chunk magic {magic:#x}")
        # Header fields are outside the CRC (it covers the body only), so a
        # crafted n or record length must surface as a corrupt chunk, not an
        # out-of-bounds slice or struct.error.
        if 4 * n > body_len:
            raise IOError(f"{self._path}: corrupt chunk")
        body = self._f.read(body_len)
        if len(body) != body_len or zlib.crc32(body) != crc:
            raise IOError(f"{self._path}: corrupt chunk")
        lens = struct.unpack(f"<{n}I", body[: 4 * n])
        off = 4 * n
        for ln in lens:
            if ln > body_len - off:
                self._records.clear()
                raise IOError(f"{self._path}: corrupt chunk")
            self._records.append(body[off : off + ln])
            off += ln
        return True

    def next(self) -> Optional[bytes]:
        if self._lib is not None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            ln = self._lib.rio_reader_next(self._h, ctypes.byref(out))
            if ln == -1:
                return None
            if ln == -2:
                raise IOError(f"{self._path}: corrupt chunk")
            return ctypes.string_at(out, ln)
        while not self._records:
            if not self._load_chunk_py():
                return None
        return self._records.pop(0)

    def __iter__(self):
        while True:
            r = self.next()
            if r is None:
                return
            yield r

    def close(self) -> None:
        if self._lib is not None:
            self._lib.rio_reader_close(self._h)
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def scan_chunks(path: str) -> List[Chunk]:
    """Chunk index of a file — what the master partitions into tasks.
    Always returns every chunk (both backends)."""
    lib = _load_native()
    if lib is not None:
        # modest initial guess; rio_scan_chunks reports the true count when
        # undersized and the loop rescans with the exact size
        cap = 1 << 16
        while True:
            offsets = (ctypes.c_uint64 * cap)()
            counts = (ctypes.c_uint32 * cap)()
            n = lib.rio_scan_chunks(path.encode(), offsets, counts, cap)
            if n < 0:
                raise IOError(f"{path}: malformed recordio file")
            if n <= cap:
                return [
                    Chunk(path, int(offsets[i]), int(counts[i]))
                    for i in range(n)
                ]
            cap = n  # undersized — rescan with the exact size
    chunks = []
    fsize = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = 0
        while True:
            head = f.read(16)
            if len(head) < 16:
                break
            magic, _, body_len, n = struct.unpack("<IIII", head)
            if magic != _MAGIC or 4 * n > body_len or pos + 16 + body_len > fsize:
                raise IOError(f"{path}: malformed recordio file")
            chunks.append(Chunk(path, pos, n))
            pos += 16 + body_len
            f.seek(pos)
    return chunks


# ---------------------------------------------------------------------------
# Prefetcher
# ---------------------------------------------------------------------------

class Prefetcher:
    """Background prefetch over many files (native threads when available,
    Python threads otherwise) — the DataProvider double-buffer generalized."""

    def __init__(self, paths: Sequence[str], n_threads: int = 2, capacity: int = 1024):
        self._lib = _load_native()
        self._paths = list(paths)
        # Guards the native (pointer, copy) pair: the C side reuses one
        # internal record buffer per prefetcher, so the pointer must be
        # copied out before another consumer can advance it.
        self._next_lock = make_lock("io.recordio.Prefetcher._next_lock")
        self._worker_error: Optional[BaseException] = None
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(self._paths))(
                *[p.encode() for p in self._paths]
            )
            self._h = self._lib.rio_prefetcher_create(
                arr, len(self._paths), n_threads, capacity
            )
        else:
            self._q: _queue.Queue = _queue.Queue(maxsize=capacity)
            self._stopped = False
            self._n_workers = max(1, min(n_threads, len(self._paths)))
            per = (len(self._paths) + self._n_workers - 1) // self._n_workers
            self._done = 0
            self._done_lock = make_lock("io.recordio.Prefetcher._done_lock")
            self._threads: List[threading.Thread] = []
            for t in range(self._n_workers):
                part = self._paths[t * per : (t + 1) * per]
                th = threading.Thread(
                    target=self._worker, args=(part,),
                    name=f"paddle-recordio-prefetch-{t}", daemon=True,
                )
                self._threads.append(th)
                th.start()

    def _worker(self, paths):
        stopped = lambda: self._stopped  # noqa: E731 — the shared teardown contract
        try:
            for p in paths:
                with Reader(p) as r:
                    for rec in r:
                        # bounded put that notices close(): don't block
                        # forever (leaking the thread + fd) when the
                        # consumer stops early
                        if not _bounded_put(self._q, rec, stopped):
                            return
        except BaseException as exc:  # surfaced to the consumer in next()
            self._worker_error = exc
        finally:
            with self._done_lock:
                self._done += 1
                last = self._done == self._n_workers
            if last:
                # the sentinel must reach a live consumer even if the queue
                # is momentarily full; only a close() may drop it
                _bounded_put(self._q, None, stopped)

    def next(self) -> Optional[bytes]:
        if self._lib is not None:
            with self._next_lock:
                out = ctypes.POINTER(ctypes.c_uint8)()
                ln = self._lib.rio_prefetcher_next(self._h, ctypes.byref(out))
                if ln == -2:
                    raise IOError(
                        "prefetcher: unreadable or corrupt recordio input"
                    )
                if ln < 0:
                    return None
                return ctypes.string_at(out, ln)
        item = self._q.get()
        if item is None:
            self._q.put(None)  # keep the sentinel for other consumers
            if self._worker_error is not None:
                raise IOError(
                    f"prefetcher worker failed: {self._worker_error!r}"
                ) from self._worker_error
            return None
        return item

    def __iter__(self):
        while True:
            r = self.next()
            if r is None:
                return
            yield r

    def close(self) -> None:
        if self._lib is not None:
            if self._h:
                self._lib.rio_prefetcher_destroy(self._h)
                self._h = None
            return
        self._stopped = True
        # unblock any worker waiting on a full queue, then JOIN them: a
        # worker's puts are bounded polls against _stopped, so every thread
        # (and its open Reader fd) is gone when close() returns — the
        # teardown-leak contract thread_report() checks.  The join is
        # DEADLINED: a worker wedged inside file i/o (hung NFS read never
        # reaches a _stopped check) must degrade to leaking one daemon
        # thread, not hang every `with Prefetcher(...)` exit forever
        deadline = time.monotonic() + 5.0
        for th in self._threads:
            while th.is_alive() and time.monotonic() < deadline:
                try:
                    self._q.get_nowait()
                except _queue.Empty:
                    pass
                th.join(timeout=0.2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: Iterable[bytes], **kw) -> int:
    n = 0
    with Writer(path, **kw) as w:
        for r in records:
            w.write(r)
            n += 1
    return n
