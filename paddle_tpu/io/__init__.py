"""Native data-IO runtime bindings (recordio + prefetch).

The C++ library lives in paddle_tpu/native/recordio.cc; `recordio` loads it via ctypes,
building it on first use with g++, and falls back to a pure-Python
implementation of the identical on-disk format when no toolchain exists.
"""

from paddle_tpu.io.recordio import (  # noqa: F401
    Chunk,
    Prefetcher,
    Reader,
    Writer,
    native_available,
    scan_chunks,
)
