"""DataFormat.proto binary data reader — feeds the reference's checked-in
binary datasets directly (paddle/trainer/tests/mnist_bin_part,
data_bin_part), completing TrainerOnePass parity.

Reference format (proto/DataFormat.proto; ProtoReader.h:53 read();
ProtoDataProvider.cpp:210 loadDataFile): a stream of varint32-length-framed
proto2 messages — one ``DataHeader`` then N ``DataSample``s — optionally
gzip-compressed when the filename ends in ``.gz``.

Implemented as a minimal proto2 wire-format decoder: the schema is four
small messages, so no protoc/generated code is needed (and the environment
bakes none in).  Packed and unpacked repeated scalar encodings are both
accepted, as protobuf parsers must.
"""

from __future__ import annotations

import ctypes
import dataclasses
import gzip
import os
import struct
import subprocess
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

# SlotDef.SlotType (DataFormat.proto:50-58)
VECTOR_DENSE = 0
VECTOR_SPARSE_NON_VALUE = 1
VECTOR_SPARSE_VALUE = 2
INDEX = 3
VAR_MDIM_DENSE = 4
VAR_MDIM_INDEX = 5
STRING = 6


@dataclasses.dataclass(frozen=True)
class SlotDef:
    type: int
    dim: int


# ---------------------------------------------------------------------------
# proto2 wire format
# ---------------------------------------------------------------------------


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value); value is int for varint/fixed
    and bytes for length-delimited."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            v, pos = _varint(buf, pos)
        elif wt == 5:  # fixed32
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wt == 1:  # fixed64
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = _varint(buf, pos)
            v = buf[pos : pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _packed_varints(v: bytes) -> List[int]:
    out = []
    pos = 0
    while pos < len(v):
        x, pos = _varint(v, pos)
        out.append(x)
    return out


def _collect_uint32(acc: List[int], wt: int, v) -> None:
    """repeated uint32 — packed (wt 2) or single (wt 0)."""
    if wt == 2:
        acc.extend(_packed_varints(v))
    else:
        acc.append(v)


def _collect_float(acc: List[float], wt: int, v) -> None:
    """repeated float — packed (wt 2, concatenated fixed32) or single."""
    if wt == 2:
        acc.extend(np.frombuffer(v, dtype="<f4").tolist())
    else:
        acc.append(struct.unpack("<f", struct.pack("<I", v))[0])


def _parse_slot_def(buf: bytes) -> SlotDef:
    t = dim = 0
    for field, _wt, v in _fields(buf):
        if field == 1:
            t = v
        elif field == 2:
            dim = v
    return SlotDef(t, dim)


def _parse_header(buf: bytes) -> List[SlotDef]:
    defs: List[SlotDef] = []
    for field, _wt, v in _fields(buf):
        if field == 1:
            defs.append(_parse_slot_def(v))
    if not defs:
        raise ValueError("DataHeader declares no slots")
    return defs


@dataclasses.dataclass
class VectorSlot:
    values: List[float]
    ids: List[int]
    dims: List[int]
    strs: List[bytes]


def _parse_vector_slot(buf: bytes) -> VectorSlot:
    vs = VectorSlot([], [], [], [])
    for field, wt, v in _fields(buf):
        if field == 1:
            _collect_float(vs.values, wt, v)
        elif field == 2:
            _collect_uint32(vs.ids, wt, v)
        elif field == 3:
            _collect_uint32(vs.dims, wt, v)
        elif field == 4:
            vs.strs.append(v)
    return vs


@dataclasses.dataclass
class SubseqSlot:
    slot_id: int
    lens: List[int]


@dataclasses.dataclass
class DataSample:
    is_beginning: bool
    vector_slots: List[VectorSlot]
    id_slots: List[int]
    var_id_slots: List[VectorSlot]
    subseq_slots: List[SubseqSlot]


def _parse_sample(buf: bytes) -> DataSample:
    s = DataSample(True, [], [], [], [])
    for field, wt, v in _fields(buf):
        if field == 1:
            s.is_beginning = bool(v)
        elif field == 2:
            s.vector_slots.append(_parse_vector_slot(v))
        elif field == 3:
            _collect_uint32(s.id_slots, wt, v)
        elif field == 4:
            s.var_id_slots.append(_parse_vector_slot(v))
        elif field == 5:
            ss = SubseqSlot(0, [])
            for f2, wt2, v2 in _fields(v):
                if f2 == 1:
                    ss.slot_id = v2
                elif f2 == 2:
                    _collect_uint32(ss.lens, wt2, v2)
            s.subseq_slots.append(ss)
    return s


# ---------------------------------------------------------------------------
# native fast path (paddle_tpu/native/protodata.cc): one-pass C++ decode of
# DENSE+INDEX files (the mnist_bin_part shape) into contiguous numpy
# buffers; anything else (sparse, sequences, gzip) falls back to the
# pure-Python decoder below.
# ---------------------------------------------------------------------------

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_SRC = os.path.join(_PKG_ROOT, "native", "protodata.cc")
_NATIVE_SO = os.path.join(_PKG_ROOT, "native", "build", "libpaddle_tpu_protodata.so")
_native_lib = None
_native_tried = False
from paddle_tpu.analysis.lock_sanitizer import make_lock

_native_lock = make_lock("io.protodata._native_lock")


def _load_native():
    global _native_lib, _native_tried
    with _native_lock:
        if _native_tried:
            return _native_lib
        _native_tried = True
        try:
            have_so = os.path.exists(_NATIVE_SO)
            have_src = os.path.exists(_NATIVE_SRC)
            stale = (
                have_so and have_src
                and os.path.getmtime(_NATIVE_SO) < os.path.getmtime(_NATIVE_SRC)
            )
            if (not have_so or stale) and have_src:
                os.makedirs(os.path.dirname(_NATIVE_SO), exist_ok=True)
                # build to a per-pid temp and rename: concurrent processes
                # (pytest workers, multi-process launch) must never CDLL a
                # half-written .so
                tmp = f"{_NATIVE_SO}.{os.getpid()}.tmp"
                subprocess.run(  # lock: allow[C304] one-time lazy native build; the lock exists to serialize exactly this compile
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     _NATIVE_SRC, "-o", tmp],
                    check=True, capture_output=True,
                )
                os.replace(tmp, _NATIVE_SO)
            elif not have_so:
                return None
            lib = ctypes.CDLL(_NATIVE_SO)
            lib.pdx_scan.restype = ctypes.c_int
            lib.pdx_scan.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_uint),
                ctypes.c_int,
            ]
            lib.pdx_decode_dense_index.restype = ctypes.c_int
            lib.pdx_decode_dense_index.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_longlong,
            ]
            _native_lib = lib
        except Exception:
            _native_lib = None
        return _native_lib


# (path, size, mtime_ns) -> (defs, count) or None: skips the full scan walk
# on later epochs, and remembers which files can NEVER take the fast path so
# they don't pay a C++ parse before every Python fallback.
_scan_cache: dict = {}


def _native_scan(lib, path: str):
    key = None
    try:
        st = os.stat(path)
        key = (str(path), st.st_size, st.st_mtime_ns)
        if key in _scan_cache:
            return _scan_cache[key]
    except OSError:
        pass
    max_slots = 64
    n = ctypes.c_longlong(0)
    ns = ctypes.c_int(0)
    types = (ctypes.c_int * max_slots)()
    dims = (ctypes.c_uint * max_slots)()
    rc = lib.pdx_scan(
        str(path).encode(), ctypes.byref(n), ctypes.byref(ns), types, dims,
        max_slots,
    )
    out = (
        ([SlotDef(types[i], int(dims[i])) for i in range(ns.value)], int(n.value))
        if rc == 0
        else None
    )
    if key is not None:
        if len(_scan_cache) > 1024:
            _scan_cache.clear()
        _scan_cache[key] = out
    return out


def native_decode_dense_index(path: str):
    """(defs, arrays-aligned-to-defs) via the C++ decoder, or None when the
    file is not the dense/index fast path (or the native lib is absent)."""
    if str(path).endswith(".gz"):
        return None
    lib = _load_native()
    if lib is None:
        return None
    scanned = _native_scan(lib, path)
    if scanned is None:
        return None
    defs, count = scanned
    dense_arrays = [
        np.empty((count, d.dim), np.float32) for d in defs if d.type == VECTOR_DENSE
    ]
    index_arrays = [
        np.empty((count,), np.int32) for d in defs if d.type == INDEX
    ]
    dense_ptrs = (ctypes.c_void_p * max(len(dense_arrays), 1))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in dense_arrays]
    )
    index_ptrs = (ctypes.c_void_p * max(len(index_arrays), 1))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in index_arrays]
    )
    rc = lib.pdx_decode_dense_index(
        str(path).encode(), dense_ptrs, index_ptrs, ctypes.c_longlong(count)
    )
    if rc != 0:
        return None
    out = []
    di = ii = 0
    for d in defs:
        if d.type == VECTOR_DENSE:
            out.append(dense_arrays[di])
            di += 1
        else:
            out.append(index_arrays[ii])
            ii += 1
    return defs, out


# ---------------------------------------------------------------------------
# file reading
# ---------------------------------------------------------------------------


def _read_framed(path: str) -> Iterator[bytes]:
    """Varint-length-framed messages (ProtoReader.h:92-101)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        size, pos = _varint(data, pos)
        yield data[pos : pos + size]
        pos += size


def read_proto_data(path: str) -> Tuple[List[SlotDef], List[DataSample]]:
    """One file -> (slot_defs, samples)."""
    it = _read_framed(path)
    try:
        header = _parse_header(next(it))
    except StopIteration:
        raise ValueError(f"{path}: empty proto data file") from None
    return header, [_parse_sample(b) for b in it]


def read_proto_header(path: str) -> List[SlotDef]:
    """Just the DataHeader (for slot-type resolution at config-parse time)."""
    for buf in _read_framed(path):
        return _parse_header(buf)
    raise ValueError(f"{path}: empty proto data file")


def _slot_offsets(defs: Sequence[SlotDef]) -> List[int]:
    """Per-slot index into its kind's storage list (vector_slots / id_slots /
    var_id_slots each count separately — DataSample stores the three kinds
    in separate repeated fields, so a shared offset mis-reads any header
    whose kinds interleave)."""
    counts = {"vec": 0, "id": 0, "var": 0}
    offs = []
    for d in defs:
        k = "id" if d.type == INDEX else "var" if d.type == VAR_MDIM_INDEX else "vec"
        offs.append(counts[k])
        counts[k] += 1
    return offs


def _slot_value(sample: DataSample, off: int, d: SlotDef):
    """Python value of a slot, by declared type; ``off`` is the slot's index
    within its kind's storage list (see _slot_offsets)."""
    if d.type == INDEX:
        return int(sample.id_slots[off])
    if d.type == VAR_MDIM_INDEX:
        return [int(x) for x in sample.var_id_slots[off].ids]
    vs = sample.vector_slots[off]
    if d.type == VECTOR_DENSE:
        return np.asarray(vs.values, np.float32)
    if d.type == VECTOR_SPARSE_NON_VALUE:
        return [int(x) for x in vs.ids]
    if d.type == VECTOR_SPARSE_VALUE:
        return list(zip((int(x) for x in vs.ids), vs.values))
    if d.type == STRING:
        return [s.decode("utf-8", "replace") for s in vs.strs]
    if d.type == VAR_MDIM_DENSE:
        a = np.asarray(vs.values, np.float32)
        return a.reshape([int(x) for x in vs.dims]) if vs.dims else a
    raise ValueError(f"unsupported slot type {d.type}")


def slot_input_types(defs: Sequence[SlotDef], sequence: bool = False):
    """Map SlotDefs onto the framework's InputTypes (the provider-side
    contract PyDataProvider2.cpp:54-69 expresses for py providers)."""
    from paddle_tpu.core import data_types as dt

    out = []
    for d in defs:
        if d.type == VECTOR_DENSE:
            t = dt.dense_vector_sequence(d.dim) if sequence else dt.dense_vector(d.dim)
        elif d.type == VECTOR_SPARSE_NON_VALUE:
            t = (
                dt.sparse_binary_vector_sequence(d.dim)
                if sequence
                else dt.sparse_binary_vector(d.dim)
            )
        elif d.type == VECTOR_SPARSE_VALUE:
            t = (
                dt.sparse_float_vector_sequence(d.dim)
                if sequence
                else dt.sparse_float_vector(d.dim)
            )
        elif d.type == INDEX:
            t = dt.integer_value_sequence(d.dim) if sequence else dt.integer_value(d.dim)
        elif d.type == VAR_MDIM_INDEX:
            # a var-length id LIST per sample — inherently a sequence slot
            # even in non-sequence mode (its _slot_value is a list)
            t = dt.integer_value_sequence(d.dim)
        else:
            raise ValueError(f"slot type {d.type} has no InputType mapping")
        out.append(t)
    return out


# ---------------------------------------------------------------------------
# writing (round-trip tests + converting py datasets into the binary format)
# ---------------------------------------------------------------------------


def _enc_varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_key(field: int, wt: int) -> bytes:
    return _enc_varint((field << 3) | wt)


def _enc_len_delim(field: int, payload: bytes) -> bytes:
    return _enc_key(field, 2) + _enc_varint(len(payload)) + payload


def _enc_packed_varints(field: int, xs: Sequence[int]) -> bytes:
    if not xs:
        return b""
    return _enc_len_delim(field, b"".join(_enc_varint(int(x)) for x in xs))


def _enc_packed_floats(field: int, xs: Sequence[float]) -> bytes:
    if len(xs) == 0:
        return b""
    return _enc_len_delim(field, np.asarray(xs, "<f4").tobytes())


def _enc_vector_slot(field: int, values=(), ids=()) -> bytes:
    return _enc_len_delim(
        field, _enc_packed_floats(1, values) + _enc_packed_varints(2, ids)
    )


def write_proto_data(path: str, defs: Sequence[SlotDef], rows, is_beginning=None):
    """Encode rows (tuples in slot order, python values as `_slot_value`
    returns them) into the varint-framed DataFormat.proto layout the
    reference trainer reads.  ``is_beginning``: optional parallel iterable of
    bools for sequence grouping (default: every sample begins a sequence)."""
    # SlotDef wire: field1(type)=key 0x08 varint, field2(dim)=key 0x10 varint
    header = b"".join(
        _enc_len_delim(
            1, b"\x08" + _enc_varint(d.type) + b"\x10" + _enc_varint(d.dim)
        )
        for d in defs
    )
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(_enc_varint(len(header)) + header)
        begins = iter(is_beginning) if is_beginning is not None else None
        for row in rows:
            body = b""
            if begins is not None and not next(begins):
                body += _enc_key(1, 0) + _enc_varint(0)  # is_beginning=false
            ids_tail = []
            for v, d in zip(row, defs):
                if d.type == INDEX:
                    ids_tail.append(int(v))
                elif d.type == VECTOR_DENSE:
                    body += _enc_vector_slot(2, values=np.asarray(v, np.float32))
                elif d.type == VECTOR_SPARSE_NON_VALUE:
                    body += _enc_vector_slot(2, ids=[int(x) for x in v])
                elif d.type == VECTOR_SPARSE_VALUE:
                    body += _enc_vector_slot(
                        2,
                        values=[float(x) for _, x in v],
                        ids=[int(i) for i, _ in v],
                    )
                else:
                    raise ValueError(f"write: unsupported slot type {d.type}")
            body += _enc_packed_varints(3, ids_tail)
            f.write(_enc_varint(len(body)) + body)


def make_reader(
    paths: Sequence[str],
    sequence: bool = False,
):
    """Reader factory over proto data files (the v2 reader contract: a
    callable returning a fresh generator).

    sequence=False: one tuple per DataSample (ProtoDataProvider semantics).
    sequence=True: samples grouped by ``is_beginning`` into sequences, each
    slot a per-timestep list (ProtoSequenceDataProvider semantics,
    ProtoDataProvider.cpp:528).
    """
    paths = list(paths)

    def reader():
        expect: Optional[List[SlotDef]] = None
        seq_acc: Optional[List[list]] = None
        for path in paths:
            if not sequence:
                nat = native_decode_dense_index(path)
                if nat is not None:
                    defs, arrays = nat
                    if expect is None:
                        expect = defs
                    elif defs != expect:
                        raise ValueError(
                            f"{path}: slot defs {defs} differ from first "
                            f"file's {expect}"
                        )
                    count = arrays[0].shape[0] if arrays else 0
                    for i in range(count):
                        yield tuple(
                            a[i] if a.ndim == 2 else int(a[i]) for a in arrays
                        )
                    continue
            defs, samples = read_proto_data(path)
            if expect is None:
                expect = defs
            elif defs != expect:
                raise ValueError(
                    f"{path}: slot defs {defs} differ from first file's "
                    f"{expect} (checkDataHeader consistency rule)"
                )
            offs = _slot_offsets(defs)
            for s in samples:
                row = tuple(
                    _slot_value(s, off, d) for off, d in zip(offs, defs)
                )
                if not sequence:
                    yield row
                    continue
                if s.is_beginning and seq_acc is not None:
                    yield tuple(seq_acc)
                    seq_acc = None
                if seq_acc is None:
                    seq_acc = [[] for _ in defs]
                for acc, v in zip(seq_acc, row):
                    acc.append(v)
        if sequence and seq_acc is not None:
            yield tuple(seq_acc)

    return reader
