"""Parameters — the ``paddle.v2.parameters`` surface (reference:
python/paddle/v2/parameters.py) plus reference-compatible tar checkpoints.

The tar layout matches the reference so v1/v2 checkpoints interoperate:
one member per parameter whose payload is the v1 binary header
(int32 version=0, uint32 value_size=4, uint64 num_elements) followed by raw
float32 data (reference: paddle/parameter/Parameter.cpp save/load:~250-340,
python/paddle/v2/parameters.py to_tar/from_tar).
"""

from __future__ import annotations

import io
import struct
import tarfile
from typing import Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from paddle_tpu.core.compiler import CompiledNetwork, NetState, Params
from paddle_tpu.core.topology import Topology


class Parameters:
    """Holds the parameter pytree + non-trainable state for a topology."""

    def __init__(self, network: CompiledNetwork, params: Params, state: NetState):
        self.network = network
        self.params = params
        self.state = state

    # -- dict-like numpy access (name = dotted path, e.g. "fc0.w0" or
    # "decoder.hproj.w0" for nested recurrent_group params) --------------
    def names(self):
        out = []

        def walk(prefix, node):
            if isinstance(node, dict):
                for k in node:
                    walk(f"{prefix}.{k}" if prefix else k, node[k])
            else:
                out.append(prefix)

        walk("", self.params)
        return out

    def keys(self):
        return self.names()

    def _resolve(self, key: str):
        parts = key.split(".")
        node = self.params
        try:
            for p in parts[:-1]:
                node = node[p]
            if parts[-1] not in node:
                raise KeyError(parts[-1])
        except (KeyError, TypeError):
            # fall back to the GLOBAL parameter name table (reference
            # parameters are named objects: parameters.get("embedding.w0"))
            named = getattr(self.network, "named_parameters", None)
            if named is not None and key in (table := named()):
                node, leaf = self._resolve(table[key])
                # legacy whole-layer names address the layer's param DICT;
                # descend to its single leaf (reference one-parameter
                # layers), never hand back a dict as if it were an array
                while isinstance(node[leaf], dict):
                    inner = node[leaf]
                    if len(inner) != 1:
                        raise KeyError(
                            f"named parameter {key!r} maps to a multi-key "
                            f"param dict ({sorted(inner)}); address a leaf "
                            f"as {table[key]}.<key>"
                        )
                    node, leaf = inner, next(iter(inner))
                return node, leaf
            raise
        return node, parts[-1]

    def get(self, key: str) -> np.ndarray:
        node, leaf = self._resolve(key)
        return np.asarray(node[leaf])

    __getitem__ = get

    def set(self, key: str, value: np.ndarray) -> None:
        import jax.numpy as jnp

        node, leaf = self._resolve(key)
        old = node[leaf]
        value = jnp.asarray(value, dtype=old.dtype).reshape(old.shape)
        node[leaf] = value

    __setitem__ = set

    def __iter__(self) -> Iterator[str]:
        return iter(self.names())

    def __len__(self) -> int:
        return len(self.names())

    # -- tar checkpoints ------------------------------------------------
    def to_tar(self, f) -> None:
        """Reference v2 tar layout (python/paddle/v2/parameters.py:266):
        per parameter a data member (v1 binary header + raw float32) AND a
        ``<name>.protobuf`` ParameterConfig member carrying name/size/dims
        (hand-rolled proto2 wire bytes — fields 1, 2, 9 of
        proto/ParameterConfig.proto) so the static ``from_tar`` can
        restore shapes, and the reference itself can parse the file."""
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.names():
                _write_tar_member(tar, name, self.get(name))

    def init_from_tar(self, f) -> None:
        """Merge a parameter tar into THIS instance, ignoring names the
        topology doesn't have (reference Parameters.init_from_tar,
        python/paddle/v2/parameters.py:314)."""
        known = set(self.names())
        for name, arr in _read_tar_members(f):
            if name in known:
                self.set(name, arr)

    class _FromTar:
        """``Parameters.from_tar(f)`` on the CLASS is the reference's
        static constructor (python/paddle/v2/parameters.py:286) and
        returns a topology-free :class:`DetachedParameters`; on an
        INSTANCE it merges into the existing parameters (kept as an
        alias of :meth:`init_from_tar` for the library's own callers)."""

        def __get__(self, obj, objtype=None):
            if obj is None:
                return DetachedParameters.from_tar
            return obj.init_from_tar

    from_tar = _FromTar()

    @staticmethod
    def from_tar_new(network: CompiledNetwork, f) -> "Parameters":
        p = create_from_network(network, seed=0)
        p.init_from_tar(f)
        return p


def _write_tar_member(tar, name: str, arr: np.ndarray) -> None:
    """One parameter as the reference pair of members: v1-binary data +
    ParameterConfig shape record."""
    arr = np.asarray(arr, np.float32)
    payload = struct.pack("<iIQ", 0, 4, arr.size) + arr.tobytes()
    info = tarfile.TarInfo(name=name)
    info.size = len(payload)
    tar.addfile(info, io.BytesIO(payload))
    conf = _encode_param_conf(name, arr.shape)
    cinfo = tarfile.TarInfo(name=f"{name}.protobuf")
    cinfo.size = len(conf)
    tar.addfile(cinfo, io.BytesIO(conf))


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _encode_param_conf(name: str, shape) -> bytes:
    """Minimal proto2 ParameterConfig wire bytes: name (field 1, string),
    size (field 2, uint64), dims (field 9, repeated uint64)."""
    nb = name.encode("utf-8")
    out = b"\x0a" + _varint(len(nb)) + nb  # field 1, wire type 2
    size = 1
    for d in shape:
        size *= int(d)
    out += b"\x10" + _varint(size)  # field 2, wire type 0
    for d in shape:
        out += b"\x48" + _varint(int(d))  # field 9, wire type 0
    return out


def _parse_param_conf(buf: bytes, member: str = "?"):
    """Parse the fields we wrote (skipping any others a reference-written
    tar may carry).  Returns (name, dims)."""
    name, dims = None, []
    i, n = 0, len(buf)

    def read_varint(i):
        v, shift = 0, 0
        while True:
            if i >= n:
                raise ValueError(
                    f"corrupt ParameterConfig member {member!r}: varint "
                    f"runs past the end of the {n}-byte record"
                )
            b = buf[i]
            v |= (b & 0x7F) << shift
            i += 1
            if not b & 0x80:
                return v, i
            shift += 7

    while i < n:
        tag, i = read_varint(i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = read_varint(i)
            if field == 9:
                dims.append(v)
        elif wire == 1:
            i += 8
        elif wire == 2:
            ln, i = read_varint(i)
            if field == 1:
                name = buf[i : i + ln].decode("utf-8")
            i += ln
        elif wire == 5:
            i += 4
        else:
            # wire types 3/4 (proto2 groups) and 6/7 don't appear in any
            # ParameterConfig a reference build can write; a partial parse
            # here would silently load the array flat (shapeless), so fail
            # loudly like the varint-overrun path does
            raise ValueError(
                f"corrupt ParameterConfig member {member!r}: unknown proto "
                f"wire type {wire} (field {field}) at byte {i}"
            )
    return name, dims


def _read_tar_members(f):
    """Yield (name, float32 array) for each data member of a
    reference-format parameter tar, with shapes restored from any
    ``<name>.protobuf`` ParameterConfig members present."""
    with tarfile.open(fileobj=f, mode="r") as tar:
        members = tar.getmembers()
        dims = {}
        for member in members:
            if member.name.endswith(".protobuf"):
                nm, dd = _parse_param_conf(
                    tar.extractfile(member).read(), member.name
                )
                dims[nm if nm else member.name[: -len(".protobuf")]] = dd
        for member in members:
            if member.name.endswith(".protobuf"):
                continue
            buf = tar.extractfile(member).read()
            version, value_size, size = struct.unpack("<iIQ", buf[:16])
            assert value_size == 4, "only float32 checkpoints supported"
            arr = np.frombuffer(buf[16 : 16 + 4 * size], dtype=np.float32)
            dd = dims.get(member.name)
            if dd and int(np.prod(dd)) == arr.size:
                arr = arr.reshape([int(d) for d in dd])
            yield member.name, arr


class DetachedParameters:
    """Topology-free parameter bag — what the reference's static
    ``Parameters.from_tar(f)`` returns: names + float32 values with no
    network attached.  Accepted anywhere a Parameters is (SGD, Inference,
    infer): the consumer builds its own parameters from the topology and
    merges these values in by name."""

    def __init__(self, values: Dict[str, np.ndarray]):
        self._values = dict(values)

    @staticmethod
    def from_tar(f) -> "DetachedParameters":
        if isinstance(f, Parameters) or not hasattr(f, "read"):
            # the class/instance duality of Parameters.from_tar (_FromTar):
            # an unbound-style call Parameters.from_tar(params_obj, f) lands
            # here with the Parameters object as `f` — catch it before
            # tarfile produces an opaque error
            raise TypeError(
                "Parameters.from_tar on the CLASS is the static constructor "
                "taking a single binary file object (got "
                f"{type(f).__name__}); to merge a tar into an existing "
                "Parameters call params.from_tar(f) / params.init_from_tar(f)"
            )
        return DetachedParameters(dict(_read_tar_members(f)))

    def names(self):
        return list(self._values)

    keys = names

    def get(self, key: str) -> np.ndarray:
        return self._values[key]

    __getitem__ = get

    def set(self, key: str, value: np.ndarray) -> None:
        self._values[key] = np.asarray(value)

    __setitem__ = set

    def __iter__(self) -> Iterator[str]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __contains__(self, key: str) -> bool:
        return key in self._values

    def to_tar(self, f) -> None:
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name, arr in self._values.items():
                _write_tar_member(tar, name, arr)

    def merge_into(self, parameters: Parameters) -> Parameters:
        """Copy every name the target topology knows into `parameters`.
        Warns when NOTHING matches — that means the tar came from a
        different/renamed topology and the consumer would otherwise run on
        silently random weights."""
        known = set(parameters.names())
        hit = [n for n in self._values if n in known]
        if self._values and not hit:
            import warnings

            warnings.warn(
                "parameter tar matched no parameter names of the target "
                f"topology (tar has {sorted(self._values)[:5]}..., topology "
                f"has {sorted(known)[:5]}...); the model keeps its random "
                "initialization",
                stacklevel=2,
            )
        elif (uncovered := sorted(known - set(self._values))):
            import warnings

            warnings.warn(
                f"parameter tar covers {len(hit)} of {len(known)} topology "
                f"parameters; {uncovered[:8]} keep their random "
                "initialization (use init_from_tar directly for intentional "
                "partial loads)",
                stacklevel=2,
            )
        for name in hit:
            parameters.set(name, self._values[name])
        return parameters


def create(cost_or_topology, seed: int = 0, dtype=None) -> Parameters:
    """paddle.parameters.create(cost) equivalent."""
    from paddle_tpu.core.topology import LayerOutput

    if isinstance(cost_or_topology, Topology):
        topo = cost_or_topology
    else:
        topo = Topology(cost_or_topology)
    network = CompiledNetwork(topo, dtype=dtype) if dtype else CompiledNetwork(topo)
    return create_from_network(network, seed)


def create_from_network(network: CompiledNetwork, seed: int = 0) -> Parameters:
    rng = jax.random.PRNGKey(seed)
    params, state = network.init(rng)
    return Parameters(network, params, state)
