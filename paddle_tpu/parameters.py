"""Parameters — the ``paddle.v2.parameters`` surface (reference:
python/paddle/v2/parameters.py) plus reference-compatible tar checkpoints.

The tar layout matches the reference so v1/v2 checkpoints interoperate:
one member per parameter whose payload is the v1 binary header
(int32 version=0, uint32 value_size=4, uint64 num_elements) followed by raw
float32 data (reference: paddle/parameter/Parameter.cpp save/load:~250-340,
python/paddle/v2/parameters.py to_tar/from_tar).
"""

from __future__ import annotations

import io
import struct
import tarfile
from typing import Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from paddle_tpu.core.compiler import CompiledNetwork, NetState, Params
from paddle_tpu.core.topology import Topology


class Parameters:
    """Holds the parameter pytree + non-trainable state for a topology."""

    def __init__(self, network: CompiledNetwork, params: Params, state: NetState):
        self.network = network
        self.params = params
        self.state = state

    # -- dict-like numpy access (name = dotted path, e.g. "fc0.w0" or
    # "decoder.hproj.w0" for nested recurrent_group params) --------------
    def names(self):
        out = []

        def walk(prefix, node):
            if isinstance(node, dict):
                for k in node:
                    walk(f"{prefix}.{k}" if prefix else k, node[k])
            else:
                out.append(prefix)

        walk("", self.params)
        return out

    def keys(self):
        return self.names()

    def _resolve(self, key: str):
        parts = key.split(".")
        node = self.params
        try:
            for p in parts[:-1]:
                node = node[p]
            if parts[-1] not in node:
                raise KeyError(parts[-1])
        except (KeyError, TypeError):
            # fall back to the GLOBAL parameter name table (reference
            # parameters are named objects: parameters.get("embedding.w0"))
            named = getattr(self.network, "named_parameters", None)
            if named is not None and key in (table := named()):
                node, leaf = self._resolve(table[key])
                # legacy whole-layer names address the layer's param DICT;
                # descend to its single leaf (reference one-parameter
                # layers), never hand back a dict as if it were an array
                while isinstance(node[leaf], dict):
                    inner = node[leaf]
                    if len(inner) != 1:
                        raise KeyError(
                            f"named parameter {key!r} maps to a multi-key "
                            f"param dict ({sorted(inner)}); address a leaf "
                            f"as {table[key]}.<key>"
                        )
                    node, leaf = inner, next(iter(inner))
                return node, leaf
            raise
        return node, parts[-1]

    def get(self, key: str) -> np.ndarray:
        node, leaf = self._resolve(key)
        return np.asarray(node[leaf])

    __getitem__ = get

    def set(self, key: str, value: np.ndarray) -> None:
        import jax.numpy as jnp

        node, leaf = self._resolve(key)
        old = node[leaf]
        value = jnp.asarray(value, dtype=old.dtype).reshape(old.shape)
        node[leaf] = value

    __setitem__ = set

    def __iter__(self) -> Iterator[str]:
        return iter(self.names())

    def __len__(self) -> int:
        return len(self.names())

    # -- tar checkpoints ------------------------------------------------
    def to_tar(self, f) -> None:
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.names():
                arr = self.get(name).astype(np.float32)
                payload = (
                    struct.pack("<iIQ", 0, 4, arr.size) + arr.tobytes()
                )
                info = tarfile.TarInfo(name=name)
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))

    def from_tar(self, f) -> None:
        known = set(self.names())
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                buf = tar.extractfile(member).read()
                version, value_size, size = struct.unpack("<iIQ", buf[:16])
                assert value_size == 4, "only float32 checkpoints supported"
                arr = np.frombuffer(buf[16 : 16 + 4 * size], dtype=np.float32)
                if member.name in known:
                    self.set(member.name, arr)

    @staticmethod
    def from_tar_new(network: CompiledNetwork, f) -> "Parameters":
        import jax

        p = create_from_network(network, seed=0)
        p.from_tar(f)
        return p


def create(cost_or_topology, seed: int = 0, dtype=None) -> Parameters:
    """paddle.parameters.create(cost) equivalent."""
    from paddle_tpu.core.topology import LayerOutput

    if isinstance(cost_or_topology, Topology):
        topo = cost_or_topology
    else:
        topo = Topology(cost_or_topology)
    network = CompiledNetwork(topo, dtype=dtype) if dtype else CompiledNetwork(topo)
    return create_from_network(network, seed)


def create_from_network(network: CompiledNetwork, seed: int = 0) -> Parameters:
    rng = jax.random.PRNGKey(seed)
    params, state = network.init(rng)
    return Parameters(network, params, state)
