"""paddle.v2.image parity — image preprocessing for vision readers
(reference: python/paddle/v2/image.py).

The reference wraps OpenCV; this environment has no cv2, so decoding uses
Pillow when importable and every geometric transform is plain numpy (HWC
uint8/float arrays in, same out).  Function names, argument shapes, and the
CHW/flip/crop semantics match the reference so v1-era vision pipelines port
unchanged."""

from __future__ import annotations

import io
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "load_image",
    "load_image_bytes",
    "resize_short",
    "to_chw",
    "center_crop",
    "random_crop",
    "left_right_flip",
    "simple_transform",
    "load_and_transform",
]


def _require_pil():
    try:
        from PIL import Image  # type: ignore

        return Image
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "image decoding needs Pillow (the reference used cv2); "
            "geometric transforms work on numpy arrays without it"
        ) from e


def load_image_bytes(bytes_: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an encoded image buffer to HWC uint8 (or HW when gray)."""
    Image = _require_pil()
    img = Image.open(io.BytesIO(bytes_))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(file: str, is_color: bool = True) -> np.ndarray:
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize(im: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize in numpy (no cv2/PIL dependency for arrays)."""
    src_h, src_w = im.shape[:2]
    if (src_h, src_w) == (h, w):
        return im
    ys = np.linspace(0, src_h - 1, h)
    xs = np.linspace(0, src_w - 1, w)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, src_h - 1)
    x1 = np.minimum(x0 + 1, src_w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    arr = im.astype(np.float64)
    top = arr[y0][:, x0] * (1 - wx) + arr[y0][:, x1] * wx
    bot = arr[y1][:, x0] * (1 - wx) + arr[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(im.dtype, np.integer):
        return np.rint(out).astype(im.dtype)  # round, don't truncate
    return out.astype(im.dtype)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORT edge becomes `size`, keeping aspect ratio
    (reference image.py:143)."""
    h, w = im.shape[:2]
    if h > w:
        return _resize(im, int(round(h * size / w)), size)
    return _resize(im, size, int(round(w * size / h)))


def to_chw(im: np.ndarray, order: Sequence[int] = (2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (reference image.py:169)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    if size > h or size > w:
        raise ValueError(f"crop size {size} exceeds image {h}x{w}")
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start : h_start + size, w_start : w_start + size]


def random_crop(
    im: np.ndarray, size: int, is_color: bool = True, rng: Optional[np.random.RandomState] = None
) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = int(rng.randint(0, h - size + 1))
    w_start = int(rng.randint(0, w - size + 1))
    return im[h_start : h_start + size, w_start : w_start + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    """Mirror horizontally (reference image.py:246)."""
    return im[:, ::-1]


def simple_transform(
    im: np.ndarray,
    resize_size: int,
    crop_size: int,
    is_train: bool,
    is_color: bool = True,
    mean: Optional[np.ndarray] = None,
    rng: Optional[np.random.RandomState] = None,
) -> np.ndarray:
    """resize_short + (random|center) crop + train-time random flip + CHW +
    optional mean subtraction — the reference's standard pipeline
    (image.py:265)."""
    im = resize_short(im, resize_size)
    if is_train:
        rng = rng or np.random
        im = random_crop(im, crop_size, is_color, rng=rng)
        if rng.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]  # per-channel
        im -= mean
    return im


def load_and_transform(
    filename: str,
    resize_size: int,
    crop_size: int,
    is_train: bool,
    is_color: bool = True,
    mean: Optional[np.ndarray] = None,
) -> np.ndarray:
    return simple_transform(
        load_image(filename, is_color), resize_size, crop_size, is_train,
        is_color, mean,
    )
