"""Versioned, CRC-framed, size-bounded wire codec for the master RPC plane.

The reference pserver treats the network as a FAULT DOMAIN: LightNetwork/
SocketChannel frame every message, time out, retry, and never trust a peer
byte (paddle/pserver/LightNetwork.cpp, SocketChannel.cpp).  Our RPC plane
instead rode ``multiprocessing.connection``'s implicit pickle — unversioned,
size-unbounded, and ``pickle.loads`` EXECUTES attacker-controlled bytes.
This module is the replacement: every message that crosses a process
boundary is one frame of

    MAGIC(3) | version(1) | length(4) | crc32(4) | payload(length)

(integers big-endian; the CRC covers ``version|length|payload``) whose
payload is a RESTRICTED typed encoding — primitives, dict/list/tuple,
numpy arrays — that a decoder can verify byte-by-byte without ever
executing anything.  A corrupt, oversized, truncated or unknown-version
frame is a structured :class:`MasterWireError` subclass, never an OOM and
never an exec of foreign bytes.

Size discipline (the ``rpc_max_message_mb`` flag): the bound is enforced on
SEND (an over-budget gradient tree fails fast with a structured error
instead of wedging against a frozen peer's full socket buffer) and on RECV
(``Connection.recv_bytes(maxlength)`` refuses before allocating, so a
hostile length prefix cannot balloon the heap).

Payload type tags (1 ASCII byte each)::

    N           None
    T / F       True / False
    i           int64 (struct >q)
    I           big int (u32 length + ASCII decimal)
    f           float64 (struct >d)
    s           str   (u32 length + utf-8)
    b           bytes (u32 length + raw)
    l / t       list / tuple (u32 count + items)
    d           dict (u32 count + key,value pairs; keys must be hashable
                primitives — None/bool/int/float/str/bytes)
    a           numpy ndarray (u8 dtype-str length + dtype str + u8 ndim +
                u32 dims... + raw C-order bytes); dtype kind must be one of
                b/i/u/f/c — object/void dtypes are REJECTED on both sides
    q / Q       numpy int8 / uint8 ndarray (u8 ndim + u32 dims... + raw
                bytes) — the compact spelling for quantized payloads, which
                skips the dtype string entirely so a tree of small blocks
                does not pay per-array dtype framing
    z           numpy scalar (u8 dtype-str length + dtype str + raw bytes)

Decoding is allocation-bounded: collection counts are validated against the
remaining buffer (every element costs >= 1 byte), array extents are
validated against the remaining raw bytes before any allocation, and
container nesting is capped at :data:`MAX_DEPTH`.

The self-lint rule A206 (analysis/ast_rules.py) pins the whole repo to this
module: raw ``pickle.loads`` / bare ``Connection.recv()`` deserialization
anywhere else is a lint error unless pragma-justified.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.analysis.lock_sanitizer import make_lock

__all__ = [
    "MAGIC",
    "VERSION",
    "FRAME_OVERHEAD",
    "MAX_DEPTH",
    "MasterWireError",
    "WireTypeError",
    "WireOversizeError",
    "WireVersionError",
    "WireCorruptError",
    "encode_payload",
    "decode_payload",
    "encode_frame",
    "decode_frame",
    "send_msg",
    "recv_msg",
    "count_bytes",
    "default_max_bytes",
    "counters",
]

MAGIC = b"PTW"
VERSION = 1
_HEAD = struct.Struct(">3sBI")  # magic, version, payload length
_CRC = struct.Struct(">I")
FRAME_OVERHEAD = _HEAD.size + _CRC.size

_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U32 = struct.Struct(">I")
_U8 = struct.Struct(">B")

MAX_DEPTH = 64          # container nesting bound (a crafted nesting bomb
                        # must exhaust the depth check, not the C stack)
_MAX_DTYPE_LEN = 16
_MAX_NDIM = 32

# numpy dtype KINDS the codec will materialize: bool, signed/unsigned int,
# float, complex.  'O' (arbitrary python objects = pickle-by-the-back-door)
# and 'V' (void/structured) are rejected on encode AND decode.
_SAFE_DTYPE_KINDS = frozenset("biufc")

# dict keys must decode to something hashable without running user code
_KEY_TYPES = (type(None), bool, int, float, str, bytes)


class MasterWireError(RuntimeError):
    """Base of the structured wire-codec error taxonomy.  Every subclass
    names WHAT the codec refused (type, size, version, integrity) — a
    hostile or damaged frame surfaces as exactly one of these, never as a
    MemoryError, a pickle exec, or a silent misparse.

    Each class carries its protocol-conformance rule id and fix hint (the
    ``P###`` namespace shared with ``analysis/protocol_lint.py``) and
    builds a structured ``diagnostics`` list on construction, so the CLI
    and tests consume wire failures the same way as lint findings.  Still
    a plain RuntimeError subclass: a wire error must NEVER be swallowed
    by the broad ``except ValueError`` recovery paths in the journal/
    config planes."""

    kind = "wire"
    rule = "P501"
    hint = "keep RPC payloads inside the typed wire universe"

    def __init__(self, *args):
        super().__init__(*args)
        from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
        message = args[0] if args else self.__class__.__doc__.split("\n")[0]
        self.diagnostics = [Diagnostic(
            rule=self.rule, severity=Severity.ERROR, message=str(message),
            source="master_wire.py", hint=self.hint,
        )]

    @property
    def rules(self):
        return [d.rule for d in self.diagnostics]


class WireTypeError(MasterWireError):
    """The object graph contains a type outside the restricted wire set
    (deterministic: re-sending the same payload fails the same way)."""

    kind = "type"
    rule = "P501"
    hint = ("reply with None/bool/int/float/str/bytes/list/tuple/dict/"
            "ndarray only — convert sets to sorted lists, objects to dicts")


class WireOversizeError(MasterWireError):
    """The frame exceeds the ``rpc_max_message_mb`` bound — raised on send
    BEFORE any byte hits the wire, and on recv BEFORE any allocation."""

    kind = "oversize"
    rule = "P506"
    hint = ("shrink the payload (chunk the task / quantize the gradient) "
            "or raise the rpc_max_message_mb flag on BOTH peers")


class WireVersionError(MasterWireError):
    """The frame announces a wire version this decoder does not speak
    (version skew between fleet processes)."""

    kind = "version"
    rule = "P507"
    hint = ("upgrade the older peer — wire VERSION must match across the "
            "fleet (rolling restarts go through drain, not mixed versions)")


class WireCorruptError(MasterWireError):
    """The frame failed structural verification: bad magic, length
    mismatch, CRC mismatch, or an undecodable payload."""

    kind = "corrupt"
    rule = "P508"
    hint = ("treat the connection as dead and re-dial — a CRC/framing "
            "mismatch means the stream is unsynchronized, not retryable "
            "in place")


class _Counters:
    """Tiny thread-safe counter table for the codec/netem observability
    plane (Service.stats() exports a snapshot as its ``wire`` field)."""

    def __init__(self, name: str):
        self._lock = make_lock(name)
        self._c: Dict[str, int] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)

    def reset(self) -> None:
        with self._lock:
            self._c.clear()


counters = _Counters("master_wire.counters")


def default_max_bytes() -> int:
    """The ``rpc_max_message_mb`` flag resolved to bytes (64 MB when the
    flag plane is unavailable — stripped deployments)."""
    try:
        from paddle_tpu.utils import flags as _flags

        mb = _flags.get_flag("rpc_max_message_mb")
    except Exception:  # noqa: BLE001 — flag plane not loaded
        mb = 64
    return max(int(float(mb) * 1024 * 1024), FRAME_OVERHEAD + 1)


# ---------------------------------------------------------------------------
# payload encoding
# ---------------------------------------------------------------------------

def _enc(obj: Any, out: bytearray, depth: int, path: str) -> None:
    if depth > MAX_DEPTH:
        raise WireTypeError(
            f"payload nesting exceeds MAX_DEPTH={MAX_DEPTH} at {path}"
        )
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif isinstance(obj, int):
        try:
            out += b"i" + _I64.pack(obj)
        except struct.error:
            digits = str(obj).encode("ascii")
            out += b"I" + _U32.pack(len(digits)) + digits
    elif isinstance(obj, float):
        out += b"f" + _F64.pack(obj)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out += b"s" + _U32.pack(len(raw)) + raw
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out += b"b" + _U32.pack(len(raw)) + raw
    elif isinstance(obj, np.ndarray):
        _enc_array(obj, out, path)
    elif isinstance(obj, np.generic):
        _enc_scalar(obj, out, path)
    elif isinstance(obj, (list, tuple)):
        out += b"l" if isinstance(obj, list) else b"t"
        out += _U32.pack(len(obj))
        for k, item in enumerate(obj):
            _enc(item, out, depth + 1, f"{path}[{k}]")
    elif isinstance(obj, dict):
        out += b"d" + _U32.pack(len(obj))
        for key, value in obj.items():
            if not isinstance(key, _KEY_TYPES):
                raise WireTypeError(
                    f"dict key of type {type(key).__name__} at {path} — "
                    f"wire dict keys must be hashable primitives"
                )
            _enc(key, out, depth + 1, f"{path}.key")
            _enc(value, out, depth + 1, f"{path}[{key!r}]")
    else:
        raise WireTypeError(
            f"type {type(obj).__name__} at {path} is outside the "
            f"restricted wire set (primitives, dict/list/tuple, numpy "
            f"arrays) — the RPC plane does not pickle"
        )


def _check_dtype(dt: np.dtype, path: str) -> bytes:
    s = dt.str
    if dt.kind not in _SAFE_DTYPE_KINDS or dt.hasobject or len(s) > _MAX_DTYPE_LEN:
        raise WireTypeError(
            f"numpy dtype {s!r} at {path} is outside the safe wire set "
            f"(kinds {''.join(sorted(_SAFE_DTYPE_KINDS))}; object/void "
            f"dtypes would smuggle pickle back in)"
        )
    return s.encode("ascii")


# int8/uint8 arrays (the quantized-gradient payload blocks) get dedicated
# one-byte tags with no dtype string: a gradient tree split into many small
# blocks would otherwise pay the 5-byte dtype framing per block.
_COMPACT_TAGS = {np.dtype(np.int8): b"q", np.dtype(np.uint8): b"Q"}
_COMPACT_DTYPES = {tag: dt for dt, tag in _COMPACT_TAGS.items()}


def _enc_array(arr: np.ndarray, out: bytearray, path: str) -> None:
    if arr.ndim > _MAX_NDIM:
        raise WireTypeError(f"ndarray ndim {arr.ndim} > {_MAX_NDIM} at {path}")
    compact = _COMPACT_TAGS.get(arr.dtype)
    if compact is not None:
        out += compact + _U8.pack(arr.ndim)
    else:
        ds = _check_dtype(arr.dtype, path)
        out += b"a" + _U8.pack(len(ds)) + ds + _U8.pack(arr.ndim)
    for dim in arr.shape:
        out += _U32.pack(dim)
    out += np.ascontiguousarray(arr).tobytes()


def _enc_scalar(val: np.generic, out: bytearray, path: str) -> None:
    dt = np.dtype(type(val))
    ds = _check_dtype(dt, path)
    out += b"z" + _U8.pack(len(ds)) + ds + val.tobytes()


def encode_payload(obj: Any) -> bytes:
    """Encode one message object into restricted typed bytes.  Raises
    :class:`WireTypeError` on anything outside the wire set."""
    out = bytearray()
    _enc(obj, out, 0, "$")
    return bytes(out)


# ---------------------------------------------------------------------------
# payload decoding — verify-before-allocate over a bounded cursor
# ---------------------------------------------------------------------------

class _Cursor:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise WireCorruptError(
                f"payload truncated: wanted {n} bytes at offset {self.pos}, "
                f"{len(self.data) - self.pos} remain"
            )
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def remaining(self) -> int:
        return len(self.data) - self.pos


def _dec_dtype(cur: _Cursor) -> np.dtype:
    (dlen,) = _U8.unpack(cur.take(1))
    if dlen == 0 or dlen > _MAX_DTYPE_LEN:
        raise WireCorruptError(f"dtype string length {dlen} out of range")
    ds = cur.take(dlen)
    try:
        dt = np.dtype(ds.decode("ascii"))
    except (TypeError, ValueError, UnicodeDecodeError) as exc:
        raise WireCorruptError(f"undecodable dtype {ds!r}: {exc}") from exc
    if dt.kind not in _SAFE_DTYPE_KINDS or dt.hasobject or dt.itemsize == 0:
        raise WireCorruptError(
            f"dtype {dt.str!r} outside the safe wire set (refusing to "
            f"materialize)"
        )
    return dt


def _dec(cur: _Cursor, depth: int) -> Any:
    if depth > MAX_DEPTH:
        raise WireCorruptError(f"payload nesting exceeds MAX_DEPTH={MAX_DEPTH}")
    tag = cur.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _I64.unpack(cur.take(8))[0]
    if tag == b"I":
        (n,) = _U32.unpack(cur.take(4))
        raw = cur.take(n)
        try:
            return int(raw.decode("ascii"))
        except (ValueError, UnicodeDecodeError) as exc:
            raise WireCorruptError(f"bad big-int digits {raw[:32]!r}") from exc
    if tag == b"f":
        return _F64.unpack(cur.take(8))[0]
    if tag == b"s":
        (n,) = _U32.unpack(cur.take(4))
        try:
            return cur.take(n).decode("utf-8")
        except UnicodeDecodeError as exc:
            raise WireCorruptError(f"bad utf-8 in string payload: {exc}") from exc
    if tag == b"b":
        (n,) = _U32.unpack(cur.take(4))
        return cur.take(n)
    if tag in (b"l", b"t"):
        (count,) = _U32.unpack(cur.take(4))
        if count > cur.remaining():  # every element costs >= 1 byte
            raise WireCorruptError(
                f"collection count {count} exceeds remaining payload "
                f"({cur.remaining()} bytes) — refusing to preallocate"
            )
        items = [_dec(cur, depth + 1) for _ in range(count)]
        return items if tag == b"l" else tuple(items)
    if tag == b"d":
        (count,) = _U32.unpack(cur.take(4))
        if 2 * count > cur.remaining():
            raise WireCorruptError(
                f"dict count {count} exceeds remaining payload "
                f"({cur.remaining()} bytes) — refusing to preallocate"
            )
        out: Dict[Any, Any] = {}
        for _ in range(count):
            key = _dec(cur, depth + 1)
            if not isinstance(key, _KEY_TYPES):
                raise WireCorruptError(
                    f"dict key of type {type(key).__name__} — keys must be "
                    f"hashable primitives"
                )
            out[key] = _dec(cur, depth + 1)
        return out
    if tag == b"a" or tag in _COMPACT_DTYPES:
        dt = _COMPACT_DTYPES[tag] if tag != b"a" else _dec_dtype(cur)
        (ndim,) = _U8.unpack(cur.take(1))
        if ndim > _MAX_NDIM:
            raise WireCorruptError(f"ndarray ndim {ndim} > {_MAX_NDIM}")
        shape = []
        n_items = 1
        for _ in range(ndim):
            (dim,) = _U32.unpack(cur.take(4))
            shape.append(dim)
            n_items *= dim
        n_bytes = n_items * dt.itemsize
        if n_bytes > cur.remaining():
            raise WireCorruptError(
                f"ndarray claims {n_bytes} raw bytes, {cur.remaining()} "
                f"remain — refusing to allocate"
            )
        raw = cur.take(n_bytes)
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == b"z":
        dt = _dec_dtype(cur)
        raw = cur.take(dt.itemsize)
        return np.frombuffer(raw, dtype=dt)[0]
    raise WireCorruptError(f"unknown payload type tag {tag!r}")


def decode_payload(data: bytes) -> Any:
    """Decode restricted typed bytes back into the message object.  Every
    structural violation is a :class:`WireCorruptError` — decoding never
    executes payload bytes and never allocates past the buffer it holds."""
    cur = _Cursor(bytes(data))
    obj = _dec(cur, 0)
    if cur.remaining():
        raise WireCorruptError(
            f"{cur.remaining()} trailing bytes after a complete payload"
        )
    return obj


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def encode_frame(payload: bytes, max_bytes: Optional[int] = None) -> bytes:
    """``MAGIC|version|len|crc32|payload`` with the size bound enforced
    BEFORE any byte is handed to the transport."""
    if max_bytes is None:
        max_bytes = default_max_bytes()
    if len(payload) + FRAME_OVERHEAD > max_bytes:
        raise WireOversizeError(
            f"outbound frame of {len(payload) + FRAME_OVERHEAD} bytes "
            f"exceeds the {max_bytes}-byte bound (flag rpc_max_message_mb) "
            f"— refusing to send"
        )
    head = _HEAD.pack(MAGIC, VERSION, len(payload))
    crc = zlib.crc32(head[3:] + payload) & 0xFFFFFFFF
    return head + _CRC.pack(crc) + payload


def decode_frame(buf: bytes, max_bytes: Optional[int] = None) -> bytes:
    """Verify one complete frame and return its payload bytes.  The
    transport preserves message boundaries, so ``buf`` must be exactly one
    frame — any mismatch is corruption, not a partial read."""
    if max_bytes is None:
        max_bytes = default_max_bytes()
    if len(buf) > max_bytes:
        raise WireOversizeError(
            f"inbound frame of {len(buf)} bytes exceeds the {max_bytes}-"
            f"byte bound (flag rpc_max_message_mb)"
        )
    if len(buf) < FRAME_OVERHEAD:
        raise WireCorruptError(
            f"frame of {len(buf)} bytes is shorter than the "
            f"{FRAME_OVERHEAD}-byte header"
        )
    if buf[:3] != MAGIC:
        raise WireCorruptError(f"bad frame magic {bytes(buf[:3])!r}")
    version = buf[3]
    if version != VERSION:
        raise WireVersionError(
            f"unknown wire version {version} (this build speaks "
            f"{VERSION}) — version skew between fleet processes"
        )
    (length,) = _U32.unpack_from(buf, 4)
    if length + FRAME_OVERHEAD != len(buf):
        raise WireCorruptError(
            f"frame length field says {length} payload bytes but the "
            f"message carries {len(buf) - FRAME_OVERHEAD}"
        )
    (crc,) = _U32.unpack_from(buf, 8)
    payload = buf[FRAME_OVERHEAD:]
    want = zlib.crc32(buf[3:8] + payload) & 0xFFFFFFFF
    if crc != want:
        raise WireCorruptError(
            f"frame crc mismatch (stored {crc:#010x}, computed {want:#010x})"
        )
    return payload


# ---------------------------------------------------------------------------
# transport helpers (one frame per Connection message)
# ---------------------------------------------------------------------------

def count_bytes(direction: str, n: int, label: Optional[str] = None) -> None:
    """Tally ``n`` wire bytes under ``wire_bytes_{direction}`` — the metric
    the quantized-allreduce bench gates on.  The aggregate row always
    updates; ``label`` adds a per-connection row (``wire_bytes_sent[repl]``)
    so a fleet's traffic decomposes by endpoint.  Mirrored into the global
    StatSet when the timers plane is importable (never a hard dependency —
    the codec must stay loadable from stripped wire-only processes)."""
    key = f"wire_bytes_{direction}"
    counters.incr(key, n)
    if label:
        counters.incr(f"{key}[{label}]", n)
    try:
        from paddle_tpu.utils.timers import global_stats

        global_stats.incr(key, n)
    except Exception:  # noqa: BLE001 — timers plane not loaded
        pass


def send_msg(conn, obj: Any, max_bytes: Optional[int] = None,
             label: Optional[str] = None) -> None:
    """Encode + frame + send one message over a
    ``multiprocessing.connection`` Connection (or a netem wrapper)."""
    frame = encode_frame(encode_payload(obj), max_bytes)
    count_bytes("sent", len(frame), label)
    conn.send_bytes(frame)


def recv_msg(conn, max_bytes: Optional[int] = None,
             label: Optional[str] = None) -> Any:
    """Receive + verify + decode one message.  The recv-side size bound
    rides ``recv_bytes(maxlength)`` so an over-budget length prefix is
    refused BEFORE allocation (the transport closes the desynced stream;
    the structured :class:`WireOversizeError` tells the caller why)."""
    if max_bytes is None:
        max_bytes = default_max_bytes()
    try:
        buf = conn.recv_bytes(max_bytes)
    except OSError as exc:
        if "bad message length" in str(exc):
            raise WireOversizeError(
                f"inbound frame exceeds the {max_bytes}-byte bound (flag "
                f"rpc_max_message_mb) — refused before allocation, "
                f"connection dropped"
            ) from exc
        raise
    count_bytes("recv", len(buf), label)
    return decode_payload(decode_frame(buf, max_bytes))
