"""Training events — the ``paddle.v2.event`` surface (reference:
python/paddle/v2/event.py)."""

from __future__ import annotations

from typing import Any, Dict, Optional


class Event:
    pass


class TestResult(Event):
    def __init__(self, evaluator: Dict[str, float], cost: float):
        self.evaluator = evaluator
        self.cost = cost

    @property
    def metrics(self) -> Dict[str, float]:
        return self.evaluator


class BeginPass(Event):
    def __init__(self, pass_id: int):
        self.pass_id = pass_id


class EndPass(Event):
    def __init__(self, pass_id: int, evaluator: Optional[Dict[str, float]] = None):
        self.pass_id = pass_id
        self.evaluator = evaluator or {}


class BeginIteration(Event):
    def __init__(self, pass_id: int, batch_id: int):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(Event):
    def __init__(
        self,
        pass_id: int,
        batch_id: int,
        cost: float,
        evaluator: Optional[Dict[str, float]] = None,
    ):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.evaluator = evaluator or {}

    @property
    def metrics(self) -> Dict[str, Any]:
        return self.evaluator
