"""Trace-hygiene analyzer — jaxpr-level TPU hazard checks on the compiled
train/eval step.

The graph linter (``analysis.graph_lint``) sees the model *description*;
this pass sees what will actually be handed to XLA.  Because the whole step
is one traced program (core/compiler.py), the jaxpr is a complete static
dataflow graph of the computation — inspecting it is pure host-side
analysis, the ahead-of-time-validation property the TF/Julia-to-TPU papers
exploit (PAPERS.md).

Rules (``T###``):

  T101 f64-leak              float64 values or f64 convert_element_type in
                             the traced program (TPUs emulate f64 at ~1/20
                             throughput; usually a stray Python float with
                             x64 enabled)
  T102 const-captured-array  a large array baked into the jaxpr as a
                             CONSTANT instead of an argument (weights
                             captured by closure: re-shipped per compile,
                             cache-key churn, no donation)
  T103 host-callback         host callbacks / debug prints inside the hot
                             path (each one is a device→host sync)
  T104 off-ladder-shape      an observed batch shape whose padded sequence
                             extents sit off the bucketing ladder — every
                             such batch is its own jit cache entry
  T105 shape-explosion       distinct batch shapes exceed the ladder
                             budget: the step recompiles per batch instead
                             of per rung
  T106 undonated-carry       a large input buffer (params / opt-state /
                             any carried-state leaf) is returned updated
                             but NOT donated — XLA double-buffers it: 2x
                             HBM held and a device copy every step

``trace_step`` builds the jaxpr of a step function exactly as jit would see
it; ``recompile_audit`` replays a reader's observed batch shapes against the
``CompileShapeCache`` contract (core/compiler.py); ``donation_audit`` checks
the train step / epoch program's carried buffers are donated (T106).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.core.batch import (
    DEFAULT_LADDER,
    DEFAULT_SUB_LADDER,
    batch_shape_key,
)

# one device→host sync per step each; debug_print compiles to a callback
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "outside_call", "host_callback_call",
})

# elements; 64 KiB of f32 — parameters are (much) bigger, batch literals too
DEFAULT_CONST_ELEMS = 16384


def _walk_jaxprs(jaxpr) -> Iterable[Tuple[Any, List]]:
    """Yield (jaxpr, consts) for the closed jaxpr and every sub-jaxpr
    (scan/cond/while bodies, closed calls) it contains."""
    seen = set()

    def visit(j, consts):
        if id(j) in seen:
            return
        seen.add(id(j))
        yield j, consts
        for eqn in j.eqns:
            for v in eqn.params.values():
                for sub in _iter_jaxpr_params(v):
                    if hasattr(sub, "jaxpr"):  # ClosedJaxpr
                        yield from visit(sub.jaxpr, list(sub.consts))
                    else:
                        yield from visit(sub, [])

    closed = jaxpr
    if hasattr(closed, "jaxpr"):
        yield from visit(closed.jaxpr, list(closed.consts))
    else:
        yield from visit(closed, [])


def _iter_jaxpr_params(v):
    from jax.core import Jaxpr

    if hasattr(v, "jaxpr") or isinstance(v, Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _iter_jaxpr_params(x)


def _aval_dtype(var) -> Optional[np.dtype]:
    aval = getattr(var, "aval", None)
    dt = getattr(aval, "dtype", None)
    return np.dtype(dt) if dt is not None else None


def lint_jaxpr(
    jaxpr,
    *,
    const_elem_threshold: int = DEFAULT_CONST_ELEMS,
    source: Optional[str] = None,
) -> List[Diagnostic]:
    """Hazard-scan a (closed) jaxpr: T101 f64 leaks, T102 closure-captured
    array constants, T103 host callbacks.  Use ``jax.make_jaxpr(fn)(*args)``
    (or :func:`trace_step`) to obtain the jaxpr of the step exactly as
    ``jax.jit`` would trace it."""
    diags: List[Diagnostic] = []
    f64 = np.dtype(np.float64)
    f64_sites: List[str] = []
    callbacks: List[str] = []
    big_consts: List[str] = []

    for j, consts in _walk_jaxprs(jaxpr):
        for cv, cval in zip(getattr(j, "constvars", ()), consts):
            size = int(np.size(cval)) if hasattr(cval, "shape") else 0
            if size >= const_elem_threshold:
                dt = getattr(cval, "dtype", "?")
                big_consts.append(
                    f"{tuple(np.shape(cval))} {dt} ({size} elems)"
                )
            if _aval_dtype(cv) == f64:
                f64_sites.append(f"constant {tuple(np.shape(cval))}")
        for eqn in j.eqns:
            prim = eqn.primitive.name
            if prim in _CALLBACK_PRIMS:
                callbacks.append(prim)
            if any(_aval_dtype(v) == f64 for v in eqn.outvars):
                if prim == "convert_element_type":
                    f64_sites.append(f"{prim} -> float64")
                else:
                    f64_sites.append(f"{prim} output")

    if f64_sites:
        uniq = sorted(set(f64_sites))
        diags.append(Diagnostic(
            rule="T101", severity=Severity.ERROR, source=source,
            message=f"float64 values in the traced step: {uniq[:6]}"
            + (f" (+{len(uniq) - 6} more)" if len(uniq) > 6 else ""),
            hint="TPUs run f64 at a fraction of f32 throughput; find the "
            "promoting Python float / np.float64 literal, or keep "
            "jax_enable_x64 off for training steps",
        ))
    if big_consts:
        diags.append(Diagnostic(
            rule="T102", severity=Severity.WARNING, source=source,
            message="large arrays are baked into the jaxpr as constants "
            f"instead of arguments: {big_consts[:4]}"
            + (f" (+{len(big_consts) - 4} more)" if len(big_consts) > 4 else ""),
            hint="a closure captured weights/batch data at trace time — "
            "pass them as function arguments so the executable is "
            "shape-polymorphic over them and buffers can be donated",
        ))
    if callbacks:
        counts = {p: callbacks.count(p) for p in sorted(set(callbacks))}
        diags.append(Diagnostic(
            rule="T103", severity=Severity.WARNING, source=source,
            message=f"host callbacks inside the traced step: {counts}",
            hint="each callback is a device->host round-trip per step; "
            "strip debug_print/callback wrappers from the hot path",
        ))
    return diags


def trace_step(fn, *example_args, **example_kwargs):
    """The closed jaxpr of ``fn`` on the example arguments — exactly the
    program jit would compile for these shapes (abstract trace; no FLOPs,
    no device transfer)."""
    return jax.make_jaxpr(fn)(*example_args, **example_kwargs)


def lint_step(
    fn,
    *example_args,
    const_elem_threshold: int = DEFAULT_CONST_ELEMS,
    source: Optional[str] = None,
    **example_kwargs,
) -> List[Diagnostic]:
    """Trace ``fn`` on example args and hazard-scan the result."""
    return lint_jaxpr(
        trace_step(fn, *example_args, **example_kwargs),
        const_elem_threshold=const_elem_threshold,
        source=source,
    )


# ---------------------------------------------------------------------------
# buffer-donation audit (T106)
# ---------------------------------------------------------------------------


def donation_audit(
    fn,
    *example_args,
    donate_argnums: Optional[Sequence[int]] = None,
    carry_elem_threshold: int = DEFAULT_CONST_ELEMS,
    source: Optional[str] = None,
) -> List[Diagnostic]:
    """T106: flag large CARRIED buffers that are copied instead of donated.

    A train step / epoch program returns updated versions of its big
    inputs (params, optimizer slots, carried state).  When such an input
    is not donated, XLA cannot alias it into the matching output: the
    program holds BOTH generations in HBM (2x the carry) and spends a
    copy per dispatch.  The heuristic mirrors what XLA's aliasing pass
    needs: a non-donated input leaf of ``carry_elem_threshold``+ elements
    whose (shape, dtype) also appears among the outputs is a carried
    buffer that will be double-buffered.

    ``fn`` may be a jitted function — its own ``donate_argnums`` are read
    back out of the traced pjit equation, so the audit checks what jit
    will actually honor; for a plain function pass ``donate_argnums``
    explicitly (the jit spelling the builder intends)."""
    closed = trace_step(fn, *example_args)
    jaxpr = closed.jaxpr
    leaf_lists = [jax.tree_util.tree_leaves(a) for a in example_args]
    counts = [len(leaves) for leaves in leaf_lists]
    arg_of: List[int] = []
    for argnum, cnt in enumerate(counts):
        arg_of.extend([argnum] * cnt)
    if len(arg_of) != len(jaxpr.invars):
        return []  # kwargs/captured structure we can't map — stay silent

    donated: Optional[List[bool]] = None
    eqns = jaxpr.eqns
    if (
        len(eqns) == 1
        and eqns[0].primitive.name == "pjit"
        and "donated_invars" in eqns[0].params
        and list(eqns[0].invars) == list(jaxpr.invars)
    ):
        # a jitted fn traces to one pjit eqn; its donated_invars are the
        # flags jit will compile with — the ground truth
        donated = list(eqns[0].params["donated_invars"])
    if donated is None:
        dset = set(donate_argnums or ())
        donated = [argnum in dset for argnum in arg_of]

    out_avals: set = set()
    for v in jaxpr.outvars:
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "shape", None) is not None:
            out_avals.add((tuple(aval.shape), str(aval.dtype)))

    per_arg: Dict[int, List[str]] = {}
    per_arg_bytes: Dict[int, int] = {}
    for i, v in enumerate(jaxpr.invars):
        if donated[i]:
            continue
        aval = getattr(v, "aval", None)
        if aval is None or not getattr(aval, "shape", None):
            continue
        size = int(np.prod(aval.shape))
        if size < carry_elem_threshold:
            continue
        sig = (tuple(aval.shape), str(aval.dtype))
        if sig not in out_avals:
            continue  # read-only input (batch data): no copy to save
        per_arg.setdefault(arg_of[i], []).append(
            f"{sig[0]} {sig[1]}"
        )
        per_arg_bytes[arg_of[i]] = per_arg_bytes.get(arg_of[i], 0) + (
            size * np.dtype(aval.dtype).itemsize
        )

    diags: List[Diagnostic] = []
    for argnum in sorted(per_arg):
        shapes = per_arg[argnum]
        mb = per_arg_bytes[argnum] / 1e6
        diags.append(Diagnostic(
            rule="T106", severity=Severity.WARNING, source=source,
            message=(
                f"argument {argnum} carries {len(shapes)} large buffer(s) "
                f"({mb:.1f} MB) returned updated but NOT donated: "
                f"{shapes[:4]}"
                + (f" (+{len(shapes) - 4} more)" if len(shapes) > 4 else "")
            ),
            hint="add donate_argnums for carried state (params/opt-state/"
            "scan carries) so XLA aliases the buffers — an undonated "
            "carry is double-buffered: 2x HBM held and one device copy "
            "per dispatch",
        ))
    return diags


# ---------------------------------------------------------------------------
# recompile-churn audit (T104/T105)
# ---------------------------------------------------------------------------


def recompile_audit(
    observed,
    *,
    ladder: Sequence[int] = DEFAULT_LADDER,
    sub_ladder: Sequence[int] = DEFAULT_SUB_LADDER,
    max_shapes: Optional[int] = None,
    source: Optional[str] = None,
) -> List[Diagnostic]:
    """Replay observed batch shapes against the shape-ladder contract.

    ``observed`` is a ``CompileShapeCache`` (its ``.shapes`` keys), an
    iterable of feeder batches, or an iterable of ``batch_shape_key``
    results.  Each distinct key is one jit compile (the cache's miss
    accounting, core/compiler.py); a laddered feed keeps them bounded by
    rung combinations, so off-ladder extents and key explosion are the two
    churn signatures worth flagging.

    T104 flags only axes whose extent VARIES across the observed keys: a
    static extent (a dense feature width, a fixed batch size) compiles once
    no matter what it is, while a varying axis off the ladder means one
    compile per distinct length — the churn signature."""
    keys = _as_shape_keys(observed)
    rungs = set(ladder) | set(sub_ladder)
    diags: List[Diagnostic] = []

    # per (slot, axis>=1): the set of extents observed across keys
    extents: Dict[Tuple[str, int], set] = {}
    for key in keys:
        for name, shape, _dtype in key:
            for axis, ext in enumerate(shape):
                if axis >= 1:
                    extents.setdefault((name, axis), set()).add(int(ext))

    off: List[str] = []
    for (name, axis), vals in sorted(extents.items()):
        if len(vals) <= 1:
            continue  # static axis: one compile regardless of value
        bad = sorted(
            v for v in vals
            if v > 1 and v not in rungs and not _is_rung_multiple(v, ladder)
        )
        if bad:
            off.append(f"{name} axis {axis}: {bad}")
    if off:
        uniq = sorted(set(off))
        diags.append(Diagnostic(
            rule="T104", severity=Severity.WARNING, source=source,
            message=f"batch shapes pad off the bucketing ladder: {uniq[:5]}"
            + (f" (+{len(uniq) - 5} more)" if len(uniq) > 5 else ""),
            hint="route the feed through reader.bucketing + "
            "DataFeeder(ladder=...) (use_bucketing flag) so every padded "
            "extent is a 16*2^k rung and compiles stay bounded",
        ))

    budget = max_shapes if max_shapes is not None else max(8, 2 * len(ladder))
    if len(keys) > budget:
        diags.append(Diagnostic(
            rule="T105", severity=Severity.WARNING, source=source,
            message=f"{len(keys)} distinct batch shapes observed (budget "
            f"{budget}) — the step recompiles per batch, not per rung",
            hint="enable bucketing, pin drop_last=True, or tie the "
            "token-budget batcher to the dominant sequence slot so rung "
            "combinations collapse",
        ))
    return diags


def _is_rung_multiple(ext: int, ladder: Sequence[int]) -> bool:
    """Past the top rung, ladder_len canonicalizes to multiples of it."""
    top = ladder[-1] if ladder else 0
    return bool(top) and ext > top and ext % top == 0


def _as_shape_keys(observed) -> List[tuple]:
    shapes = getattr(observed, "shapes", None)
    if isinstance(shapes, dict):  # CompileShapeCache
        return list(shapes)
    keys = []
    for item in observed:
        if isinstance(item, tuple) and item and isinstance(item[0], tuple):
            keys.append(item)  # already a shape key
        else:
            keys.append(batch_shape_key(item))
    return list(dict.fromkeys(keys))
