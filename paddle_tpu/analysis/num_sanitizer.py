"""Divergence-localizing numerics sanitizer — the runtime leg of the
numerics plane.

The divergence sentinel (robustness/sentinel.py + the fused device half in
trainer/step.py) detects that a step went non-finite and skips it — but it
cannot say WHICH op produced the first NaN/inf, so a ``nan_batch`` chaos
drill ends as "a step was skipped" instead of "this feed slot poisoned
that dot".  This module closes the gap the way the lock sanitizer closed
it for deadlocks: armed via ``PADDLE_TPU_NUM_SANITIZER=1`` (the
``num_sanitizer`` flag), the trainer keeps a host copy of each step's
inputs BEFORE the donated dispatch consumes them, and when the sentinel
flags a step, the step's jaxpr is re-executed **equation by equation**
through a small interpreter on the captured batch:

* the first eqn whose output is non-finite is named, with layer
  provenance from the named-scope stack (the T100 note plane's
  vocabulary) and source provenance from ``eqn.source_info``;
* call-like eqns (pjit / custom-vjp), ``scan`` (stepped iteration by
  iteration) and ``cond`` (the taken branch) are descended into, so the
  record points at a primitive, not at "the scan";
* every input of the offending eqn gets max-abs / non-finite-count
  stats folded into StatSet ``num/<eqn>`` rows (the guarded
  ``StatSet.observe`` keeps non-finite observations in their own
  bucket), and the whole postmortem rides the PR-13 flight-recorder
  dump (``flight-<pid>.json``, ``otherData.numerics``).

Unarmed, the training path is untouched: no captures, no copies, no
extra dispatches — counter-asserted in tests (``num_sanitizer/captures``
stays zero) and byte-identical params either way (the sanitizer only
observes; it never changes the step).
"""

from __future__ import annotations

import logging
import math
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.utils.timers import global_stats

__all__ = ["NumericsSanitizer", "num_sanitizer_armed", "find_first_nonfinite"]

_log = logging.getLogger("paddle_tpu.analysis.num_sanitizer")

ENV_FLAG = "PADDLE_TPU_NUM_SANITIZER"


def num_sanitizer_armed() -> bool:
    """The ``num_sanitizer`` flag (environment: ``PADDLE_TPU_NUM_SANITIZER``);
    tolerant of a stripped flags plane."""
    try:
        from paddle_tpu.utils import flags as _flags

        return bool(_flags.get_flag("num_sanitizer"))
    except KeyError:  # pragma: no cover — stripped deployment
        return os.environ.get(ENV_FLAG, "").lower() in ("1", "true", "yes")


# ---------------------------------------------------------------------------
# the eqn-by-eqn interpreter
# ---------------------------------------------------------------------------


class _Found(Exception):
    """Raised by the interpreter at the first non-finite-producing eqn;
    carries the postmortem record."""

    def __init__(self, record: Dict[str, Any]):
        super().__init__(record.get("primitive", "?"))
        self.record = record


def _is_inexact(x) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is None:
        return False
    # jnp.issubdtype: ml_dtypes floats (bfloat16/f8) are not numpy
    # inexact subtypes, and a bf16 NaN must not slip past the check
    import jax.numpy as jnp

    return jnp.issubdtype(np.dtype(dt), jnp.inexact)


def _nonfinite(x) -> bool:
    if not _is_inexact(x):
        return False
    arr = np.asarray(x)
    return bool(arr.size) and not bool(np.isfinite(arr).all())


def _val_stats(x) -> Dict[str, Any]:
    """Shape/dtype/max-abs/non-finite-count summary of one value."""
    out: Dict[str, Any] = {
        "shape": list(np.shape(x)),
        "dtype": str(getattr(x, "dtype", type(x).__name__)),
    }
    try:
        arr = np.asarray(x, dtype=np.float64) if _is_inexact(x) else None
    except (TypeError, ValueError):
        arr = None
    if arr is not None and arr.size:
        finite = arr[np.isfinite(arr)]
        out["max_abs"] = float(np.abs(finite).max()) if finite.size else None
        out["n_nonfinite"] = int(arr.size - finite.size)
    return out


def _bind(eqn, invals: Sequence[Any]):
    subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
    outs = eqn.primitive.bind(*subfuns, *invals, **bind_params)
    return list(outs) if eqn.primitive.multiple_results else [outs]


def _call_prims() -> frozenset:
    """The lint's call-like primitive set — ONE list, so the lint seeing
    through a call and the postmortem localizing into it never diverge."""
    from paddle_tpu.analysis.numerics_lint import _INLINE_PRIMS

    return _INLINE_PRIMS


def _sub_closed_jaxprs(params: Dict[str, Any]):
    from paddle_tpu.analysis.numerics_lint import _sub_jaxprs

    return _sub_jaxprs(params)


def _record(eqn, invals, outs, path: str, idx: int) -> Dict[str, Any]:
    from paddle_tpu.analysis.numerics_lint import _eqn_layer, _eqn_site

    src, line = _eqn_site(eqn)
    return {
        "eqn": f"{path}{idx}:{eqn.primitive.name}",
        "primitive": eqn.primitive.name,
        "layer": _eqn_layer(eqn),
        "source": src,
        "line": line,
        "inputs": [_val_stats(x) for x in invals],
        "outputs": [_val_stats(x) for x in outs],
    }


def _eval_jaxpr(jaxpr, consts, args, path: str) -> List[Any]:
    """Evaluate ``jaxpr`` eqn by eqn; raises :class:`_Found` at the first
    eqn whose output holds a NaN/inf, after localizing INTO call-like /
    scan / cond eqns so the record names a primitive, not a region."""
    from jax.core import Literal

    env: Dict[Any, Any] = {}

    def read(v):
        return v.val if isinstance(v, Literal) else env[v]

    for var, val in zip(jaxpr.constvars, consts):
        env[var] = val
    for var, val in zip(jaxpr.invars, args):
        env[var] = val
    for idx, eqn in enumerate(jaxpr.eqns):
        invals = [read(v) for v in eqn.invars]
        outs = _bind(eqn, invals)
        if any(_nonfinite(o) for o in outs):
            raise _Found(_localize(eqn, invals, outs, path, idx))
        for var, val in zip(eqn.outvars, outs):
            env[var] = val
    return [read(v) for v in jaxpr.outvars]


def _localize(eqn, invals, outs, path: str, idx: int) -> Dict[str, Any]:
    prim = eqn.primitive.name
    here = f"{path}{idx}:{prim}/"
    try:
        if prim in _call_prims():
            for sub in _sub_closed_jaxprs(eqn.params):
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                if len(inner.invars) == len(invals):
                    try:
                        _eval_jaxpr(inner, list(getattr(sub, "consts", ())),
                                    invals, here)
                    except _Found as f:
                        return f.record
                    break
        elif prim == "scan":
            rec = _localize_scan(eqn, invals, here)
            if rec is not None:
                return rec
        elif prim == "cond":
            branches = eqn.params.get("branches", ())
            pred = int(np.asarray(invals[0]))
            if 0 <= pred < len(branches):
                sub = branches[pred]
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                if len(inner.invars) == len(invals) - 1:
                    try:
                        _eval_jaxpr(inner, list(getattr(sub, "consts", ())),
                                    invals[1:], here + f"branch{pred}/")
                    except _Found as f:
                        return f.record
    except _Found:
        raise
    except Exception:  # noqa: BLE001 — localization is best-effort
        _log.debug("sub-localization failed at %s%d:%s", path, idx, prim,
                   exc_info=True)
    return _record(eqn, invals, outs, path, idx)


def _localize_scan(eqn, invals, here: str) -> Optional[Dict[str, Any]]:
    """Step a scan's body iteration by iteration to find the first
    non-finite-producing step AND eqn inside it."""
    params = eqn.params
    sub = params.get("jaxpr")
    if sub is None:
        return None
    inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
    n_consts = int(params.get("num_consts", 0))
    n_carry = int(params.get("num_carry", 0))
    length = int(params.get("length", 0))
    reverse = bool(params.get("reverse", False))
    consts = invals[:n_consts]
    carry = list(invals[n_consts:n_consts + n_carry])
    xs = invals[n_consts + n_carry:]
    steps = range(length - 1, -1, -1) if reverse else range(length)
    for t in steps:
        xsl = [np.asarray(x)[t] for x in xs]
        try:
            outs = _eval_jaxpr(
                inner, list(getattr(sub, "consts", ())),
                list(consts) + carry + xsl, f"{here}step{t}/",
            )
        except _Found as f:
            f.record["scan_step"] = t
            return f.record
        carry = list(outs[:n_carry])
    return None


def find_first_nonfinite(fn, args) -> Optional[Dict[str, Any]]:
    """Trace ``fn`` on ``args`` and re-execute its jaxpr eqn-by-eqn;
    returns the postmortem record of the first non-finite-producing eqn
    (with ``poisoned_inputs`` naming any arg leaves that were ALREADY
    non-finite — the poisoned-feed case), or None when every value stays
    finite."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    flat: List[Any] = []
    labels: List[str] = []
    for argnum, a in enumerate(args):
        for pth, leaf in jax.tree_util.tree_leaves_with_path(a):
            flat.append(leaf)
            labels.append(f"arg{argnum}{jax.tree_util.keystr(pth)}")
    if len(flat) != len(closed.jaxpr.invars):
        flat = jax.tree_util.tree_leaves(args)
        labels = [f"in{i}" for i in range(len(flat))]
    poisoned = [
        {"input": lbl, **_val_stats(v)}
        for lbl, v in zip(labels, flat) if _nonfinite(v)
    ]
    try:
        _eval_jaxpr(closed.jaxpr, list(closed.consts), flat, "")
    except _Found as f:
        rec = f.record
        rec["poisoned_inputs"] = poisoned
        return rec
    if poisoned:
        # inputs were poisoned but nothing downstream blew up (masked away)
        return {"eqn": None, "primitive": None, "poisoned_inputs": poisoned,
                "inputs": [], "outputs": []}
    return None


# ---------------------------------------------------------------------------
# the trainer-facing sanitizer
# ---------------------------------------------------------------------------


class NumericsSanitizer:
    """Pre-step input capture + postmortem driver for one trainer.

    ``step_body`` is the UN-jitted single-step computation (the same
    ``_train_step_body`` the jitted step compiles), traced fresh on the
    captured arguments — host-side re-execution, no donation, no effect
    on the training trajectory."""

    def __init__(self, step_body, stats=None):
        self._step_body = step_body
        self._stats = stats if stats is not None else global_stats
        self._captured = None
        self._where = ""

    @classmethod
    def for_trainer(cls, trainer) -> "NumericsSanitizer":
        from paddle_tpu.trainer.step import _train_step_body

        # sentinel=False: the postmortem wants the raw computation — the
        # per-leaf select that protects params on device would otherwise
        # sit between the first NaN and the metrics
        body = _train_step_body(
            trainer.network, trainer.optimizer, trainer._metrics_fn,
            trainer._prune_masks, sentinel=False,
        )
        return cls(body)

    def capture(self, params, state, opt_state, batch, rng,
                where: str = "") -> None:
        """Host-copy this step's inputs BEFORE the donated dispatch
        invalidates them.  Armed-mode cost only; the unarmed trainer
        never constructs this object."""
        import jax

        self._stats.incr("num_sanitizer/captures")
        self._captured = jax.device_get((params, state, opt_state, batch, rng))
        self._where = where

    def postmortem(self, reason: str) -> Optional[Dict[str, Any]]:
        """Re-execute the captured step eqn-by-eqn and dump the numerics
        postmortem into the flight recorder.  Never raises."""
        if self._captured is None:
            return None
        try:
            rec = find_first_nonfinite(self._step_body, self._captured)
        except Exception:  # noqa: BLE001 — a postmortem must never crash
            _log.exception("numerics postmortem failed (%s)", reason)
            return None
        if rec is None:
            _log.warning(
                "numerics sanitizer: %s but the re-executed step is "
                "finite everywhere (non-determinism or fetch-side issue)",
                reason,
            )
            return None
        rec["reason"] = reason
        rec["where"] = self._where
        tag = rec.get("eqn") or "input-only"
        for j, s in enumerate(rec.get("inputs", ())):
            if s.get("max_abs") is not None:
                self._stats.observe(f"num/{tag}/in{j}_max_abs", s["max_abs"])
            if s.get("n_nonfinite"):
                self._stats.observe(f"num/{tag}/in{j}_max_abs", math.nan)
        _log.error(
            "numerics postmortem (%s): first non-finite at %s layer=%s "
            "%s:%s poisoned=%s", reason, tag, rec.get("layer"),
            rec.get("source"), rec.get("line"),
            [p["input"] for p in rec.get("poisoned_inputs", ())],
        )
        from paddle_tpu import obs as _obs

        _obs.flight_dump(f"num-sanitizer: {reason}",
                         extra={"numerics": rec})
        return rec
