"""Runtime lock-order sanitizer — the dynamic leg of the concurrency plane.

The static lint (:mod:`~paddle_tpu.analysis.concurrency_lint`) infers lock
discipline from the source; this module *watches* it at runtime.  With
``PADDLE_TPU_LOCK_SANITIZER=1`` in the environment, every lock the package
constructs through :func:`make_lock` / :func:`make_rlock` is instrumented:

  * a per-thread held-lock stack (reentrant acquisitions counted, never
    double-pushed — an RLock re-enter is NOT an ordering event);
  * a global acquisition-order edge set: first time any thread acquires
    lock B while holding lock A, the edge ``A -> B`` is recorded together
    with the acquiring stack.  Before blocking on B, the sanitizer checks
    whether a ``B -> ... -> A`` path already exists — a cycle means two
    threads can interleave into a deadlock, and :class:`DeadlockReport`
    raises *immediately* (at the acquisition that would close the cycle,
    not after the drill wedges) carrying BOTH acquisition stacks: the one
    that recorded the conflicting order and the one attempting it now;
  * held-time value stats ride the existing StatSet plane
    (``utils.timers.global_stats`` keys ``lock_held/<name>``), so the
    chaos drills' stat dumps show which locks are contended and for how
    long.

With the env flag unset the factories return plain ``threading`` primitives
— zero overhead, zero import cost (this module never imports jax, so the
jax-free ``paddle-tpu master`` process can use it).

``make chaos`` exports the flag, turning every failover / kill-one-of-N
fleet drill into a lock-order race detector run; the reader-teardown leak
tests use :func:`thread_report` (alive ``paddle-*`` worker threads) the
same way.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "DeadlockReport",
    "SanitizedLock",
    "edges",
    "held_report",
    "make_lock",
    "make_rlock",
    "reset",
    "sanitizer_enabled",
    "thread_report",
]

ENV_FLAG = "PADDLE_TPU_LOCK_SANITIZER"

# every thread the package spawns is named with this prefix so leak checks
# (and humans reading `py-spy dump`) can attribute it
THREAD_PREFIX = "paddle-"


def sanitizer_enabled() -> bool:
    """True when the environment arms the sanitizer (``=1``/anything truthy;
    ``0``/``false``/``off``/empty disarm)."""
    return os.environ.get(ENV_FLAG, "").lower() not in ("", "0", "false", "off")


class DeadlockReport(RuntimeError):
    """A lock acquisition would close a cycle in the acquisition-order
    graph.  ``cycle`` is the lock-name path ``[B, ..., A, B]``;
    ``this_stack`` is where the offending acquisition is happening,
    ``other_stack`` where the conflicting order was first recorded."""

    def __init__(self, cycle: List[str], this_stack: str, other_stack: str):
        self.cycle = cycle
        self.this_stack = this_stack
        self.other_stack = other_stack
        super().__init__(
            "lock-order cycle: " + " -> ".join(cycle)
            + "\n--- acquisition closing the cycle (this thread) ---\n"
            + this_stack
            + "--- first acquisition of the conflicting order ---\n"
            + other_stack
        )


def _stack() -> str:
    # drop the two sanitizer frames so the report starts at the caller
    return "".join(traceback.format_stack()[:-2])


class _Registry:
    """Global acquisition-order graph + per-thread held stacks.

    Guarded by a RAW ``threading.Lock`` (never a SanitizedLock: the
    registry must not observe itself) with short, non-blocking critical
    sections — the registry lock is always innermost and never held across
    a user lock acquisition, so it cannot participate in any cycle it
    reports."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (holder_name, acquired_name) -> stack of the acquisition that
        # first recorded this order
        self._edges: Dict[Tuple[str, str], str] = {}
        self._graph: Dict[str, Set[str]] = {}
        # thread ident -> [ [lock, reenter_count, t_acquired], ... ]
        self._held: Dict[int, List[List]] = {}

    # -- per-thread stack ------------------------------------------------
    def _stack_of(self, ident: int) -> List[List]:
        with self._mu:
            return list(self._held.get(ident, ()))

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst in the edge graph (DFS), or None.
        Caller holds ``_mu``."""
        seen = {src}
        trail = [(src, [src])]
        while trail:
            node, path = trail.pop()
            if node == dst:
                return path
            for nxt in self._graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    trail.append((nxt, path + [nxt]))
        return None

    def before_acquire(self, lock: "SanitizedLock") -> None:
        """Record ordering edges held -> lock; raise DeadlockReport when an
        inverse path already exists.  Runs BEFORE the blocking acquire so a
        true deadlock is reported instead of wedging the drill."""
        ident = threading.get_ident()
        with self._mu:
            held = self._held.get(ident, ())
            for entry in held:
                holder = entry[0]
                if holder is lock:
                    return  # reentrant re-acquire: not an ordering event
            for entry in held:
                holder = entry[0]
                if holder.name == lock.name:
                    # a DIFFERENT lock object under the same name (two
                    # instances of one class): the name-keyed graph cannot
                    # order them — skip rather than fabricate a self-edge
                    # (instance-level ABBA between same-named siblings is
                    # the static lint's C303 territory)
                    continue
                key = (holder.name, lock.name)
                if key in self._edges:
                    continue
                inverse = self._path(lock.name, holder.name)
                if inverse is not None:
                    other = self._edges.get(
                        (inverse[0], inverse[1]), "<unrecorded>\n"
                    )
                    raise DeadlockReport(
                        [holder.name] + inverse, _stack(), other
                    )
                self._edges[key] = _stack()
                self._graph.setdefault(holder.name, set()).add(lock.name)

    def on_acquired(self, lock: "SanitizedLock") -> None:
        ident = threading.get_ident()
        with self._mu:
            held = self._held.setdefault(ident, [])
            for entry in held:
                if entry[0] is lock:
                    entry[1] += 1
                    return
            held.append([lock, 1, time.perf_counter()])

    def on_released(self, lock: "SanitizedLock") -> Optional[float]:
        """Pop (or decrement) the entry; returns held seconds on the final
        release, None on a reentrant pop."""
        ident = threading.get_ident()
        with self._mu:
            held = self._held.get(ident, [])
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is lock:
                    held[i][1] -= 1
                    if held[i][1] == 0:
                        _, _, t0 = held.pop(i)
                        if not held:
                            self._held.pop(ident, None)
                        return time.perf_counter() - t0
                    return None
        return None

    def held_report(self) -> Dict[str, List[str]]:
        """Currently held sanitized locks per live thread (name -> lock
        names, innermost last) — the drill-teardown leak check."""
        by_ident = {t.ident: t.name for t in threading.enumerate()}
        with self._mu:
            return {
                by_ident.get(ident, f"thread-{ident}"): [e[0].name for e in held]
                for ident, held in self._held.items()
                if held
            }

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._graph.clear()
            self._held.clear()


_registry = _Registry()


class SanitizedLock:
    """Instrumented Lock/RLock: ordering edges + held-time stats.  Same
    acquire/release/context-manager surface as the wrapped primitive."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._lk = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _registry.before_acquire(self)
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _registry.on_acquired(self)
        return ok

    def release(self) -> None:
        self._lk.release()  # raises on misuse BEFORE the registry pops
        dt = _registry.on_released(self)
        if dt is not None:
            # lazy: utils.timers is stdlib-only, but keep the import off
            # the module path so a half-initialized package can still lock
            from paddle_tpu.utils.timers import global_stats

            global_stats.observe(f"lock_held/{self.name}", dt)

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked() if not self.reentrant else False

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} reentrant={self.reentrant}>"


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented when the sanitizer env flag is
    armed.  ``name`` is the stable identity in cycle reports and held-time
    stats (convention: ``Module.Class.attr``)."""
    if sanitizer_enabled():
        return SanitizedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — instrumented when armed; reentrant
    re-acquisition is recognized and never reported as an ordering event."""
    if sanitizer_enabled():
        return SanitizedLock(name, reentrant=True)
    return threading.RLock()


def held_report() -> Dict[str, List[str]]:
    """Sanitized locks currently held, per thread — empty after a clean
    teardown."""
    return _registry.held_report()


def edges() -> Dict[Tuple[str, str], str]:
    """The observed acquisition-order edge set (for tests/debugging)."""
    return _registry.edges()


def reset() -> None:
    """Clear the global graph + held stacks (test isolation)."""
    _registry.reset()


def thread_report(prefix: str = THREAD_PREFIX) -> List[str]:
    """Names of alive package worker threads (``paddle-*`` by the naming
    convention) — the reader/prefetcher teardown leak check: after every
    close/stop this must come up empty."""
    return sorted(
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(prefix)
    )
