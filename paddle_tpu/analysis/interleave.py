"""Deterministic interleaving explorer over the distributed planes.

The DYNAMIC leg of the protocol conformance plane (the static leg is
:mod:`~paddle_tpu.analysis.protocol_lint`): instead of asserting the
drill invariants (zero double-serve, epoch-fenced acks, single fenced
leader, journal replay == live state) along the N interleavings the
hand-written chaos drills happen to exercise, this module SEARCHES the
schedule space — the MODIST/TLA-lineage answer ROADMAP item 4(b) names.

Three properties make the search honest:

* **Real state machines.**  Each :class:`Model` drives the production
  code — ``serving.router.Router`` (``address=None``, injected
  ``client_factory``), ``master.Service`` (journaled), and
  ``master_ha.LeaseFile`` — never a re-implementation.  A bug found
  here is a bug in the shipping protocol.
* **Virtual time, zero threads.**  Clocks and sleeps are injected
  (:class:`VirtualClock`; the PR-5 injectable-clock discipline), the
  router's poll thread is parked, and every event applies synchronously
  on the explorer's thread — a schedule is a pure function of its event
  list, so the same seed replays bit-identically forever.
* **Faults are events.**  The PR-15 fault vocabulary (drop / lost reply
  = executed-but-unacked / duplicate submit / partition / heal /
  crash-restart of engines, routers, masters / clock advance = lease
  expiry) is part of each model's enabled-event set, so the scheduler
  interleaves faults with protocol steps instead of bolting them on.

Exploration is seeded-random (``explore_schedules``) or bounded-DFS
(``dfs_explore``); any violating schedule is SHRUNK by delta debugging
(:func:`shrink_events`) to a minimal event list and emitted as a
JSON spec replayable forever via ``paddle-tpu explore --replay
<spec.json>`` (:func:`replay_spec`) — a found bug becomes a one-file
regression test, not a flaky repro recipe.

``planted="double_serve"`` arms the acceptance canary: the router's
journal silently drops ``done`` records, so a crash-restart forgets
settled requests and a client retry re-serves one — the explorer must
detect it, shrink it to <= 6 events (submit → crash → restart → retry)
and replay the spec.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import tempfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "VirtualClock",
    "Model",
    "RouterModel",
    "MasterModel",
    "LeaseModel",
    "MODELS",
    "make_model",
    "run_schedule",
    "explore_schedules",
    "dfs_explore",
    "shrink_events",
    "replay_spec",
]

# Events are plain JSON dicts: {"op": <name>, ...params}.  Their JSON
# dump (sorted keys) is the identity used by DFS branching and shrinking.


def event_key(ev: Dict[str, Any]) -> str:
    return json.dumps(ev, sort_keys=True)


class VirtualClock:
    """Deterministic time: callable (the ``clock=`` injection point) and
    a ``sleep`` whose only effect is advancing it — a schedule never
    touches wall time."""

    def __init__(self, t: float = 1000.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)

    def sleep(self, dt: float) -> None:
        self.advance(dt)


class Model:
    """One explorable protocol plane.  Subclasses own real production
    state machines and expose them as an event-enabled transition system:

    * ``reset()``        — fresh incarnation under the model's workdir
    * ``enabled()``      — the currently-applicable events (JSON dicts)
    * ``apply(event)``   — perform one event synchronously
    * ``check()``        — invariant violations AFTER the last event
    * ``finish()``       — end-of-schedule (deep/expensive) invariants
    * ``close()``        — tear down OS resources

    ``apply`` may itself record violations into ``self.violations`` for
    hazards only visible at the call boundary (a stale ack accepted, a
    renew that lied)."""

    name = "model"

    def __init__(self, workdir: str, planted: Optional[str] = None):
        self.workdir = workdir
        self.planted = planted
        self.violations: List[str] = []

    # -- transition-system surface ----------------------------------------
    def reset(self) -> None:
        raise NotImplementedError

    def enabled(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def apply(self, event: Dict[str, Any]) -> None:
        raise NotImplementedError

    def check(self) -> List[str]:
        return []

    def finish(self) -> List[str]:
        return []

    def close(self) -> None:
        pass

    # -- shared helpers ----------------------------------------------------
    def applicable(self, event: Dict[str, Any]) -> bool:
        key = event_key(event)
        return any(event_key(e) == key for e in self.enabled())

    def drain_violations(self) -> List[str]:
        out, self.violations = self.violations, []
        return out


# ---------------------------------------------------------------------------
# Router model — the serving fleet's zero-double-serve contract
# ---------------------------------------------------------------------------

class _SimEngine:
    def __init__(self, engine_id: str, port: int):
        self.engine_id = engine_id
        self.port = port
        self.alive = True
        self.partitioned = False
        self.drop_next_reply = False


class _SimEngineClient:
    """The router->engine data plane over virtual transport: executes the
    request on the sim engine (recording the execution tick — the
    double-serve evidence trail) and injects the PR-15 fault vocabulary:
    a dead/partitioned engine raises before executing; an armed
    ``drop_next_reply`` raises AFTER executing (the at-least-once hazard
    the ledger must absorb)."""

    def __init__(self, model: "RouterModel", address):
        from paddle_tpu import master as _master

        self._m = model
        self._master = _master
        self._engine = model.engine_by_port(int(address[1]))

    def _check_up(self):
        e = self._engine
        if e is None or not e.alive or e.partitioned:
            raise self._master.MasterTransportError(
                "sim engine unreachable")

    def serve(self, req_id, src_ids, max_new_tokens=None, deadline_s=None,
              beam_size=None, session_id=None, priority=None):
        self._check_up()
        m = self._m
        m.tick += 1
        m.executions.append((m.tick, str(req_id), self._engine.engine_id))
        if self._engine.drop_next_reply:
            self._engine.drop_next_reply = False
            raise self._master.MasterTransportError(
                "reply lost after execution")
        return {
            "req_id": str(req_id), "status": "served",
            "tokens": [int(x) + 1 for x in src_ids], "error": None,
        }

    def stats(self):
        self._check_up()
        return {}

    def drain(self, timeout_s=0.0):
        return True

    def ping(self):
        return "pong"

    def close(self):
        pass


class RouterModel(Model):
    """Real ``serving.router.Router`` (no sockets, parked poll thread,
    virtual clock) over simulated engines.

    Invariant (the PR-18 drill contract, now schedule-searched): once a
    request id is SETTLED (its first non-duplicate terminal result), no
    engine may execute it again — across re-routes, retries, engine
    crashes, partitions AND router crash-restarts recovering the ledger
    from the journal."""

    name = "router"
    REQS = ("q1", "q2", "q3")
    ENGINES = ("e1", "e2")

    def __init__(self, workdir: str, planted: Optional[str] = None):
        super().__init__(workdir, planted)
        self.router = None
        self._incarnation = 0

    # -- lifecycle ---------------------------------------------------------
    def reset(self) -> None:
        self.close()
        self.violations = []
        self.clock = VirtualClock()
        self.tick = 0
        self.executions: List[Tuple[int, str, str]] = []
        self.settled: Dict[str, Tuple[int, str]] = {}
        self.submitted: set = set()
        self.results: List[Dict[str, Any]] = []
        self.engines: Dict[str, _SimEngine] = {}
        self._incarnation += 1
        inc_dir = os.path.join(self.workdir, f"run{self._incarnation}")
        os.makedirs(inc_dir, exist_ok=True)
        self.journal_path = os.path.join(inc_dir, "router.journal")
        self._start_router()
        for i, eid in enumerate(self.ENGINES):
            self.engines[eid] = _SimEngine(eid, 9000 + i)
            self.router.register_engine(eid, "sim", 9000 + i)

    def _start_router(self) -> None:
        from paddle_tpu.serving.router import Router

        r = Router(
            address=None,
            journal_path=self.journal_path,
            clock=self.clock,
            sleep=self.clock.sleep,
            stats_poll_s=1e9,          # park the poll thread: zero async
            lease_timeout_s=5.0,
            queue_limit=16,
            default_deadline_s=0.0,    # no implicit deadlines
            affinity=False,
            call_timeout_s=5.0,
            client_factory=lambda addr, t: _SimEngineClient(self, addr),
        )
        if self.planted == "double_serve":
            # the acceptance canary: the journal silently drops "done"
            # records, so a failed-over router forgets settled ids and a
            # client retry re-serves one — detect, shrink, replay
            orig = r._journal

            def dropping(rec, _orig=orig):
                if rec.get("t") != "done":
                    _orig(rec)

            r._journal = dropping
        self.router = r

    def _crash_router(self) -> None:
        """Crash semantics, not shutdown: the journal file handle drops
        dead (no close-time "leave" records) and then the incarnation is
        torn down without journaling anything further."""
        r = self.router
        with r._jlock:
            if r._jfile is not None:
                try:
                    r._jfile.close()
                except OSError:
                    pass
                r._jfile = None
        r.close()  # journals nothing (jfile gone); joins the poll thread
        self.router = None

    def close(self) -> None:
        if self.router is not None:
            self._crash_router()
        self.engines = {}

    def engine_by_port(self, port: int) -> Optional[_SimEngine]:
        for e in self.engines.values():
            if e.port == port:
                return e
        return None

    # -- transition system -------------------------------------------------
    def enabled(self) -> List[Dict[str, Any]]:
        evs: List[Dict[str, Any]] = []
        up = self.router is not None
        if up:
            for q in self.REQS:
                if q not in self.submitted:
                    evs.append({"op": "submit", "req": q})
            for q in sorted(self.settled):
                evs.append({"op": "retry", "req": q})
            evs.append({"op": "crash_router"})
        else:
            evs.append({"op": "restart_router"})
        for eid in sorted(self.engines):
            e = self.engines[eid]
            if e.alive:
                evs.append({"op": "crash_engine", "engine": eid})
                if not e.partitioned:
                    if self.router is not None:
                        evs.append({"op": "heartbeat", "engine": eid})
                    evs.append({"op": "drop_reply", "engine": eid})
                    evs.append({"op": "partition", "engine": eid})
                else:
                    evs.append({"op": "heal", "engine": eid})
            else:
                evs.append({"op": "restart_engine", "engine": eid})
        evs.append({"op": "advance", "dt": 3.0})
        return evs

    def _serve(self, req: str) -> Dict[str, Any]:
        res = self.router.serve(req, [1, 2, 3])
        self.results.append(res)
        if not res.get("duplicate") and req not in self.settled:
            self.settled[req] = (self.tick, res["status"])
        return res

    def apply(self, event: Dict[str, Any]) -> None:
        op = event["op"]
        if op == "submit":
            self.submitted.add(event["req"])
            self._serve(event["req"])
        elif op == "retry":
            self._serve(event["req"])
        elif op == "crash_engine":
            self.engines[event["engine"]].alive = False
        elif op == "restart_engine":
            e = self.engines[event["engine"]]
            e.alive = True
            e.partitioned = False
            if self.router is not None:
                self.router.register_engine(e.engine_id, "sim", e.port)
        elif op == "heartbeat":
            # the agent's renew loop: an expired lease re-registers
            e = self.engines[event["engine"]]
            if not self.router.heartbeat(e.engine_id):
                self.router.register_engine(e.engine_id, "sim", e.port)
        elif op == "partition":
            self.engines[event["engine"]].partitioned = True
        elif op == "heal":
            self.engines[event["engine"]].partitioned = False
        elif op == "drop_reply":
            self.engines[event["engine"]].drop_next_reply = True
        elif op == "crash_router":
            self._crash_router()
        elif op == "restart_router":
            self._start_router()
            # surviving engines re-register with the new incarnation
            # (their agents' heartbeat loop does this in production)
            for e in self.engines.values():
                if e.alive:
                    self.router.register_engine(e.engine_id, "sim", e.port)
        elif op == "advance":
            self.clock.advance(event["dt"])
        else:  # pragma: no cover - scheduler only draws from enabled()
            raise ValueError(f"unknown router event {op!r}")

    def check(self) -> List[str]:
        out = self.drain_violations()
        from paddle_tpu.serving.router import _TERMINAL

        for tick, req, eid in self.executions:
            s = self.settled.get(req)
            if s is not None and tick > s[0]:
                out.append(
                    f"double-serve: request {req!r} executed on engine "
                    f"{eid!r} (tick {tick}) AFTER being settled as "
                    f"{s[1]!r} at tick {s[0]}"
                )
        for res in self.results:
            if res["status"] not in _TERMINAL:
                out.append(
                    f"non-terminal ledger status {res['status']!r} for "
                    f"{res['req_id']!r}"
                )
        return out

    def finish(self) -> List[str]:
        return self.check()


# ---------------------------------------------------------------------------
# Master model — epoch-fenced leases + journal replay == live state
# ---------------------------------------------------------------------------

class MasterModel(Model):
    """Real journaled ``master.Service`` with virtual workers.

    Invariants: task-set conservation (todo+pending+done+discarded is
    constant under every interleaving of leases, acks, failures, lease
    expiries and crash-restarts); epoch fencing (an ack carrying a
    superseded epoch must be REFUSED — the mirror tracks the newest
    leased epoch per task); recovery fidelity (a restart from
    snapshot+journal reproduces the live fingerprint exactly)."""

    name = "master"
    WORKERS = ("w0", "w1")

    def __init__(self, workdir: str, planted: Optional[str] = None):
        super().__init__(workdir, planted)
        self.svc = None
        self._incarnation = 0

    # -- lifecycle ---------------------------------------------------------
    def reset(self) -> None:
        self.close()
        self.violations = []
        self.clock = VirtualClock()
        self._incarnation += 1
        self.dir = os.path.join(self.workdir, f"m{self._incarnation}")
        os.makedirs(self.dir, exist_ok=True)
        data = os.path.join(self.dir, "d.rio")
        from paddle_tpu.io import recordio

        recordio.write_records(
            data, (f"{i}".encode() for i in range(80)),
            max_chunk_records=10,
        )
        self.svc = self._make_service()
        self.svc.set_dataset([data])
        self.total = self.svc.n_tasks()
        for w in self.WORKERS:
            self.svc.register_worker(w)
        self.holding: Dict[str, Tuple[int, int]] = {}
        self.lease_epoch: Dict[int, int] = {}
        self.finished: List[Tuple[int, int]] = []

    def _make_service(self):
        from paddle_tpu import master as _master

        return _master.Service(
            snapshot_path=os.path.join(self.dir, "snap.json"),
            clock=self.clock,
            chunks_per_task=2,
            auto_rotate=False,
            journal=True,
            journal_fsync=False,
            timeout_s=5.0,
            failure_max=3,
            worker_timeout_s=1e9,  # registry churn is its own event space
        )

    def close(self) -> None:
        if self.svc is not None:
            try:
                self.svc.close()
            except Exception:  # noqa: BLE001 — teardown of a crashed twin
                pass
            self.svc = None

    # -- transition system -------------------------------------------------
    def enabled(self) -> List[Dict[str, Any]]:
        evs: List[Dict[str, Any]] = []
        for w in self.WORKERS:
            evs.append({"op": "get", "worker": w})
            if w in self.holding:
                evs.append({"op": "finish", "worker": w})
                evs.append({"op": "fail", "worker": w})
                evs.append({"op": "ret", "worker": w})
        if self.finished:
            evs.append({"op": "stale_ack"})
        evs.append({"op": "advance", "dt": 6.0})  # past the task lease
        evs.append({"op": "restart"})
        return evs

    def apply(self, event: Dict[str, Any]) -> None:
        import numpy as np

        op = event["op"]
        if op == "get":
            w = event["worker"]
            got = self.svc.get_task(w)
            if isinstance(got, dict):
                tid = int(got["task"]["task_id"])
                epoch = int(got["epoch"])
                self.holding[w] = (tid, epoch)
                self.lease_epoch[tid] = max(
                    self.lease_epoch.get(tid, epoch), epoch)
        elif op in ("finish", "fail", "ret"):
            w = event["worker"]
            tid, epoch = self.holding.pop(w)
            if op == "finish":
                ok = self.svc.task_finished(
                    tid, epoch,
                    {"g": np.arange(4, dtype=np.float32) + tid, "rows": 10},
                )
                if ok:
                    self.finished.append((tid, epoch))
            elif op == "fail":
                ok = self.svc.task_failed(tid, epoch)
            else:
                ok = self.svc.task_returned(tid, epoch)
            if ok and self.lease_epoch.get(tid, epoch) > epoch:
                self.violations.append(
                    f"epoch fence breached: {op} of task {tid} accepted "
                    f"at stale epoch {epoch} (newest lease is epoch "
                    f"{self.lease_epoch[tid]})"
                )
        elif op == "stale_ack":
            # A client retry re-sends an already-landed (task, epoch) ack —
            # the reply-lost case.  task_finished deliberately accepts the
            # duplicate (at-least-once ack delivery), so the invariant is
            # state-INVARIANCE, not rejection: the queue fingerprint must
            # not move and the first delivery's result payload must win
            # (the duplicate carries a zeros payload, so any clobbering
            # is bit-detectable).
            tid, epoch = self.finished[-1]
            fp = self._fingerprint()
            ok = self.svc.task_finished(
                tid, epoch,
                {"g": np.zeros(4, dtype=np.float32), "rows": 0},
            )
            if not ok:
                self.violations.append(
                    f"duplicate ack rejected: task {tid} epoch {epoch} — "
                    f"a reply-lost retry must be accepted-and-deduped, "
                    f"not bounced into a recompute"
                )
            if self._fingerprint() != fp:
                self.violations.append(
                    f"duplicate ack mutated queue state: task {tid} "
                    f"epoch {epoch}"
                )
            stored = self.svc.results.get(self.svc.pass_id, {}).get(tid)
            if stored is not None and stored.get("rows") == 0:
                self.violations.append(
                    f"duplicate ack clobbered the landed result of task "
                    f"{tid}: zeros payload overwrote the original"
                )
        elif op == "advance":
            self.clock.advance(event["dt"])
        elif op == "restart":
            fp = self._fingerprint()
            self.svc.fence()
            self.svc = self._make_service()  # recovers snapshot+journal
            if self._fingerprint() != fp:
                self.violations.append(
                    "recovery infidelity: snapshot+journal replay does "
                    "not reproduce the live queue state"
                )
        else:  # pragma: no cover - scheduler only draws from enabled()
            raise ValueError(f"unknown master event {op!r}")

    def _fingerprint(self) -> Dict[str, Any]:
        svc = self.svc
        with svc._lock:
            return {
                "pass_id": svc.pass_id,
                "todo": sorted((t.task_id, t.epoch) for t in svc.todo),
                "pending": sorted(
                    (tid, ent[0].epoch) for tid, ent in svc.pending.items()
                ),
                "done": sorted((t.task_id, t.epoch) for t in svc.done),
                "discarded": sorted(t.task_id for t in svc.discarded),
                "fail_events": svc.fail_events,
            }

    def check(self) -> List[str]:
        out = self.drain_violations()
        svc = self.svc
        with svc._lock:
            n = (len(svc.todo) + len(svc.pending) + len(svc.done)
                 + len(svc.discarded))
        if n != self.total:
            out.append(
                f"task-set conservation broken: todo+pending+done+"
                f"discarded = {n}, expected {self.total}"
            )
        return out

    def finish(self) -> List[str]:
        out = self.check()
        fp = self._fingerprint()
        self.svc.fence()
        self.svc = self._make_service()
        if self._fingerprint() != fp:
            out.append(
                "recovery infidelity at end of schedule: snapshot+journal "
                "replay does not reproduce the live queue state"
            )
        return out


# ---------------------------------------------------------------------------
# Lease model — HA leader election fencing
# ---------------------------------------------------------------------------

class LeaseModel(Model):
    """Real ``master_ha.LeaseFile`` candidates on one shared directory
    under a virtual clock.

    Invariants: ``renew()`` must never report success to a usurped
    owner (that would be TWO fenced writers); ``release()`` by a
    non-owner must not delete the owner's lease; at most one candidate
    passes ``held_by_me()`` at any instant."""

    name = "ha"
    CANDS = ("a", "b")

    def __init__(self, workdir: str, planted: Optional[str] = None):
        super().__init__(workdir, planted)
        self._incarnation = 0

    def reset(self) -> None:
        self.violations = []
        self.clock = VirtualClock()
        self._incarnation += 1
        d = os.path.join(self.workdir, f"ha{self._incarnation}")
        os.makedirs(d, exist_ok=True)
        from paddle_tpu.master_ha import LeaseFile

        self.leases = {
            c: LeaseFile(d, c, lease_timeout=5.0, clock=self.clock,
                         sleep=self.clock.sleep)
            for c in self.CANDS
        }
        self.believes = {c: False for c in self.CANDS}

    def enabled(self) -> List[Dict[str, Any]]:
        evs: List[Dict[str, Any]] = []
        for c in self.CANDS:
            evs.append({"op": "acquire", "cand": c})
            if self.believes[c]:
                evs.append({"op": "renew", "cand": c})
                evs.append({"op": "release", "cand": c})
        evs.append({"op": "advance", "dt": 3.0})
        return evs

    def apply(self, event: Dict[str, Any]) -> None:
        op, c = event["op"], event.get("cand")
        if op == "acquire":
            self.believes[c] = self.leases[c].try_acquire()
        elif op == "renew":
            ok = self.leases[c].renew()
            self.believes[c] = ok
            if ok and self.leases[c].current_owner() != c:
                self.violations.append(
                    f"fence breach: renew() by {c!r} reported success "
                    f"while {self.leases[c].current_owner()!r} owns the "
                    f"lease (two writers believe they are fenced in)"
                )
        elif op == "release":
            owner_before = self.leases[c].current_owner()
            self.leases[c].release()
            self.believes[c] = False
            owner_after = self.leases[c].current_owner()
            if owner_before not in (None, c) and owner_after != owner_before:
                self.violations.append(
                    f"release() by non-owner {c!r} destroyed "
                    f"{owner_before!r}'s lease"
                )
        elif op == "advance":
            self.clock.advance(event["dt"])
        else:  # pragma: no cover - scheduler only draws from enabled()
            raise ValueError(f"unknown ha event {op!r}")

    def check(self) -> List[str]:
        out = self.drain_violations()
        holders = [c for c in self.CANDS if self.leases[c].held_by_me()]
        if len(holders) > 1:
            out.append(f"dual leader: {holders} both hold a live lease")
        return out

    def finish(self) -> List[str]:
        return self.check()


MODELS: Dict[str, Callable[..., Model]] = {
    RouterModel.name: RouterModel,
    MasterModel.name: MasterModel,
    LeaseModel.name: LeaseModel,
}


def make_model(name: str, workdir: str,
               planted: Optional[str] = None) -> Model:
    if name not in MODELS:
        raise ValueError(
            f"unknown model {name!r}; choose from {sorted(MODELS)}")
    return MODELS[name](workdir, planted=planted)


# ---------------------------------------------------------------------------
# schedulers: replay, seeded-random, bounded DFS
# ---------------------------------------------------------------------------

def run_schedule(model: Model,
                 events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Replay ``events`` on a fresh incarnation of ``model``.  An event
    no longer applicable in the (possibly shrunk) context is SKIPPED —
    the ddmin contract: subsets of a violating schedule stay meaningful.
    Returns ``{violations, applied, trace}``; ``trace`` is the applied
    prefix up to (and including) the first violating event."""
    model.reset()
    trace: List[Dict[str, Any]] = []
    applied = 0
    for ev in events:
        if not model.applicable(ev):
            continue
        model.apply(ev)
        applied += 1
        trace.append(ev)
        vs = model.check()
        if vs:
            return {"violations": vs, "applied": applied,
                    "trace": list(trace)}
    vs = model.finish()
    return {"violations": vs, "applied": applied, "trace": list(trace)}


def _random_schedule(model: Model, rng: random.Random,
                     max_events: int) -> Dict[str, Any]:
    model.reset()
    trace: List[Dict[str, Any]] = []
    for _ in range(max_events):
        evs = model.enabled()
        if not evs:
            break
        ev = evs[rng.randrange(len(evs))]
        model.apply(ev)
        trace.append(ev)
        vs = model.check()
        if vs:
            return {"violations": vs, "trace": trace}
    return {"violations": model.finish(), "trace": trace}


def shrink_events(model: Model, events: Sequence[Dict[str, Any]],
                  max_rounds: int = 64) -> List[Dict[str, Any]]:
    """ddmin delta debugging: the smallest sub-sequence of ``events``
    that still violates (each candidate replays on a fresh incarnation;
    deterministic models make this exact, not probabilistic)."""
    def fails(cand: Sequence[Dict[str, Any]]) -> bool:
        return bool(run_schedule(model, cand)["violations"])

    current = list(events)
    if not fails(current):
        return current  # not reproducible: return as-is, caller decides
    n = 2
    rounds = 0
    while len(current) >= 2 and rounds < max_rounds:
        rounds += 1
        chunk = max(1, len(current) // n)
        reduced = False
        # try removing each chunk (complement testing)
        for i in range(0, len(current), chunk):
            cand = current[:i] + current[i + chunk:]
            if cand and fails(cand):
                current = cand
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if chunk == 1:
                break
            n = min(n * 2, len(current))
    # final greedy single-event pass
    i = 0
    while i < len(current) and rounds < max_rounds * 2:
        rounds += 1
        cand = current[:i] + current[i + 1:]
        if cand and fails(cand):
            current = cand
        else:
            i += 1
    return current


def _spec(model: Model, seed: Optional[int], events: List[Dict[str, Any]],
          violations: List[str]) -> Dict[str, Any]:
    return {
        "version": 1,
        "model": model.name,
        "planted": model.planted,
        "seed": seed,
        "events": events,
        "violations": violations,
    }


def explore_schedules(
    model: Model,
    schedules: int = 50,
    seed: int = 0,
    max_events: int = 14,
    shrink: bool = True,
) -> Dict[str, Any]:
    """Seeded-random exploration: ``schedules`` independent schedules of
    up to ``max_events`` events each (schedule ``i`` draws from
    ``random.Random(f"{seed}:{i}")``, so any subset of the batch replays
    independently).  Stops at the first violation; when ``shrink``, the
    violating schedule is ddmin-minimized and returned as a replayable
    spec."""
    for i in range(int(schedules)):
        rng = random.Random(f"{seed}:{i}")
        out = _random_schedule(model, rng, max_events)
        if out["violations"]:
            events = out["trace"]
            if shrink:
                events = shrink_events(model, events)
                out = run_schedule(model, events)
            return {
                "violation_found": True,
                "schedules_run": i + 1,
                "spec": _spec(model, seed, list(events),
                              out["violations"]),
            }
    return {"violation_found": False, "schedules_run": int(schedules),
            "spec": None}


def dfs_explore(model: Model, depth: int = 4,
                branch_limit: int = 6,
                max_paths: int = 2000) -> Dict[str, Any]:
    """Bounded-DFS exploration: every event sequence up to ``depth``
    (first ``branch_limit`` enabled events per state, depth-first,
    at most ``max_paths`` path replays).  Deterministic models replay
    each prefix from scratch, so no state snapshotting is needed."""
    stack: List[List[Dict[str, Any]]] = [[]]
    paths = 0
    while stack and paths < max_paths:
        prefix = stack.pop()
        paths += 1
        out = run_schedule(model, prefix)
        if out["violations"]:
            events = shrink_events(model, out["trace"])
            final = run_schedule(model, events)
            return {
                "violation_found": True,
                "paths_run": paths,
                "spec": _spec(model, None, list(events),
                              final["violations"]),
            }
        if out["applied"] < len(prefix):
            continue  # an event became inapplicable: pruned branch
        if len(prefix) < depth:
            frontier = model.enabled()[:branch_limit]
            for ev in reversed(frontier):
                stack.append(prefix + [ev])
    return {"violation_found": False, "paths_run": paths, "spec": None}


def replay_spec(spec: Dict[str, Any],
                workdir: Optional[str] = None) -> Dict[str, Any]:
    """Re-run a shrunk violation spec (``paddle-tpu explore --replay``).
    Returns ``{violations, applied, reproduced}`` — ``reproduced`` means
    the replay hit a violation again, the regression-test contract."""
    own_dir = workdir is None
    if own_dir:
        workdir = tempfile.mkdtemp(prefix="paddle-tpu-explore-")
    model = make_model(spec["model"], workdir, planted=spec.get("planted"))
    try:
        out = run_schedule(model, spec.get("events", ()))
        return {
            "violations": out["violations"],
            "applied": out["applied"],
            "reproduced": bool(out["violations"]),
        }
    finally:
        model.close()
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)
