"""Config-time graph lint — abstract shape/dtype/arity propagation over the
``Topology`` dataclass graph, before any JAX trace.

The reference's ``config_parser.py`` runs hundreds of per-layer
``config_assert`` checks while building the ModelConfig proto, so a bad
config dies at parse time with layer provenance instead of mid-training
inside the gserver interpreter.  Our graph is a typed dataclass IR that
exists *before* execution (the TensorFlow/Julia-to-TPU ahead-of-time
observation from PAPERS.md), which makes every check here pure host-side
analysis with zero TPU cost.

Rules (``G###``; each maps to a reference ``config_assert`` family — see
IMPLEMENTATION_MAP.md "Static analysis"):

  G001 unknown-layer-type        layer type not in the impl registry
  G002 dangling-input            input name resolves to no layer in scope
  G003 arity-mismatch            wrong input count for the layer type
  G004 width-mismatch            input widths incompatible with the type's
                                 contract (addto/concat/gru_step/...)
  G005 dead-layer                created during config build but reachable
                                 from no output/evaluator (cost-unreachable)
  G006 param-share-conflict      shared parameter names with conflicting
                                 shapes / mixed declaration forms
  G007 unknown-attr              attrs key that no code in paddle_tpu ever
                                 reads or writes (typo'd option — silently
                                 ignored at runtime)
  G008 shard-axis-unknown        shard_axis/seq_parallel_axis names an axis
                                 absent from the mesh
  G009 dynamic-width-bucketing   batch-wide trans feeding a weight while
                                 length bucketing is enabled (batch size
                                 varies per bucket; the resolved width
                                 cannot)
  G010 fused-pattern-defeated    a decoder step that would lower onto the
                                 fused attention-GRU core except for
                                 dropout/error-clip inside the pattern
  G011 data-slot-unresolved      v1 data layer whose provider types could
                                 not be resolved (feeding will fail)
  G013 unknown-activation        act name not in the activation registry
  G014 drop-rate-range           drop_rate outside [0, 1)
  G015 data-type-dim-mismatch    data layer size != its InputType dim
  G017 label-dim-mismatch        cost-layer label vocab != prediction width

``G016 duplicate-layer-name`` lives in ``core.topology`` (the graph cannot
even be built, so the constructor raises it as a DiagnosticError).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.core.data_types import SlotKind
from paddle_tpu.core.topology import LayerConf, Topology

# ---------------------------------------------------------------------------
# per-type contracts (only constraints the impls genuinely enforce)
# ---------------------------------------------------------------------------

# exact input count
_EXACT_ARITY: Dict[str, int] = {
    "gru_step": 2,        # (gates [B,3H], prev_h)
    "lstm_step": 3,       # (gates [B,4H], prev_h, prev_c)
    "scaling": 2,         # (weight [B,1], x)
    "interpolation": 3,   # (lambda [B,1], x1, x2)
    "expand": 2,          # (x, pattern)
    "trans": 1,
    "maxid": 1,
    "embedding": 1,
    "seqpool": 1,
    "seqlastins": 1,
    "sum_cost": 1,
    "out_prod": 2,
    "cos": 2,
    "dotmul": 2,
    "rank_cost": 3,       # (left, right, label)
}

# minimum input count
_MIN_ARITY: Dict[str, int] = {
    "fc": 1,
    "addto": 1,
    "concat": 1,
    "cross_entropy": 2,
    "square_error": 2,
    "smooth_l1": 2,
    "multi_binary_label_cross_entropy": 2,
    "soft_binary_class_cross_entropy": 2,
    "huber_regression": 2,
    "huber_classification": 2,
}

_CE_COST_TYPES = frozenset({
    "cross_entropy",
    "multi_binary_label_cross_entropy",
})


def _width(conf: Optional[LayerConf]) -> int:
    """Declared last-axis width, or 0 when unknowable (placeholder sizes)."""
    if conf is None or conf.attrs.get("_v1_size_only"):
        return 0
    return int(conf.size or 0)


def _has_dynamic_width(conf: LayerConf) -> bool:
    if conf.attr("dynamic_width_in"):
        return True
    return any(
        s.get("dynamic_width") for s in conf.attrs.get("projections", ())
    )


# ---------------------------------------------------------------------------
# attr-key universe (rule G007)
# ---------------------------------------------------------------------------

_ATTR_UNIVERSE: Optional[Set[str]] = None


def _scan_attr_keys(tree: ast.AST, keys: Set[str]) -> None:
    """Collect every attrs key the code READS (``.attr("k")``,
    ``.attrs.get("k")``, ``.attrs["k"]``, ``"k" in x.attrs``) or WRITES
    (string keys of a dict literal passed as ``attrs=...`` / stored into
    ``.attrs``)."""

    def lit(node) -> Optional[str]:
        return node.value if (
            isinstance(node, ast.Constant) and isinstance(node.value, str)
        ) else None

    # names aliased to an attrs dict (`a = conf.attrs`) — reads through the
    # alias count as reads of attrs keys
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Attribute
        ) and node.value.attr == "attrs":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases.add(t.id)

    def is_attrs_expr(node) -> bool:
        if isinstance(node, ast.Attribute) and node.attr == "attrs":
            return True
        return isinstance(node, ast.Name) and node.id in aliases

    def dict_keys(node) -> Iterable[str]:
        if isinstance(node, ast.Dict):
            for k in node.keys:
                s = lit(k) if k is not None else None
                if s is not None:
                    yield s
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "dict"
        ):
            for kw in node.keywords:
                if kw.arg:
                    yield kw.arg

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            # x.attr("k", ...) / x.attrs.get("k", ...)
            if isinstance(f, ast.Attribute) and node.args:
                s = lit(node.args[0])
                if s is not None and (
                    f.attr == "attr"
                    or (f.attr == "get" and is_attrs_expr(f.value))
                ):
                    keys.add(s)
            # attrs={...} / attrs=dict(...) keyword anywhere
            for kw in node.keywords:
                if kw.arg == "attrs":
                    keys.update(dict_keys(kw.value))
        elif isinstance(node, ast.Subscript) and is_attrs_expr(node.value):
            s = lit(node.slice)
            if s is not None:
                keys.add(s)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and isinstance(
            node.ops[0], (ast.In, ast.NotIn)
        ):
            if is_attrs_expr(node.comparators[0]):
                s = lit(node.left)
                if s is not None:
                    keys.add(s)
        elif isinstance(node, ast.Assign):
            # conf.attrs = {...} or attrs: ... = {...} assignments
            for t in node.targets:
                if (is_attrs_expr(t) or (
                    isinstance(t, ast.Name) and t.id == "attrs"
                )):
                    keys.update(dict_keys(node.value))


def attr_key_universe(refresh: bool = False) -> Set[str]:
    """Every attrs key read or written anywhere in ``paddle_tpu`` — the set
    a LayerConf attrs key must belong to, or nothing will ever consume it.
    Built once per process by AST-scanning the package source."""
    global _ATTR_UNIVERSE
    if _ATTR_UNIVERSE is not None and not refresh:
        return _ATTR_UNIVERSE
    import paddle_tpu

    keys: Set[str] = set()
    root = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (SyntaxError, OSError):  # pragma: no cover
                continue
            _scan_attr_keys(tree, keys)
    _ATTR_UNIVERSE = keys
    return keys


# ---------------------------------------------------------------------------
# the linter
# ---------------------------------------------------------------------------


def _mesh_axis_names(mesh) -> Tuple[str, ...]:
    if mesh is not None:
        return tuple(mesh.axis_names)
    from paddle_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, get_default_mesh

    default = get_default_mesh()
    if default is not None:
        return tuple(default.axis_names)
    return (DATA_AXIS, MODEL_AXIS)


@dataclasses.dataclass
class _LintCtx:
    diags: List[Diagnostic]
    source: Optional[str]
    axis_names: Tuple[str, ...]
    mesh_explicit: bool
    attr_universe: Set[str]
    activations: Set[str]
    layer_types: Set[str]

    def emit(self, rule, severity, message, layer=None, hint=None) -> None:
        self.diags.append(
            Diagnostic(
                rule=rule,
                severity=severity,
                message=message,
                layer=layer,
                source=self.source,
                hint=hint,
            )
        )


def _lint_one(ctx: _LintCtx, path: Tuple[str, ...], conf: LayerConf,
              layers: Dict[str, LayerConf], visible: Set[str]) -> None:
    name = ".".join(path)
    E, W = Severity.ERROR, Severity.WARNING

    # G001 unknown layer type
    if conf.type not in ctx.layer_types:
        ctx.emit(
            "G001", E,
            f"unknown layer type {conf.type!r}",
            layer=name,
            hint="use one of the registered types "
            "(paddle_tpu.layers.base.registered_layer_types())",
        )
        return  # nothing below is meaningful for an unknown type

    # G002 dangling inputs (auxiliary "<layer>@out" addresses resolve by base)
    dangling = [
        i for i in conf.inputs
        if i not in layers and i.split("@")[0] not in visible
    ]
    if dangling:
        ctx.emit(
            "G002", E,
            f"inputs {dangling} name no layer in the graph",
            layer=name,
            hint="the input layer was never built, or its name is typo'd; "
            f"layers in scope: {sorted(visible)[:8]}...",
        )
        return  # arity/width below would double-report

    # memory link resolution + width
    if conf.type == "memory":
        link = conf.attrs.get("link")
        if link and link.split("@")[0] not in visible:
            ctx.emit(
                "G002", E,
                f"memory link {link!r} names no layer in the step graph",
                layer=name,
                hint="link the memory to a layer built inside the step "
                "(memory(name=...) or .set_input(layer))",
            )
        elif link:
            tgt = layers.get(link.split("@")[0])
            # "@"-addressed auxiliary outputs (lstm_step's "<name>@cell")
            # have their own widths — only plain links are checkable
            if tgt is not None and _width(tgt) and _width(conf) and \
                    "@" not in link and _width(tgt) != _width(conf):
                ctx.emit(
                    "G004", E,
                    f"memory size {conf.size} != linked layer "
                    f"{link!r} size {tgt.size}",
                    layer=name,
                    hint="a memory carries its link's previous output; "
                    "declare memory(size=) equal to the linked layer's size",
                )

    # G003 arity
    want = _EXACT_ARITY.get(conf.type)
    n = len(conf.inputs)
    if want is not None and n != want:
        ctx.emit(
            "G003", E,
            f"{conf.type} takes exactly {want} input(s), got {n} "
            f"({list(conf.inputs)})",
            layer=name,
            hint=f"see the {conf.type!r} layer contract in paddle_tpu.layers",
        )
        return
    want_min = _MIN_ARITY.get(conf.type)
    if want_min is not None and n < want_min:
        ctx.emit(
            "G003", E,
            f"{conf.type} needs at least {want_min} input(s), got {n}",
            layer=name,
            hint=f"see the {conf.type!r} layer contract in paddle_tpu.layers",
        )
        return

    ins = [layers.get(i.split("@")[0], layers.get(i)) for i in conf.inputs]

    # G004 width contracts (0 = unknown ⇒ skip; dynamic widths are runtime)
    if not _has_dynamic_width(conf) and not any(
        c is not None and _has_dynamic_width(c) for c in ins
    ):
        _lint_widths(ctx, name, conf, ins)

    # G013 unknown activation
    if conf.act and conf.act not in ctx.activations:
        ctx.emit(
            "G013", E,
            f"unknown activation {conf.act!r}",
            layer=name,
            hint=f"known activations: {sorted(ctx.activations)}",
        )

    # G014 drop_rate range
    if not (0.0 <= conf.drop_rate < 1.0):
        ctx.emit(
            "G014", E,
            f"drop_rate {conf.drop_rate} outside [0, 1)",
            layer=name,
            hint="dropout keeps each unit with probability 1-drop_rate; "
            "1.0 would zero the whole layer",
        )

    # G007 unknown attrs keys ('_'-prefixed keys are build artifacts)
    unknown = [
        k for k in conf.attrs
        if not k.startswith("_") and k not in ctx.attr_universe
    ]
    if unknown:
        ctx.emit(
            "G007", W,
            f"attrs keys {sorted(unknown)} are read by no paddle_tpu code "
            "and will be silently ignored",
            layer=name,
            hint="probably a typo'd layer option; compare with the layer's "
            "documented attrs",
        )

    # G008 shard axes
    for axis in (conf.shard_axis, conf.attr("seq_parallel_axis")):
        if axis and axis not in ctx.axis_names:
            ctx.emit(
                "G008",
                E if ctx.mesh_explicit else W,
                f"shard axis {axis!r} is not a mesh axis "
                f"{list(ctx.axis_names)}",
                layer=name,
                hint="use one of the mesh's named axes (parallel.mesh: "
                "'data'/'model'), or extend the mesh",
            )

    # G011 unresolved v1 data slots
    why = conf.attrs.get("_v1_unresolved")
    if why:
        ctx.emit(
            "G011", W,
            f"data slot types unresolved: {why} — feeding this graph will "
            "fail at the DataFeeder boundary",
            layer=name,
            hint="declare input_types on the @provider, make its init_hook "
            "runnable, or feed through an explicit DataFeeder",
        )

    # G015 data layer size vs declared InputType dim
    if conf.type == "data" and conf.input_type is not None:
        it = conf.input_type
        if it.kind in (SlotKind.DENSE, SlotKind.INDEX) and _width(conf) and \
                it.dim != conf.size:
            ctx.emit(
                "G015", E,
                f"data layer size {conf.size} != its "
                f"{it.kind.value} input_type dim {it.dim}",
                layer=name,
                hint="data_layer(size=...) must equal the provider slot's "
                "declared dimension",
            )

    # G017 cost-label dimension
    if conf.type in _CE_COST_TYPES and len(conf.inputs) >= 2:
        pred, label = ins[0], ins[1]
        if (
            label is not None
            and label.type == "data"
            and label.input_type is not None
            and label.input_type.kind == SlotKind.INDEX
            and pred is not None
            and _width(pred)
            and label.input_type.dim != _width(pred)
        ):
            ctx.emit(
                "G017", E,
                f"label {label.name!r} has {label.input_type.dim} classes "
                f"but the prediction {pred.name!r} is {pred.size} wide",
                layer=name,
                hint="integer_value(n) must match the classifier width n",
            )


def _lint_widths(ctx: _LintCtx, name: str, conf: LayerConf,
                 ins: Sequence[Optional[LayerConf]]) -> None:
    E = Severity.ERROR
    t = conf.type
    w = _width(conf)
    iw = [_width(c) for c in ins]

    def bad(msg: str, hint: str) -> None:
        ctx.emit("G004", E, msg, layer=name, hint=hint)

    if t == "addto":
        sizes = {x for x in iw if x}
        if w:
            sizes |= {w}
        if len(sizes) > 1:
            bad(
                f"addto inputs must all match the output width; got "
                f"{iw} -> {w}",
                "addto sums its inputs elementwise — every input needs the "
                "same size",
            )
    elif t == "concat":
        if w and all(iw) and sum(iw) != w:
            bad(
                f"concat of widths {iw} gives {sum(iw)}, but size={w} "
                "declared",
                "declare size as the sum of the input widths (or omit it)",
            )
    elif t == "gru_step":
        if iw[0] and w and iw[0] != 3 * w:
            bad(
                f"gru_step gate input is {iw[0]} wide; needs 3*size "
                f"= {3 * w}",
                "the gate input stacks update/reset/candidate projections: "
                "project the step input to 3*size first",
            )
        elif len(iw) > 1 and iw[1] and w and iw[1] != w:
            bad(
                f"gru_step state input is {iw[1]} wide; needs size = {w}",
                "the previous-state memory must carry `size` features",
            )
    elif t == "lstm_step":
        if iw[0] and w and iw[0] != 4 * w:
            bad(
                f"lstm_step gate input is {iw[0]} wide; needs 4*size "
                f"= {4 * w}",
                "the gate input stacks input/forget/output/candidate "
                "projections: project the step input to 4*size first",
            )
        else:
            for slot, x in enumerate(iw[1:], 1):
                if x and w and x != w:
                    bad(
                        f"lstm_step state input {slot} is {x} wide; needs "
                        f"size = {w}",
                        "prev_h and prev_c must both carry `size` features",
                    )
                    break
    elif t in ("scaling", "interpolation"):
        if iw[0] and iw[0] != 1:
            bad(
                f"{t} weight input must be width 1, got {iw[0]}",
                "the first input is a per-sample scalar weight",
            )
        elif t == "interpolation" and iw[1] and iw[2] and iw[1] != iw[2]:
            bad(
                f"interpolation endpoints differ in width: {iw[1]} vs "
                f"{iw[2]}",
                "both interpolated inputs need the same size",
            )


def _iter_layers(topology: Topology, prefix: Tuple[str, ...] = ()):
    """(dotted-path, conf) over a topology INCLUDING recurrent_group
    sub-topologies."""
    for n, c in topology.layers.items():
        yield prefix + (n,), c
        sub = c.attrs.get("_sub_topology")
        if sub is not None:
            yield from _iter_layers(sub, prefix + (n,))


def _reachable(topology: Topology) -> Set[str]:
    """All layer names in this topology and its sub-topologies."""
    out: Set[str] = set()

    def visit(t: Topology) -> None:
        for n, c in t.layers.items():
            out.add(n)
            sub = c.attrs.get("_sub_topology")
            if sub is not None:
                visit(sub)

    visit(topology)
    return out


def _lint_fused_pattern(ctx: _LintCtx, path: Tuple[str, ...],
                        conf: LayerConf) -> None:
    """G010: the PR-2 fused attention-GRU matcher is structural — dropout or
    error-clip on any layer inside the pattern silently defeats it and the
    decoder falls back to the generic per-layer scan.  Re-run the matcher
    with those attributes stripped; if it matches only then, the config
    gave up the fused core without knowing."""
    from paddle_tpu.layers.attention import match_attention_gru_step

    sub: Topology = conf.attrs["_sub_topology"]
    scan_names = set(conf.attrs.get("_scan_placeholders", ()))
    static_seq = {
        p for (p, is_seq) in conf.attrs.get("_static_placeholders", ())
        if is_seq
    }
    for mem in conf.attrs.get("_memories", ()):
        if match_attention_gru_step(sub.layers, mem, scan_names, static_seq):
            continue  # fuses as-is
        cleaned = {}
        dirty: List[str] = []
        for n, c in sub.layers.items():
            if c.drop_rate or c.attr("error_clip", 0.0):
                dirty.append(n)
                attrs = {k: v for k, v in c.attrs.items() if k != "error_clip"}
                c = dataclasses.replace(c, drop_rate=0.0, attrs=attrs)
            cleaned[n] = c
        if dirty and match_attention_gru_step(
            cleaned, mem, scan_names, static_seq
        ):
            ctx.emit(
                "G010", Severity.WARNING,
                "this decoder step matches the fused attention-GRU core "
                f"except for dropout/error-clip on {sorted(dirty)}; the "
                "group falls back to the generic (slower) scan",
                layer=".".join(path),
                hint="move dropout outside the matched pattern (e.g. onto "
                "the group output) or drop error_clip inside the step to "
                "regain the fused lowering",
            )


def _compile_probe(ctx: _LintCtx, topology: Topology) -> None:
    """G006: build the CompiledNetwork parameter-sharing maps and abstractly
    evaluate parameter init (``jax.eval_shape`` — shape-only, zero FLOPs) so
    name-collision and shared-shape conflicts surface here with provenance
    instead of deep inside a matmul."""
    import jax

    from paddle_tpu.analysis.diagnostics import DiagnosticError
    from paddle_tpu.core.compiler import CompiledNetwork

    try:
        net = CompiledNetwork(topology)
        jax.eval_shape(net.init_params, jax.random.PRNGKey(0))
    except DiagnosticError as e:
        # the compiler already speaks the diagnostic format (G006 family);
        # re-home its findings under this lint run's source
        for d in e.diagnostics:
            ctx.diags.append(dataclasses.replace(d, source=ctx.source))
    except ValueError as e:
        msg = str(e).splitlines()[0]
        ctx.emit(
            "G006", Severity.ERROR,
            f"parameter build conflict: {msg}",
            hint="two layers share a parameter name with incompatible "
            "shapes/forms; use distinct ParamAttr names or align the sizes",
        )
    except Exception as e:  # init-time failure of any layer
        ctx.emit(
            "G006", Severity.ERROR,
            f"parameter init fails: {type(e).__name__}: "
            f"{str(e).splitlines()[0] if str(e) else e!r}",
            hint="abstract parameter init failed — the layer sizes/attrs "
            "are inconsistent even before tracing",
        )


def lint_topology(
    topology: Topology,
    *,
    mesh=None,
    created: Optional[Iterable[str]] = None,
    evaluator_layers: Optional[Iterable[str]] = None,
    source: Optional[str] = None,
    bucketing: Optional[bool] = None,
) -> List[Diagnostic]:
    """Lint one Topology.  ``created`` is the full set of layer names built
    during config construction (for dead-layer detection);
    ``evaluator_layers`` are extra liveness roots (evaluator/extra-layer
    inputs).  ``bucketing=None`` reads the ``use_bucketing`` flag."""
    import paddle_tpu.layers  # noqa: F401 — populates the impl registry
    from paddle_tpu.layers.base import registered_layer_types
    from paddle_tpu.ops.activations import registered_activations
    from paddle_tpu.utils.flags import get_flag

    ctx = _LintCtx(
        diags=[],
        source=source,
        axis_names=_mesh_axis_names(mesh),
        mesh_explicit=mesh is not None,
        attr_universe=attr_key_universe(),
        activations=set(registered_activations()) | {"", "identity", "linear"},
        layer_types=set(registered_layer_types()),
    )

    def walk(t: Topology, prefix: Tuple[str, ...], inherited: Set[str]) -> None:
        visible = inherited | set(t.layers)
        for n in t.order:
            conf = t.layers[n]
            _lint_one(ctx, prefix + (n,), conf, t.layers, visible)
            sub = conf.attrs.get("_sub_topology")
            if sub is not None:
                if conf.type == "recurrent_group":
                    _lint_fused_pattern(ctx, prefix + (n,), conf)
                walk(sub, prefix + (n,), visible)

    walk(topology, (), set())

    # G009 dynamic width x bucketing
    if bucketing is None:
        bucketing = bool(get_flag("use_bucketing"))
    if bucketing:
        dyn = [
            ".".join(path) for path, c in _iter_layers(topology)
            if _has_dynamic_width(c)
        ]
        if dyn:
            ctx.emit(
                "G009", Severity.ERROR,
                f"layers {dyn} consume a batch-wide transpose (dynamic "
                "weight width = batch size) but length bucketing is "
                "enabled — bucketed batch sizes vary per rung, so the "
                "resolved weights cannot fit every bucket",
                hint="disable use_bucketing for this config, or restructure "
                "away from whole-minibatch trans feeding a projection",
            )

    # G005 dead layers
    if created is not None:
        live = _reachable(topology)
        roots = set(evaluator_layers or ())
        dead = sorted(
            n for n in set(created) - live - roots
            if not n.startswith("__memory_")  # deferred-link handles
        )
        if dead:
            ctx.emit(
                "G005", Severity.WARNING,
                f"layers {dead} were built but are reachable from no "
                "output or evaluator — they will never execute",
                hint="remove them, add them to outputs()/Outputs(), or "
                "attach them to an evaluator/extra_layers",
            )

    # G006 compile probe — only when the graph is structurally sound
    if not any(
        d.rule in ("G001", "G002", "G003") and d.severity == Severity.ERROR
        for d in ctx.diags
    ):
        _compile_probe(ctx, topology)

    return ctx.diags


def lint_parsed(parsed, *, mesh=None, bucketing: Optional[bool] = None
                ) -> List[Diagnostic]:
    """Lint a v1 ``ParsedConfig`` (the ``parse_config`` result): the built
    topology plus parse-level context — every layer the config file created
    (dead-layer analysis) and the evaluator inputs (liveness roots), with
    the config path as provenance."""
    eval_roots: Set[str] = set()
    for ev in getattr(parsed, "evaluators", ()) or ():
        for lo in getattr(ev, "layers", ()) or ():
            eval_roots.add(lo.name)
            eval_roots.update(_ancestors(lo))
    return lint_topology(
        parsed.topology,
        mesh=mesh,
        created=getattr(parsed, "all_layer_names", None),
        evaluator_layers=eval_roots,
        source=getattr(parsed, "source_file", None),
        bucketing=bucketing,
    )


def _ancestors(lo) -> Set[str]:
    out: Set[str] = set()
    stack = list(lo.parents)
    while stack:
        p = stack.pop()
        if p.name in out:
            continue
        out.add(p.name)
        stack.extend(p.parents)
    return out
