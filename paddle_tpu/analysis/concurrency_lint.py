"""Lock-discipline lint — static C-rules over paddle_tpu's threaded planes.

PRs 6-8 grew a real multi-threaded distributed system (master queue /
registry / fence plane on one RLock, HA standby tail thread, elastic
heartbeat thread, async checkpoint writers, reader prefetch pools) and
every protocol race shipped so far was found by hand-driven chaos drills.
This pass applies the config_assert philosophy to *threads*: infer each
class's lock discipline from the AST and report the violations before the
drill does.  The runtime leg (:mod:`~paddle_tpu.analysis.lock_sanitizer`)
checks the same invariants dynamically while the drills run.

Inference, per class (and per module, for module-level locks/globals):

  * lock attrs        ``self._lock = threading.Lock()/RLock()/Condition()``
                      (or the ``make_lock``/``make_rlock`` sanitizer
                      factories, or any ``with self.X:`` whose name matches
                      ``lock|mutex|_mu``);
  * guarded fields    fields mutated at least once while a lock is held —
                      assignment, ``del``, subscript stores, and container
                      mutators (``.append``/``.update``/...);
  * held-set          propagated interprocedurally within the class: a
                      private method whose every in-class call site holds
                      lock L is analyzed as if L were held on entry
                      (``__init__`` is single-threaded by construction:
                      its writes and call sites are exempt);
  * thread entries    targets of ``threading.Thread(target=...)`` /
                      ``threading.Timer(..., cb)`` — a method or nested
                      function that runs on a second thread.

Rules (``C###``):

  C301 mixed-guard-write   a guarded field is also written while its lock
                           set is NOT held — two writers can interleave
  C302 unguarded-read      a thread-entry path reads a guarded field
                           without the lock — torn/stale reads on the
                           second thread
  C303 lock-order-cycle    the static acquisition graph (nested ``with``,
                           plus calls into lock-acquiring methods, across
                           classes) contains a cycle — an ABBA deadlock
  C304 blocking-under-lock a blocking call (``os.fsync``, socket/pipe
                           send/recv/accept, ``time.sleep``, subprocess,
                           no-timeout ``.wait()``/queue ops, thread join)
                           while holding a lock — annotate intentional
                           holds (journal fsync-before-ack) with the
                           pragma below
  C305 leaked-thread       a non-daemon thread with no join path, or a
                           no-timeout ``Event.wait`` in a loop (a stop
                           flag can never interrupt it)
  C306 uninjectable-sleep  a ``time.sleep`` polling loop in a function
                           with no injectable ``sleep``/``clock`` hook —
                           the LeaseFile testability discipline: polling
                           loops must be drivable by a fake clock

Allowlist pragma (same line as the finding)::

    os.fsync(f.fileno())  # lock: allow[C304] fsync-before-ack is the contract

``# lock: allow[C304,C306] why`` suppresses several rules at once.  The
justification string is REQUIRED — an empty one is its own finding (C300).

Run via ``paddle-tpu lint --concurrency`` (``make lint``).  Rule ids are
stable; every rule has a firing mutation test in
tests/test_concurrency_lint.py.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis import pragmas as _pragmas
from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["lint_concurrency_file", "lint_concurrency_package"]

_LOCKNAME_RE = re.compile(r"lock|mutex|_mu$", re.IGNORECASE)

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "make_lock", "make_rlock"})
_EVENT_CTORS = frozenset({"Event"})
_THREAD_CTORS = frozenset({"Thread", "Timer"})

# container-mutator method names: `self.x.append(...)` mutates field x
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "update", "setdefault", "add", "discard", "sort", "reverse",
})

# method names too generic to resolve cross-class by name alone
_CROSS_CALL_STOPLIST = frozenset({
    "close", "open", "start", "stop", "run", "next", "read", "write",
    "send", "recv", "get", "put", "join", "wait", "acquire", "release",
    "append", "add", "update", "items", "keys", "values", "copy", "flush",
})

# receiver tails that mean a blocking transport op regardless of receiver
_BLOCKING_TAILS = frozenset({
    "accept", "connect", "recv", "recv_bytes", "send", "sendall",
    "send_bytes",
    # the master_wire transport helpers block exactly like the raw socket
    # ops they wrap (one frame send / one frame recv)
    "send_msg", "recv_msg",
})
_SUBPROCESS_FNS = frozenset({"run", "call", "check_call", "check_output", "Popen"})
_THREADISH_RE = re.compile(r"thread|proc|worker|pending", re.IGNORECASE)
_QUEUEISH_RE = re.compile(r"(^|_)q(s)?($|_)|queue", re.IGNORECASE)

_SLEEP_INJECTABLES = frozenset({"sleep", "sleep_fn", "clock"})


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _attr_root(node: ast.AST) -> Tuple[Optional[ast.AST], List[ast.AST]]:
    """Descend a Subscript/Attribute chain; returns (root expr, chain nodes).
    ``self.fences[fid]["arrived"]`` -> (Name 'self'-rooted Attribute, ...)."""
    chain: List[ast.AST] = []
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        chain.append(node)
        node = node.value
    return node, chain


def _self_field(node: ast.AST) -> Optional[ast.Attribute]:
    """The ``self.X`` Attribute at the root of a target/receiver chain."""
    root, chain = _attr_root(node)
    if isinstance(root, ast.Name) and root.id == "self" and chain:
        last = chain[-1]
        if isinstance(last, ast.Attribute):
            return last
    return None


@dataclasses.dataclass
class _Event:
    """One analyzed occurrence inside a function body (access / call /
    acquisition / blocking op / sleep), with the lexically-held lock set."""

    kind: str  # read|write|self_call|other_call|acquire|blocking|sleep|wait
    name: str
    line: int
    held: frozenset
    thread_side: bool = False
    in_loop: bool = False
    detail: str = ""


@dataclasses.dataclass
class _Spawn:
    target: Optional[str]     # 'self.m' | local/module function name
    daemon: Optional[bool]    # None = not specified
    line: int
    var: Optional[str] = None       # local var the Thread was bound to
    attr: Optional[str] = None      # self attr the Thread was stored to


@dataclasses.dataclass
class _FnInfo:
    name: str
    params: Set[str]
    events: List[_Event] = dataclasses.field(default_factory=list)
    spawns: List[_Spawn] = dataclasses.field(default_factory=list)
    joined_vars: Set[str] = dataclasses.field(default_factory=set)
    daemonized_vars: Set[str] = dataclasses.field(default_factory=set)
    is_thread_entry: bool = False


@dataclasses.dataclass
class _ClassInfo:
    module: str
    name: str
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    event_attrs: Set[str] = dataclasses.field(default_factory=set)
    thread_attrs: Set[str] = dataclasses.field(default_factory=set)
    method_names: Set[str] = dataclasses.field(default_factory=set)
    init_params: Set[str] = dataclasses.field(default_factory=set)
    methods: Dict[str, _FnInfo] = dataclasses.field(default_factory=dict)
    joined_attrs: Set[str] = dataclasses.field(default_factory=set)
    thread_entries: Set[str] = dataclasses.field(default_factory=set)

    @property
    def key(self) -> str:
        return f"{self.module}.{self.name}"

    def lock_id(self, attr: str) -> str:
        return f"{self.module}.{self.name}.{attr}"


@dataclasses.dataclass
class _ModuleInfo:
    name: str
    relpath: str
    classes: Dict[str, _ClassInfo] = dataclasses.field(default_factory=dict)
    module_locks: Set[str] = dataclasses.field(default_factory=set)
    functions: Dict[str, _FnInfo] = dataclasses.field(default_factory=dict)
    global_writes: List[_Event] = dataclasses.field(default_factory=list)
    pragmas: Dict[int, Tuple[Set[str], str]] = dataclasses.field(default_factory=dict)
    pragma_used: Set[int] = dataclasses.field(default_factory=set)


class _Universe:
    """Package-wide lookup tables for cross-class resolution."""

    def __init__(self, modules: Sequence[_ModuleInfo]):
        self.modules = list(modules)
        # lock attr name -> owning class keys (unique name = resolvable)
        self.lock_attr_owners: Dict[str, List[_ClassInfo]] = {}
        # method name -> owning classes
        self.method_owners: Dict[str, List[_ClassInfo]] = {}
        for m in modules:
            for c in m.classes.values():
                for a in c.lock_attrs:
                    self.lock_attr_owners.setdefault(a, []).append(c)
                for meth in c.method_names:
                    self.method_owners.setdefault(meth, []).append(c)

    def resolve_foreign_lock(self, attr: str, own: Optional[_ClassInfo]) -> Optional[str]:
        owners = [c for c in self.lock_attr_owners.get(attr, ()) if c is not own]
        if len(owners) == 1:
            return owners[0].lock_id(attr)
        return None

    def resolve_foreign_method(self, name: str, own: Optional[_ClassInfo]) -> Optional[_ClassInfo]:
        if name in _CROSS_CALL_STOPLIST or name.startswith("__"):
            return None
        owners = [c for c in self.method_owners.get(name, ()) if c is not own]
        if len(owners) == 1:
            return owners[0]
        return None


# ---------------------------------------------------------------------------
# phase 1: declarations (lock/event/thread attrs, thread entries, pragmas)
# ---------------------------------------------------------------------------

def _module_name(path: str, base: str) -> str:
    rel = os.path.relpath(path, base)
    for prefix in ("paddle_tpu" + os.sep,):
        if rel.startswith(prefix):
            rel = rel[len(prefix):]
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'lock' | 'event' | 'thread' when value is a recognized constructor."""
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    if dotted is None:
        return None
    tail = dotted.rpartition(".")[2]
    if tail in _LOCK_CTORS:
        return "lock"
    if tail in _EVENT_CTORS:
        return "event"
    if tail in _THREAD_CTORS:
        return "thread"
    return None


def _collect_pragmas(src: str, relpath: str, diags: List[Diagnostic],
                     info: _ModuleInfo) -> None:
    """Pragmas parse through the shared plane parser (analysis.pragmas):
    COMMENT tokens only — a ``# lock: allow[...]`` spelled inside a string
    literal is documentation, not an annotation — and an empty
    justification is its own C300 finding."""
    for line, p in _pragmas.collect(src, "lock", relpath, diags).items():
        info.pragmas[line] = (set(p.rules), p.justification)


def _declared(tree: ast.Module, mod: str, relpath: str) -> _ModuleInfo:
    info = _ModuleInfo(name=mod, relpath=relpath)
    for node in tree.body:
        if isinstance(node, ast.Assign) and _ctor_kind(node.value) == "lock":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    info.module_locks.add(t.id)
        elif isinstance(node, ast.ClassDef):
            c = _ClassInfo(module=mod, name=node.name)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    c.method_names.add(item.name)
                    if item.name == "__init__":
                        c.init_params = {a.arg for a in item.args.args}
                        c.init_params |= {a.arg for a in item.args.kwonlyargs}
            for sub in ast.walk(node):
                # self.X = <ctor>  anywhere in the class body
                if isinstance(sub, ast.Assign):
                    kind = _ctor_kind(sub.value)
                    if kind:
                        for t in sub.targets:
                            f = _self_field(t)
                            if f is not None and not isinstance(
                                t, (ast.Subscript,)
                            ):
                                {"lock": c.lock_attrs,
                                 "event": c.event_attrs,
                                 "thread": c.thread_attrs}[kind].add(f.attr)
                # any `with self.X:` with a lock-ish name counts as a lock
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for it in sub.items:
                        f = _self_field(it.context_expr)
                        if f is not None and _LOCKNAME_RE.search(f.attr):
                            c.lock_attrs.add(f.attr)
                # `self.X.join(...)` anywhere -> X has a join path
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                    if sub.func.attr == "join":
                        f = _self_field(sub.func.value)
                        if f is not None:
                            c.joined_attrs.add(f.attr)
            c.event_attrs -= c.lock_attrs
            info.classes[node.name] = c
    return info


# ---------------------------------------------------------------------------
# phase 2: body analysis
# ---------------------------------------------------------------------------

class _FnScanner:
    """Walk one function body tracking the lexically held lock set."""

    def __init__(self, universe: _Universe, minfo: _ModuleInfo,
                 cls: Optional[_ClassInfo], fn: _FnInfo,
                 local_locks: Optional[Dict[str, str]] = None,
                 qual: str = ""):
        self.u = universe
        self.m = minfo
        self.c = cls
        self.fn = fn
        self.qual = qual or fn.name
        self.local_locks = dict(local_locks or {})
        self.global_names: Set[str] = set()
        self.thread_side = fn.is_thread_entry

    # -- lock resolution -------------------------------------------------
    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                # directly `with self.X:`
                if self.c is not None and expr.attr in self.c.lock_attrs:
                    return self.c.lock_id(expr.attr)
                return None
            # `with other.X:` (any depth) — resolvable when X is a
            # lock-named attr owned by exactly one analyzed class
            if _LOCKNAME_RE.search(expr.attr):
                return self.u.resolve_foreign_lock(expr.attr, self.c)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.m.module_locks:
                return f"{self.m.name}.{expr.id}"
            if expr.id in self.local_locks:
                return self.local_locks[expr.id]
        return None

    # -- entry -----------------------------------------------------------
    def scan(self, node: ast.AST, held: frozenset = frozenset()) -> None:
        # pre-pass: thread targets among nested defs, local lock vars,
        # daemonized/joined thread vars
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func)
                tail = d.rpartition(".")[2] if d else ""
                if tail in _THREAD_CTORS:
                    self._note_spawn(sub)
                elif tail == "join" and isinstance(sub.func, ast.Attribute):
                    recv = sub.func.value
                    if isinstance(recv, ast.Name):
                        self.fn.joined_vars.add(recv.id)
            elif isinstance(sub, ast.Assign):
                if _ctor_kind(sub.value) == "lock":
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            self.local_locks[t.id] = (
                                f"{self.m.name}.{self.qual}.{t.id}"
                            )
                # t.daemon = True
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                            and isinstance(t.value, ast.Name)
                            and isinstance(sub.value, ast.Constant)
                            and sub.value.value):
                        self.fn.daemonized_vars.add(t.value.id)
            elif isinstance(sub, ast.Global):
                self.global_names.update(sub.names)
        body = node.body if hasattr(node, "body") else [node]
        self._stmts(body, held, 0)

    def _note_spawn(self, call: ast.Call) -> None:
        d = _dotted(call.func) or ""
        tail = d.rpartition(".")[2]
        target_expr = None
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif tail == "Timer" and len(call.args) >= 2:
            target_expr = call.args[1]
        daemon = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        target = None
        if target_expr is not None:
            f = _self_field(target_expr)
            if f is not None:
                target = f"self.{f.attr}"
                if self.c is not None:
                    self.c.thread_entries.add(f.attr)
            elif isinstance(target_expr, ast.Name):
                target = target_expr.id
        self.fn.spawns.append(_Spawn(target=target, daemon=daemon,
                                     line=call.lineno))

    # -- statements ------------------------------------------------------
    def _stmts(self, body: Sequence[ast.stmt], held: frozenset, loop: int) -> None:
        for stmt in body:
            self._stmt(stmt, held, loop)

    def _stmt(self, stmt: ast.stmt, held: frozenset, loop: int) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for it in stmt.items:
                lock = self._resolve_lock(it.context_expr)
                if lock is not None:
                    self._event("acquire", lock, it.context_expr.lineno,
                                new_held, loop)
                    new_held = new_held | {lock}
                else:
                    self._exprs([it.context_expr], held, loop)
            self._stmts(stmt.body, new_held, loop)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested_def(stmt, held)
        elif isinstance(stmt, ast.ClassDef):
            pass  # nested classes: out of scope
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            header = [stmt.test] if isinstance(stmt, ast.While) else [stmt.iter]
            self._exprs(header, held, loop + 1)
            self._stmts(stmt.body, held, loop + 1)
            self._stmts(stmt.orelse, held, loop)
        elif isinstance(stmt, ast.If):
            self._exprs([stmt.test], held, loop)
            self._stmts(stmt.body, held, loop)
            self._stmts(stmt.orelse, held, loop)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held, loop)
            for h in stmt.handlers:
                self._stmts(h.body, held, loop)
            self._stmts(stmt.orelse, held, loop)
            self._stmts(stmt.finalbody, held, loop)
        elif isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            skip: Set[int] = set()
            for t in targets:
                self._write_target(t, held, loop, skip)
            value = stmt.value
            if value is not None:
                self._exprs([value], held, loop, skip)
            if isinstance(stmt, ast.AugAssign):
                # aug-assign reads the target too; the write already notes it
                pass
        elif isinstance(stmt, ast.Delete):
            skip = set()
            for t in stmt.targets:
                self._write_target(t, held, loop, skip)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._exprs([stmt.value], held, loop)
        elif isinstance(stmt, ast.Expr):
            self._exprs([stmt.value], held, loop)
        else:
            exprs = [v for v in ast.iter_child_nodes(stmt)
                     if isinstance(v, ast.expr)]
            self._exprs(exprs, held, loop)

    def _nested_def(self, stmt, held: frozenset) -> None:
        """A nested ``def``: if it is a thread target it runs on a second
        thread with NOTHING held; otherwise treat it as running where it
        was defined (closure called locally)."""
        is_thread = any(
            s.target == stmt.name for s in self.fn.spawns
        )
        sub_fn = _FnInfo(
            name=f"{self.fn.name}.{stmt.name}",
            params={a.arg for a in stmt.args.args} | self.fn.params,
            is_thread_entry=is_thread or self.fn.is_thread_entry,
        )
        scanner = _FnScanner(self.u, self.m, self.c, sub_fn,
                             local_locks=self.local_locks,
                             qual=f"{self.qual}.{stmt.name}")
        scanner.global_names = set(self.global_names)
        scanner.scan(stmt, frozenset() if is_thread else held)
        # nested events fold into the enclosing method record so the
        # class-level passes see them (entry-held union still applies to
        # the ENCLOSING method; thread bodies carry thread_side=True).
        # Spawns do NOT fold back: the enclosing scan()'s pre-pass already
        # walked the nested body, so extending here would double-record
        # every nested-def Thread construction (duplicate C305s).
        self.fn.events.extend(sub_fn.events)

    # -- writes ----------------------------------------------------------
    def _write_target(self, t: ast.AST, held: frozenset, loop: int,
                      skip: Set[int]) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._write_target(el, held, loop, skip)
            return
        f = _self_field(t)
        if f is not None:
            if self.c is not None and (
                f.attr in self.c.lock_attrs or f.attr in self.c.event_attrs
            ):
                return
            self._event("write", f.attr, t.lineno, held, loop)
            root, chain = _attr_root(t)
            skip.update(id(n) for n in chain)
            skip.add(id(root))
            # subscript stores read the container first; chain exprs
            # (indices) still get scanned by the caller via value walk
            for n in chain:
                if isinstance(n, ast.Subscript):
                    self._exprs([n.slice], held, loop)
            return
        root, chain = _attr_root(t)
        if isinstance(root, ast.Name) and (
            root.id in self.global_names
            or (not chain and root.id in self.m.module_locks)
        ):
            self._event("gwrite", root.id, t.lineno, held, loop)
            skip.add(id(root))
            skip.update(id(n) for n in chain)

    # -- expressions -----------------------------------------------------
    def _exprs(self, exprs: Sequence[Optional[ast.expr]], held: frozenset,
               loop: int, skip: Optional[Set[int]] = None) -> None:
        skip = skip or set()
        for e in exprs:
            if e is None:
                continue
            lambda_sub: Set[int] = set()
            for node in ast.walk(e):
                if id(node) in lambda_sub:
                    continue
                if isinstance(node, ast.Lambda):
                    # a lambda body runs LATER, somewhere else — analyzing
                    # it with the definition site's held-set would invent
                    # findings at a context the code never executes in.
                    # ast.walk still yields its children, so blacklist the
                    # whole subtree explicitly.
                    for sub in ast.walk(node):
                        if sub is not node:
                            lambda_sub.add(id(sub))
                    continue
                if isinstance(node, ast.Call):
                    self._call(node, held, loop, skip)
                elif isinstance(node, ast.Attribute) and id(node) not in skip:
                    if (isinstance(node.value, ast.Name)
                            and node.value.id == "self"
                            and isinstance(node.ctx, ast.Load)):
                        c = self.c
                        if c is not None and node.attr not in c.method_names \
                                and node.attr not in c.lock_attrs \
                                and node.attr not in c.event_attrs:
                            self._event("read", node.attr, node.lineno,
                                        held, loop)

    def _call(self, node: ast.Call, held: frozenset, loop: int,
              skip: Set[int]) -> None:
        d = _dotted(node.func)
        tail = (d or "").rpartition(".")[2]
        head = (d or "").rpartition(".")[0]
        func = node.func

        # time.sleep: C304 material under a lock, C306 material in a loop
        if d == "time.sleep" or (tail == "sleep" and head == "time"):
            self._event("sleep", "time.sleep", node.lineno, held, loop,
                        in_loop=loop > 0)
        elif d == "os.fsync":
            self._event("blocking", "os.fsync", node.lineno, held, loop)
        elif head == "subprocess" and tail in _SUBPROCESS_FNS:
            self._event("blocking", d, node.lineno, held, loop)
        elif isinstance(func, ast.Attribute):
            recv = func.value
            recv_name = _dotted(recv) or ""
            recv_field = _self_field(recv)
            is_lock_recv = (
                self._resolve_lock(recv) is not None
                or (recv_field is not None and self.c is not None
                    and recv_field.attr in self.c.lock_attrs)
            )
            if tail in _BLOCKING_TAILS and not isinstance(recv, ast.Constant):
                self._event("blocking", f".{tail}", node.lineno, held, loop)
            elif tail == "join" and not isinstance(recv, ast.Constant):
                if d != "os.path.join" and not (d or "").endswith("path.join"):
                    if _THREADISH_RE.search(recv_name) or (
                        recv_field is not None and self.c is not None
                        and recv_field.attr in self.c.thread_attrs
                    ) or (isinstance(recv, ast.Name)
                          and recv.id in self.fn.joined_vars
                          and any(s.var == recv.id for s in self.fn.spawns)):
                        self._event("blocking", ".join", node.lineno, held, loop)
            elif tail == "wait" and not node.args and not node.keywords:
                if not is_lock_recv:  # Condition.wait releases the lock
                    ev = (recv_field is not None and self.c is not None
                          and recv_field.attr in self.c.event_attrs)
                    self._event("wait", recv_name or ".wait", node.lineno,
                                held, loop, in_loop=loop > 0,
                                detail="event" if ev else "")
            elif tail == "get" and not node.args and not any(
                kw.arg == "timeout" for kw in node.keywords
            ) and _QUEUEISH_RE.search(recv_name):
                self._event("blocking", ".get", node.lineno, held, loop)
            elif tail == "put" and not any(
                kw.arg == "timeout" for kw in node.keywords
            ) and _QUEUEISH_RE.search(recv_name):
                self._event("blocking", ".put", node.lineno, held, loop)

            # self-calls / cross-class calls (for held-set + lock-graph)
            if recv_field is None and isinstance(recv, ast.Name) \
                    and recv.id == "self":
                if self.c is not None and tail in self.c.method_names:
                    self._event("self_call", tail, node.lineno, held, loop)
                    skip.add(id(func))
            elif not is_lock_recv and tail not in _MUTATORS:
                self._event("other_call", tail, node.lineno, held, loop)

        # container mutators on self fields: `self.todo.append(x)`
        if isinstance(func, ast.Attribute) and tail in _MUTATORS:
            f = _self_field(func.value)
            if f is not None and self.c is not None \
                    and f.attr not in self.c.lock_attrs \
                    and f.attr not in self.c.event_attrs:
                self._event("write", f.attr, node.lineno, held, loop)
                skip.add(id(func.value))
            else:
                root, _ = _attr_root(func.value)
                if isinstance(root, ast.Name) and root.id in self.global_names:
                    self._event("gwrite", root.id, node.lineno, held, loop)

    def _event(self, kind: str, name: str, line: int, held: frozenset,
               loop: int, in_loop: bool = False, detail: str = "") -> None:
        self.fn.events.append(_Event(
            kind=kind, name=name, line=line, held=held,
            thread_side=self.thread_side, in_loop=in_loop or loop > 0,
            detail=detail,
        ))


# ---------------------------------------------------------------------------
# phase 3: class-level reasoning + diagnostics
# ---------------------------------------------------------------------------

_TOP = None  # lattice top for the entry-held fixpoint ("unknown context")


def _entry_held_fixpoint(c: _ClassInfo) -> Dict[str, Optional[frozenset]]:
    """Held-on-entry per method: intersection over in-class call sites,
    {} for externally-callable methods (public names, dunders, thread
    entries).  ``__init__`` call sites are exempt (single-threaded by
    construction).  A private method with NO visible non-init call site is
    dispatched dynamically (``getattr(self, f"_apply_{t}")``) or dead —
    its context is unknowable statically, so it maps to ``None`` (exempt
    from C301/C302 rather than reported at a context the code never runs
    in)."""
    sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    for mname, fn in c.methods.items():
        if mname == "__init__":
            continue
        for ev in fn.events:
            if ev.kind == "self_call":
                sites.setdefault(ev.name, []).append((mname, ev.held))

    entry: Dict[str, object] = {}
    for mname in c.methods:
        if (not mname.startswith("_") or mname.startswith("__")
                or mname in c.thread_entries):
            entry[mname] = frozenset()
        else:
            entry[mname] = _TOP

    changed = True
    while changed:
        changed = False
        for mname, slist in sites.items():
            if mname not in entry or entry[mname] == frozenset():
                continue
            met = None
            for caller, held in slist:
                ce = entry.get(caller, frozenset())
                if ce is _TOP:
                    continue  # unresolved caller contributes nothing yet
                eff = frozenset(ce) | held
                met = eff if met is None else (met & eff)
            if met is not None and met != entry[mname]:
                entry[mname] = met
                changed = True
    return {m: (None if e is _TOP else frozenset(e))
            for m, e in entry.items()}


def _thread_held_fixpoint(c: _ClassInfo) -> Dict[str, frozenset]:
    """Minimum lock set held when each method runs ON A SPAWNED THREAD:
    seeded at the thread entries (nothing held), propagated through
    self-calls with the lexical held-set at each call site.  Methods not
    in the result are unreachable from any thread entry — C302 does not
    apply to them."""
    held_map: Dict[str, frozenset] = {
        m: frozenset() for m in c.thread_entries if m in c.methods
    }
    changed = True
    while changed:
        changed = False
        for mname in list(held_map):
            fn = c.methods.get(mname)
            if fn is None:
                continue
            for ev in fn.events:
                if ev.kind != "self_call":
                    continue
                cand = held_map[mname] | ev.held
                cur = held_map.get(ev.name)
                new = cand if cur is None else (cur & cand)
                if new != cur:
                    held_map[ev.name] = new
                    changed = True
    return held_map


def _acquires_fixpoint(c: _ClassInfo) -> Dict[str, frozenset]:
    """Locks each method may acquire (directly or via self-calls)."""
    acq: Dict[str, Set[str]] = {m: set() for m in c.methods}
    for mname, fn in c.methods.items():
        for ev in fn.events:
            if ev.kind == "acquire":
                acq[mname].add(ev.name)
    changed = True
    while changed:
        changed = False
        for mname, fn in c.methods.items():
            for ev in fn.events:
                if ev.kind == "self_call" and ev.name in acq:
                    before = len(acq[mname])
                    acq[mname] |= acq[ev.name]
                    if len(acq[mname]) != before:
                        changed = True
    return {m: frozenset(s) for m, s in acq.items()}


class _Linter:
    def __init__(self, universe: _Universe):
        self.u = universe
        self.diags: List[Diagnostic] = []
        self._acq_cache: Dict[str, Dict[str, frozenset]] = {}
        # edge -> (relpath, line) where first observed
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # -- pragma-aware emit ----------------------------------------------
    def _emit(self, m: _ModuleInfo, rule: str, severity: Severity,
              message: str, line: int, hint: Optional[str] = None,
              layer: Optional[str] = None) -> bool:
        pragma = m.pragmas.get(line)
        if pragma and rule in pragma[0]:
            m.pragma_used.add(line)
            return False
        self.diags.append(Diagnostic(
            rule=rule, severity=severity, message=message,
            source=m.relpath, line=line, hint=hint, layer=layer,
        ))
        return True

    def _acquires(self, c: _ClassInfo) -> Dict[str, frozenset]:
        got = self._acq_cache.get(c.key)
        if got is None:
            got = self._acq_cache[c.key] = _acquires_fixpoint(c)
        return got

    def _edge(self, m: _ModuleInfo, a: str, b: str, line: int) -> None:
        if a == b:
            return  # reentrant same-lock (RLock) is not an ordering event
        pragma = m.pragmas.get(line)
        if pragma and "C303" in pragma[0]:
            m.pragma_used.add(line)
            return
        self.edges.setdefault((a, b), (m.relpath, line))

    # -- per-class -------------------------------------------------------
    def lint_class(self, m: _ModuleInfo, c: _ClassInfo) -> None:
        entry = _entry_held_fixpoint(c)
        thread_held = _thread_held_fixpoint(c)
        acquires = self._acquires(c)

        def eff(mname: str, ev: _Event) -> Optional[frozenset]:
            """Effective held set for C301/C304, None = unknowable context
            (dynamically-dispatched private method)."""
            base = entry.get(mname, frozenset())
            if ev.thread_side and mname not in c.thread_entries:
                # nested thread body: entry-held of the enclosing method
                # does NOT apply (fresh thread holds nothing)
                base = frozenset()
            if base is None:
                return None
            return ev.held | base

        # guarded fields: written at least once under a lock
        guards: Dict[str, Set[str]] = {}
        for mname, fn in c.methods.items():
            if mname == "__init__":
                continue
            for ev in fn.events:
                if ev.kind == "write":
                    h = eff(mname, ev)
                    if h:
                        guards.setdefault(ev.name, set()).update(h)

        for mname, fn in c.methods.items():
            if mname == "__init__":
                continue
            for ev in fn.events:
                h = eff(mname, ev)
                if h is None:
                    h_lex = ev.held  # lexical only; skip guard rules
                else:
                    h_lex = h
                if ev.kind == "write" and ev.name in guards and h is not None:
                    if not (h & guards[ev.name]):
                        self._emit(
                            m, "C301", Severity.ERROR,
                            f"field {ev.name!r} is written here without "
                            f"{_fmt_locks(guards[ev.name])}, but other "
                            "writes hold it — two writers can interleave",
                            ev.line, layer=f"{c.name}.{mname}",
                            hint="take the lock around this write, or move "
                            "the field out of the guarded set",
                        )
                elif ev.kind == "read" and ev.name in guards and (
                    mname in thread_held or ev.thread_side
                ):
                    on_thread = ev.held | (
                        frozenset() if ev.thread_side
                        else thread_held.get(mname, frozenset())
                    )
                    if not (on_thread & guards[ev.name]):
                        self._emit(
                            m, "C302", Severity.ERROR,
                            f"guarded field {ev.name!r} read without "
                            f"{_fmt_locks(guards[ev.name])} on a thread-entry "
                            "path — the second thread can observe torn/stale "
                            "state",
                            ev.line, layer=f"{c.name}.{mname}",
                            hint="read under the lock (snapshot into a local "
                            "if the hold must stay short)",
                        )
                elif ev.kind in ("blocking", "sleep", "wait") and h_lex:
                    self._emit(
                        m, "C304", Severity.WARNING,
                        f"blocking call {ev.name} while holding "
                        f"{_fmt_locks(h_lex)} — every other thread touching this "
                        "lock stalls behind the i/o",
                        ev.line, layer=f"{c.name}.{mname}",
                        hint="move the blocking op outside the critical "
                        "section, or annotate the intentional hold: "
                        "# lock: allow[C304] <why>",
                    )
                if ev.kind == "wait" and ev.in_loop and ev.detail == "event":
                    self._emit(
                        m, "C305", Severity.WARNING,
                        f"no-timeout Event.wait on {ev.name!r} inside a "
                        "loop — a stop flag can never interrupt it",
                        ev.line, layer=f"{c.name}.{mname}",
                        hint="wait(timeout) and re-check the stop condition "
                        "each iteration",
                    )
                if ev.kind == "sleep" and ev.in_loop:
                    self._maybe_c306(m, c, fn, ev, mname)
                if ev.kind == "acquire":
                    for holder in h_lex:
                        self._edge(m, holder, ev.name, ev.line)
                if ev.kind == "self_call" and h_lex:
                    for b in acquires.get(ev.name, ()):
                        for holder in h_lex:
                            self._edge(m, holder, b, ev.line)
                if ev.kind == "other_call" and h_lex:
                    other = self.u.resolve_foreign_method(ev.name, c)
                    if other is not None:
                        oacq = self._acquires(other)
                        locks = oacq.get(ev.name, frozenset())
                        for b in locks:
                            for holder in h_lex:
                                self._edge(m, holder, b, ev.line)

            # C305: non-daemon threads with no join path
            for sp in fn.spawns:
                if sp.daemon is True or (
                    sp.var is not None and sp.var in fn.daemonized_vars
                ):
                    continue
                joined = (
                    (sp.var is not None and sp.var in fn.joined_vars)
                    or (sp.attr is not None and sp.attr in c.joined_attrs)
                )
                if not joined:
                    self._emit(
                        m, "C305", Severity.WARNING,
                        "non-daemon thread with no join path — interpreter "
                        "shutdown blocks on it forever if its loop never "
                        "exits",
                        sp.line, layer=f"{c.name}.{mname}",
                        hint="daemon=True for best-effort workers, or keep "
                        "a handle and join() it on close/stop",
                    )

    def _maybe_c306(self, m: _ModuleInfo, c: Optional[_ClassInfo],
                    fn: _FnInfo, ev: _Event, mname: str) -> None:
        injectable = fn.params & _SLEEP_INJECTABLES
        if not injectable and c is not None:
            injectable = c.init_params & _SLEEP_INJECTABLES
        if injectable:
            return
        where = f"{c.name}.{mname}" if c is not None else mname
        self._emit(
            m, "C306", Severity.WARNING,
            "time.sleep polling loop with no injectable clock — tests "
            "must burn wall time to drive it (the LeaseFile "
            "clock=/sleep= discipline)",
            ev.line, layer=where,
            hint="accept sleep=time.sleep (and clock=time.time if "
            "deadlines are involved) and call the injected hooks",
        )

    # -- module level ----------------------------------------------------
    def lint_module_functions(self, m: _ModuleInfo) -> None:
        # module pseudo-class: module-level locks guard `global` writes
        guards: Dict[str, Set[str]] = {}
        for fn in m.functions.values():
            for ev in fn.events:
                if ev.kind == "gwrite" and ev.held:
                    guards.setdefault(ev.name, set()).update(ev.held)
        for fname, fn in m.functions.items():
            for ev in fn.events:
                if ev.kind == "gwrite" and ev.name in guards:
                    if not (ev.held & guards[ev.name]):
                        self._emit(
                            m, "C301", Severity.ERROR,
                            f"module global {ev.name!r} written without "
                            f"{_fmt_locks(guards[ev.name])}, but other "
                            "writes hold it",
                            ev.line, layer=fname,
                            hint="take the module lock around this write",
                        )
                elif ev.kind in ("blocking", "sleep", "wait") and ev.held:
                    self._emit(
                        m, "C304", Severity.WARNING,
                        f"blocking call {ev.name} while holding "
                        f"{_fmt_locks(ev.held)}",
                        ev.line, layer=fname,
                        hint="move the blocking op outside the critical "
                        "section, or annotate: # lock: allow[C304] <why>",
                    )
                if ev.kind == "sleep" and ev.in_loop:
                    self._maybe_c306(m, None, fn, ev, fname)
                if ev.kind == "wait" and ev.in_loop and ev.detail == "event":
                    self._emit(
                        m, "C305", Severity.WARNING,
                        f"no-timeout Event.wait on {ev.name!r} inside a loop",
                        ev.line, layer=fname,
                        hint="wait(timeout) and re-check the stop condition",
                    )
                if ev.kind == "acquire":
                    for holder in ev.held:
                        self._edge(m, holder, ev.name, ev.line)
            for sp in fn.spawns:
                if sp.daemon is True or (
                    sp.var is not None and sp.var in fn.daemonized_vars
                ):
                    continue
                if sp.var is not None and sp.var in fn.joined_vars:
                    continue
                self._emit(
                    m, "C305", Severity.WARNING,
                    "non-daemon thread with no join path",
                    sp.line, layer=fname,
                    hint="daemon=True for best-effort workers, or keep a "
                    "handle and join() it",
                )

    def check_unused_pragmas(self, modules) -> None:
        """A pragma that suppressed nothing is a stale annotation — the
        hold it justified moved or stopped being blocking.  Reported as
        C300 (the shared stale-pragma discipline, analysis.pragmas) so
        the allowlist stays an honest record of intentional holds."""
        for m in modules:
            table = {
                line: _pragmas.Pragma(line, frozenset(rules_), just)
                for line, (rules_, just) in m.pragmas.items()
            }
            self.diags.extend(_pragmas.stale_findings(
                table, m.pragma_used, "lock", m.relpath,
            ))

    # -- C303 cycle check (package-wide) ---------------------------------
    def check_cycles(self) -> None:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        seen: Set[str] = set()
        reported: Set[frozenset] = set()

        def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
            seen.add(node)
            stack.append(node)
            on_stack.add(node)
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_stack:
                    cycle = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        self._report_cycle(cycle)
                elif nxt not in seen:
                    dfs(nxt, stack, on_stack)
            stack.pop()
            on_stack.discard(node)

        for node in sorted(graph):
            if node not in seen:
                dfs(node, [], set())

    def _report_cycle(self, cycle: List[str]) -> None:
        sites = []
        for a, b in zip(cycle, cycle[1:]):
            where = self.edges.get((a, b))
            if where:
                sites.append(f"{a} -> {b} at {where[0]}:{where[1]}")
        first = self.edges.get((cycle[0], cycle[1]), ("", 0))
        self.diags.append(Diagnostic(
            rule="C303", severity=Severity.ERROR,
            message="static lock-order inversion: "
            + " -> ".join(cycle) + " (" + "; ".join(sites) + ")",
            source=first[0] or None, line=first[1] or None,
            hint="pick one global order for these locks and acquire them "
            "in it everywhere (or collapse them into one lock)",
        ))


def _fmt_locks(locks) -> str:
    names = sorted(locks)
    if len(names) == 1:
        return f"lock {names[0]!r}"
    return "any of {" + ", ".join(repr(n) for n in names) + "}"


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def _parse_module(path: str, base: str) -> Tuple[Optional[_ModuleInfo],
                                                 Optional[ast.Module],
                                                 List[Diagnostic]]:
    relpath = os.path.relpath(path, base) if base else path
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return None, None, [Diagnostic(
            rule="C300", severity=Severity.ERROR,
            message=f"syntax error: {e.msg}", source=relpath, line=e.lineno,
        )]
    info = _declared(tree, _module_name(path, base or os.path.dirname(path)),
                     relpath)
    diags: List[Diagnostic] = []
    _collect_pragmas(src, relpath, diags, info)
    return info, tree, diags


def _analyze_bodies(universe: _Universe, info: _ModuleInfo,
                    tree: ast.Module) -> None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            c = info.classes[node.name]
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                fn = _FnInfo(
                    name=item.name,
                    params={a.arg for a in item.args.args}
                    | {a.arg for a in item.args.kwonlyargs},
                    is_thread_entry=item.name in c.thread_entries,
                )
                c.methods[item.name] = fn
                _FnScanner(universe, info, c, fn,
                           qual=f"{node.name}.{item.name}").scan(item)
                for sp in fn.spawns:
                    if sp.target is not None and sp.target.startswith("self."):
                        c.thread_entries.add(sp.target[len("self."):])
            # spawn var/attr binding: `t = Thread(...)` / `self.x = Thread(...)`
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = c.methods[item.name]
                    _bind_spawn_vars(item, fn)
            # entry flags may have arrived after scanning (Timer in a later
            # method): re-mark
            for mname in c.thread_entries:
                if mname in c.methods:
                    c.methods[mname].is_thread_entry = True
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = _FnInfo(
                name=node.name,
                params={a.arg for a in node.args.args}
                | {a.arg for a in node.args.kwonlyargs},
            )
            info.functions[node.name] = fn
            _FnScanner(universe, info, None, fn, qual=node.name).scan(node)
            _bind_spawn_vars(node, fn)


def _bind_spawn_vars(fn_node: ast.AST, fn: _FnInfo) -> None:
    """Attach `t = Thread(...)` / `self.x = Thread(...)` bindings to the
    recorded spawns (by line) for the C305 join-path check."""
    for sub in ast.walk(fn_node):
        if not isinstance(sub, ast.Assign) or not isinstance(sub.value, ast.Call):
            continue
        d = _dotted(sub.value.func) or ""
        if d.rpartition(".")[2] not in _THREAD_CTORS:
            continue
        var = attr = None
        for t in sub.targets:
            if isinstance(t, ast.Name):
                var = t.id
            else:
                f = _self_field(t)
                if f is not None:
                    attr = f.attr
        for sp in fn.spawns:
            if sp.line == sub.value.lineno:
                sp.var = sp.var or var
                sp.attr = sp.attr or attr
        # `self.attr = t` later in the function also binds the attr
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Name):
            f = _self_field(sub.targets[0]) if sub.targets else None
            if f is not None:
                for sp in fn.spawns:
                    if sp.var == sub.value.id and sp.attr is None:
                        sp.attr = f.attr


def _lint_files(paths: Sequence[str], base: str) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    parsed: List[Tuple[_ModuleInfo, ast.Module]] = []
    for path in paths:
        info, tree, file_diags = _parse_module(path, base)
        diags.extend(file_diags)
        if info is not None and tree is not None:
            parsed.append((info, tree))
    universe = _Universe([info for info, _ in parsed])
    for info, tree in parsed:
        _analyze_bodies(universe, info, tree)
    linter = _Linter(universe)
    for info, _ in parsed:
        for c in info.classes.values():
            linter.lint_class(info, c)
        linter.lint_module_functions(info)
    linter.check_cycles()
    linter.check_unused_pragmas([info for info, _ in parsed])
    diags.extend(linter.diags)
    return diags


def lint_concurrency_file(path: str, root: Optional[str] = None) -> List[Diagnostic]:
    """All C-rules over one source file (cross-class resolution limited to
    the classes that file defines) — the mutation-test entry point."""
    base = root or os.path.dirname(os.path.abspath(path))
    return _lint_files([os.path.abspath(path)], base)


def lint_concurrency_package(root: Optional[str] = None,
                             extra_paths: Optional[List[str]] = None
                             ) -> List[Diagnostic]:
    """Every C-rule over the paddle_tpu package tree (plus ``extra_paths``)
    — the ``paddle-tpu lint --concurrency`` body."""
    if root is None:
        import paddle_tpu

        root = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    base = os.path.dirname(root)
    files: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        files.extend(
            os.path.join(dirpath, fn) for fn in sorted(filenames)
            if fn.endswith(".py")
        )
    return _lint_files(sorted(files) + list(extra_paths or ()), base)
