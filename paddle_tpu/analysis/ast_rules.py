"""Self-lint: custom AST rules over paddle_tpu's own source.

The graph and trace linters check the user's model; these rules check *us* —
the host-side Python that builds and drives the traced program.  They encode
the trace-time discipline jax demands (everything outside jnp is frozen into
the jaxpr at trace time) plus repo invariants the runtime can't check early.

Rules (``A###``):

  A201 time-in-jit        ``time.time()``-family calls inside a function
                          traced by ``jax.jit`` — the value is baked in at
                          trace time and never ticks again
  A202 host-rng-in-jit    ``random.*`` / ``np.random.*`` sampling inside a
                          jitted function — one draw at trace time, the
                          same "random" constant every step (use
                          ``jax.random`` with a threaded key)
  A203 unseeded-reader-rng  direct global-module ``random.X(...)`` /
                          ``np.random.X(...)`` sampling in reader/dataset
                          modules — reader order becomes irreproducible and
                          immune to the ``seed`` flag (thread an explicit
                          ``rng`` / ``random.Random(seed)``)
  A204 duplicate-flag     the same flag name registered twice via
                          ``define_flag`` (the loser silently wins; see
                          utils/flags.py re-registration guard)
  A205 wall-clock-in-obs  ``time.time()``/``time.time_ns()`` in an
                          ``obs/`` module — span timestamps must come
                          from the MONOTONIC, injectable tracer clock
                          (an NTP step would fold a timeline backward).
                          The one legitimate wall read (the merge
                          anchor) carries the pragma
                          ``# obs: allow-wall-clock <why>`` with a
                          REQUIRED justification.
  A206 raw-deserialization  ``pickle.load``/``pickle.loads``/
                          ``pickle.Unpickler`` or a bare zero-argument
                          ``.recv()`` (the ``multiprocessing.connection``
                          implicit-unpickle read) ANYWHERE outside
                          ``master_wire.py`` — unpickling executes
                          attacker-controlled bytes, and the RPC plane's
                          whole safety story is that every byte crossing a
                          process boundary rides the restricted typed
                          codec instead.  Genuinely-local, never-network
                          reads (a CRC-verified AOT cache blob, an
                          operator-written dataset file) escape with
                          ``# wire: allow[A206] <why>`` — justification
                          REQUIRED, stale pragmas flagged (the shared
                          analysis.pragmas discipline).

Run via :func:`lint_package` (the ``paddle-tpu lint`` CLI / ``make lint``).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

_TIME_FNS = frozenset({
    "time", "perf_counter", "monotonic", "process_time", "time_ns",
    "perf_counter_ns", "monotonic_ns",
})

# numpy/np-module RNG samplers + `random` module samplers; seeding calls and
# generator constructors are fine (they are how you FIX the finding)
_RNG_OK = frozenset({"RandomState", "default_rng", "Random", "seed", "SeedSequence"})

# reader-plane modules for A203 (package-relative path prefixes)
_READER_PREFIXES = ("reader" + os.sep, "dataset" + os.sep)

# the wall-clock time.* calls A205 forbids in obs/ modules (monotonic /
# perf_counter are exactly what spans SHOULD use, so they stay legal)
_WALL_FNS = frozenset({"time", "time_ns"})

# the pickle entry points that EXECUTE payload bytes (A206); dumps/dump
# only serialize and stay legal
_PICKLE_LOADS = frozenset({"load", "loads", "Unpickler"})
_PICKLE_MODULES = frozenset({"pickle", "cPickle", "_pickle", "dill"})


def _name_of(node: ast.AST) -> Optional[str]:
    """Dotted name of an expression like ``np.random.rand`` -> that string."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _jit_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(names bound to jax.jit itself, module aliases of jax) — so both
    ``jax.jit(f)`` and ``from jax import jit; jit(f)`` are recognized."""
    jit_names: Set[str] = set()
    jax_mods: Set[str] = {"jax"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    jax_mods.add(a.asname or "jax")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "jit":
                        jit_names.add(a.asname or "jit")
    return jit_names, jax_mods


def _is_jit_expr(node: ast.AST, jit_names: Set[str], jax_mods: Set[str]) -> bool:
    """True for ``jax.jit``, a bare jit alias, or ``partial(jax.jit, ...)``."""
    dotted = _name_of(node)
    if dotted is not None:
        if dotted in jit_names:
            return True
        head, _, tail = dotted.rpartition(".")
        if tail == "jit" and head in jax_mods:
            return True
    if isinstance(node, ast.Call):  # partial(jax.jit, ...) decorator form
        fn = _name_of(node.func)
        if fn in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0], jit_names, jax_mods)
    return False


def _jitted_functions(tree: ast.Module) -> Tuple[List[ast.AST], List[ast.Lambda]]:
    """FunctionDefs traced by jax.jit in this module: decorated with it, or
    passed to it by name (``jax.jit(step, ...)`` — the trainer/step builder
    idiom).  By-name resolution is SCOPE-AWARE: ``jax.jit(step)`` binds to
    the innermost ``def step`` visible from the call site (longest enclosing
    scope prefix), not to every same-named def in the module — two factories
    each defining a local ``step`` where only one is jitted must not flag
    the other.  Lambdas passed inline come back separately."""
    jit_names, jax_mods = _jit_aliases(tree)
    lambdas: List[ast.Lambda] = []
    funcs: List[ast.AST] = []
    # (scope path where DEFINED, name, node) / (scope path of the CALL, name)
    defs: List[Tuple[Tuple[str, ...], str, ast.AST]] = []
    calls: List[Tuple[Tuple[str, ...], str]] = []

    def walk(node: ast.AST, scope: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(
                    _is_jit_expr(d, jit_names, jax_mods)
                    for d in child.decorator_list
                ):
                    funcs.append(child)
                defs.append((scope, child.name, child))
                walk(child, scope + (child.name,))
            else:
                if isinstance(child, ast.Call) and _is_jit_expr(
                    child.func, jit_names, jax_mods
                ) and child.args:
                    arg = child.args[0]
                    if isinstance(arg, ast.Name):
                        calls.append((scope, arg.id))
                    elif isinstance(arg, ast.Lambda):
                        lambdas.append(arg)
                walk(child, scope)

    walk(tree, ())

    for cscope, name in calls:
        best = None
        for dscope, dname, dnode in defs:
            if dname != name or dscope != cscope[: len(dscope)]:
                continue  # not this name / not visible from the call site
            if best is None or len(dscope) > len(best[0]):
                best = (dscope, dnode)
        if best is not None and best[1] not in funcs:
            funcs.append(best[1])
    return funcs, lambdas


def _host_rng_heads(tree: ast.Module) -> Set[str]:
    """Dotted-name heads that denote HOST RNG modules in this file.  Only
    an actual ``import random`` binds the bare name ``random`` to the
    stdlib module — ``from jax import random`` binds the (key-threaded,
    jit-safe) jax namespace to the same name and must NOT flag."""
    heads: Set[str] = {"np.random", "numpy.random"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "random":
                    heads.add(a.asname or "random")
                elif a.name == "numpy.random":
                    heads.add(a.asname or "numpy.random")
    return heads


def _scan_traced_body(body: ast.AST, relpath: str, diags: List[Diagnostic],
                      owner: str, rng_heads: Set[str]) -> None:
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        dotted = _name_of(node.func)
        if dotted is None:
            continue
        head, _, tail = dotted.rpartition(".")
        if head == "time" and tail in _TIME_FNS:
            diags.append(Diagnostic(
                rule="A201", severity=Severity.ERROR,
                message=f"`{dotted}()` inside jit-traced function "
                f"{owner!r} — evaluated once at trace time, constant "
                "forever after",
                source=relpath, line=node.lineno,
                hint="time on the host around the dispatch "
                "(utils.timers.stat_timer), never inside the traced step",
            ))
        elif head in rng_heads and tail not in _RNG_OK:
            diags.append(Diagnostic(
                rule="A202", severity=Severity.ERROR,
                message=f"`{dotted}(...)` inside jit-traced function "
                f"{owner!r} — drawn once at trace time, the same value "
                "every step",
                source=relpath, line=node.lineno,
                hint="use jax.random with a key threaded through the step "
                "(ApplyContext.layer_rng)",
            ))


def _scan_reader_rng(tree: ast.Module, relpath: str,
                     diags: List[Diagnostic], rng_heads: Set[str]) -> None:
    # `import random as _random` aliases resolve; `from jax import random`
    # does not flag (the shared _host_rng_heads resolution, same as A202)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _name_of(node.func)
        if dotted is None:
            continue
        head, _, tail = dotted.rpartition(".")
        if head in rng_heads and tail not in _RNG_OK:
            diags.append(Diagnostic(
                rule="A203", severity=Severity.ERROR,
                message=f"global-RNG call `{dotted}(...)` in reader module "
                "— sample order is irreproducible and ignores the `seed` "
                "flag",
                source=relpath, line=node.lineno,
                hint="accept an explicit `rng` (random.Random/np.random."
                "RandomState seeded from the seed flag) and sample from it",
            ))


def _scan_obs_wall_clock(tree: ast.Module, src: str, relpath: str,
                         diags: List[Diagnostic]) -> None:
    """A205 over one obs/ module: wall-clock calls are forbidden unless
    the LINE carries ``# obs: allow-wall-clock <justification>``.  The
    pragma parses through the shared plane parser (analysis.pragmas) —
    comment tokens only, empty justification is its own finding, and a
    stale pragma (suppressing nothing) reports uniformly with the
    ``# lock:``/``# num:`` planes.  Alias-aware like the RNG rules:
    ``import time as t; t.time()`` and ``from time import time`` must
    not slip past the ban."""
    from paddle_tpu.analysis import pragmas as _pragmas

    time_mods = {"time"}
    bare_wall: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _WALL_FNS:
                    bare_wall.add(a.asname or a.name)
    pragma_diags: List[Diagnostic] = []
    table = _pragmas.collect(src, "obs", relpath, pragma_diags)
    diags.extend(pragma_diags)
    # a malformed (empty-why) pragma already reported above — the wall
    # read on its line must not double-report, but is NOT suppressed
    # either in the sense that the pragma finding keeps the lint red
    malformed = {d.line for d in pragma_diags if d.line is not None}
    used: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _name_of(node.func)
        if dotted is None:
            continue
        head, _, tail = dotted.rpartition(".")
        if not (
            (head in time_mods and tail in _WALL_FNS)
            or (head == "" and tail in bare_wall)
        ):
            continue
        if node.lineno in table:
            used.add(node.lineno)
            continue
        if node.lineno in malformed:
            continue
        diags.append(Diagnostic(
            rule="A205", severity=Severity.ERROR,
            message=f"wall-clock `{dotted}()` in an obs/ module — span "
            "timestamps must be monotonic (an NTP step folds the "
            "timeline backward)",
            source=relpath, line=node.lineno,
            hint="use the tracer's injectable monotonic clock; a "
            "genuinely-needed wall read (merge anchor) takes "
            "`# obs: allow-wall-clock <why>`",
        ))
    diags.extend(_pragmas.stale_findings(table, used, "obs", relpath))


def _scan_wire_hygiene(tree: ast.Module, src: str, relpath: str,
                       diags: List[Diagnostic]) -> None:
    """A206 over one module: raw deserialization outside master_wire.py.

    Alias-aware for the pickle module (``import pickle as p``,
    ``from pickle import loads``); the bare ``.recv()`` check keys on the
    ZERO-argument signature — ``socket.recv(bufsize)`` reads bytes (legal
    everywhere), ``Connection.recv()`` unpickles (the hazard)."""
    from paddle_tpu.analysis import pragmas as _pragmas

    if os.path.basename(relpath) == "master_wire.py":
        return  # the codec is the one legitimate home of deserialization
    mods: Set[str] = set()
    bare: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in _PICKLE_MODULES:
                    mods.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module in _PICKLE_MODULES:
            for a in node.names:
                if a.name in _PICKLE_LOADS:
                    bare[a.asname or a.name] = f"{node.module}.{a.name}"
    pragma_diags: List[Diagnostic] = []
    table = _pragmas.collect(src, "wire", relpath, pragma_diags)
    diags.extend(pragma_diags)
    malformed = {d.line for d in pragma_diags if d.line is not None}
    used: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        hit: Optional[str] = None
        dotted = _name_of(node.func)
        if dotted is not None:
            head, _, tail = dotted.rpartition(".")
            if head in mods and tail in _PICKLE_LOADS:
                hit = f"`{dotted}(...)` executes payload bytes to deserialize"
            elif head == "" and tail in bare:
                hit = (f"`{dotted}(...)` ({bare[tail]}) executes payload "
                       f"bytes to deserialize")
        if hit is None and (
            isinstance(node.func, ast.Attribute) and node.func.attr == "recv"
            and not node.args and not node.keywords
        ):
            hit = ("bare `.recv()` (Connection-style) implicitly unpickles "
                   "whatever the peer sent")
        if hit is None:
            continue
        pragma = table.get(node.lineno)
        if pragma is not None and pragma.suppresses("A206"):
            used.add(node.lineno)
            continue
        if node.lineno in malformed:
            continue  # the rejected pragma already keeps the lint red
        diags.append(Diagnostic(
            rule="A206", severity=Severity.ERROR,
            message=f"{hit} outside master_wire.py — raw deserialization "
            "of bytes you did not verify is forbidden on every plane "
            "(a corrupt or hostile frame must be a structured rejection, "
            "never an exec)",
            source=relpath, line=node.lineno,
            hint="route the bytes through paddle_tpu.master_wire "
            "(encode_payload/decode_payload, send_msg/recv_msg); a "
            "genuinely-local, never-network read takes "
            "`# wire: allow[A206] <why>`",
        ))
    diags.extend(_pragmas.stale_findings(table, used, "wire", relpath))


def _scan_flag_defs(tree: ast.Module, relpath: str,
                    defs: Dict[str, Tuple[str, int]],
                    diags: List[Diagnostic]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _name_of(node.func)
        if dotted is None or dotted.split(".")[-1] != "define_flag":
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant):
            continue
        name = node.args[0].value
        if not isinstance(name, str):
            continue
        if name in defs:
            first_file, first_line = defs[name]
            diags.append(Diagnostic(
                rule="A204", severity=Severity.ERROR,
                message=f"flag {name!r} registered twice (first at "
                f"{first_file}:{first_line})",
                source=relpath, line=node.lineno,
                hint="reuse the existing flag or pick a distinct name; "
                "conflicting re-registration raises at import "
                "(utils.flags.define_flag)",
            ))
        else:
            defs[name] = (relpath, node.lineno)


def lint_file(path: str, root: Optional[str] = None,
              _flag_defs: Optional[Dict[str, Tuple[str, int]]] = None
              ) -> List[Diagnostic]:
    """All AST rules over one source file."""
    relpath = os.path.relpath(path, root) if root else path
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Diagnostic(
            rule="A200", severity=Severity.ERROR,
            message=f"syntax error: {e.msg}", source=relpath, line=e.lineno,
        )]
    diags: List[Diagnostic] = []
    funcs, lambdas = _jitted_functions(tree)
    rng_heads = _host_rng_heads(tree)
    for fn in funcs:
        _scan_traced_body(fn, relpath, diags, fn.name, rng_heads)
    for lam in lambdas:
        _scan_traced_body(lam, relpath, diags, "<lambda>", rng_heads)
    if relpath.replace("paddle_tpu" + os.sep, "", 1).startswith(
        _READER_PREFIXES
    ) or os.sep + "dataset" + os.sep in relpath or (
        os.sep + "reader" + os.sep in relpath
    ):
        _scan_reader_rng(tree, relpath, diags, rng_heads)
    if os.sep + "obs" + os.sep in relpath or relpath.replace(
        "paddle_tpu" + os.sep, "", 1
    ).startswith("obs" + os.sep):
        _scan_obs_wall_clock(tree, src, relpath, diags)
    _scan_wire_hygiene(tree, src, relpath, diags)
    if _flag_defs is not None:
        _scan_flag_defs(tree, relpath, _flag_defs, diags)
    return diags


def lint_package(root: Optional[str] = None,
                 extra_paths: Optional[List[str]] = None) -> List[Diagnostic]:
    """Run every AST rule over the paddle_tpu package tree (plus any
    ``extra_paths`` files, e.g. bench.py) — the ``paddle-tpu lint`` body."""
    if root is None:
        import paddle_tpu

        root = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    diags: List[Diagnostic] = []
    flag_defs: Dict[str, Tuple[str, int]] = {}
    base = os.path.dirname(root)
    files: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        files.extend(
            os.path.join(dirpath, fn) for fn in sorted(filenames)
            if fn.endswith(".py")
        )
    for path in sorted(files) + list(extra_paths or ()):
        diags.extend(lint_file(path, root=base, _flag_defs=flag_defs))
    return diags
