"""Static analysis — config-time diagnostics and jaxpr-level TPU hazard
checks (the reference ``config_parser.py`` config_assert plane, grown into
five passes over the trace-time graph stack):

  * :mod:`~paddle_tpu.analysis.graph_lint` — abstract shape/dtype/arity
    propagation over the Topology IR before any trace (rules ``G###``);
  * :mod:`~paddle_tpu.analysis.trace_lint` — jaxpr inspection of the
    compiled step for TPU hazards: f64 leaks, closure-captured weights,
    host callbacks, recompile churn (rules ``T###``);
  * :mod:`~paddle_tpu.analysis.ast_rules` — self-lint of paddle_tpu's own
    source for trace-time discipline (rules ``A###``);
  * :mod:`~paddle_tpu.analysis.concurrency_lint` — lock-discipline lint
    over the package's own threaded planes (rules ``C###``);
  * :mod:`~paddle_tpu.analysis.lock_sanitizer` — the RUNTIME leg of the
    concurrency plane: instrumented locks (``PADDLE_TPU_LOCK_SANITIZER=1``)
    that detect lock-order cycles while the chaos drills run.

All passes share one diagnostic model (rule id, severity, layer/file
provenance, fix hint — :mod:`~paddle_tpu.analysis.diagnostics`) and are
wired into the CLI as ``paddle-tpu lint`` / ``make lint``.

Submodules import lazily (PEP 562): ``trace_lint``/``graph_lint`` pull jax
and the core IR, which the jax-free consumers of ``lock_sanitizer`` and
``diagnostics`` (master.py, the reader plane) must not pay for — the
``paddle-tpu master`` process stays jax-import-free.
"""

import importlib
from typing import List

# public name -> defining submodule (resolved on first attribute access)
_EXPORTS = {
    "Diagnostic": "diagnostics",
    "DiagnosticError": "diagnostics",
    "ProtocolError": "diagnostics",
    "protocol_error": "diagnostics",
    "Severity": "diagnostics",
    "config_assert": "diagnostics",
    "errors": "diagnostics",
    "format_diagnostics": "diagnostics",
    "raise_if_errors": "diagnostics",
    "lint_file": "ast_rules",
    "lint_package": "ast_rules",
    "attr_key_universe": "graph_lint",
    "lint_parsed": "graph_lint",
    "lint_topology": "graph_lint",
    "donation_audit": "trace_lint",
    "lint_jaxpr": "trace_lint",
    "lint_step": "trace_lint",
    "recompile_audit": "trace_lint",
    "trace_step": "trace_lint",
    "lint_concurrency_file": "concurrency_lint",
    "lint_concurrency_package": "concurrency_lint",
    "lint_protocol_package": "protocol_lint",
    "lint_protocol_sources": "protocol_lint",
    "explore_schedules": "interleave",
    "replay_spec": "interleave",
    "shrink_events": "interleave",
    "PrecisionCertificate": "numerics_lint",
    "certify_precision_plan": "numerics_lint",
    "lint_numerics_config": "numerics_lint",
    "lint_numerics_jaxpr": "numerics_lint",
    "lint_numerics_package": "numerics_lint",
    "lint_numerics_step": "numerics_lint",
    "NumericsSanitizer": "num_sanitizer",
    "num_sanitizer_armed": "num_sanitizer",
    "DeadlockReport": "lock_sanitizer",
    "make_lock": "lock_sanitizer",
    "make_rlock": "lock_sanitizer",
    "sanitizer_enabled": "lock_sanitizer",
}

__all__: List[str] = sorted(_EXPORTS)


def __getattr__(name: str):
    mod_name = _EXPORTS.get(name)
    if mod_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(f"{__name__}.{mod_name}")
    value = getattr(mod, name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
