"""Static analysis — config-time diagnostics and jaxpr-level TPU hazard
checks (the reference ``config_parser.py`` config_assert plane, grown into
three passes over the trace-time graph stack):

  * :mod:`~paddle_tpu.analysis.graph_lint` — abstract shape/dtype/arity
    propagation over the Topology IR before any trace (rules ``G###``);
  * :mod:`~paddle_tpu.analysis.trace_lint` — jaxpr inspection of the
    compiled step for TPU hazards: f64 leaks, closure-captured weights,
    host callbacks, recompile churn (rules ``T###``);
  * :mod:`~paddle_tpu.analysis.ast_rules` — self-lint of paddle_tpu's own
    source for trace-time discipline (rules ``A###``).

All passes share one diagnostic model (rule id, severity, layer/file
provenance, fix hint — :mod:`~paddle_tpu.analysis.diagnostics`) and are
wired into the CLI as ``paddle-tpu lint`` / ``make lint``.
"""

from paddle_tpu.analysis.ast_rules import lint_file, lint_package
from paddle_tpu.analysis.diagnostics import (
    Diagnostic,
    DiagnosticError,
    Severity,
    config_assert,
    errors,
    format_diagnostics,
    raise_if_errors,
)
from paddle_tpu.analysis.graph_lint import (
    attr_key_universe,
    lint_parsed,
    lint_topology,
)
from paddle_tpu.analysis.trace_lint import (
    donation_audit,
    lint_jaxpr,
    lint_step,
    recompile_audit,
    trace_step,
)

__all__ = [
    "Diagnostic",
    "DiagnosticError",
    "Severity",
    "attr_key_universe",
    "config_assert",
    "donation_audit",
    "errors",
    "format_diagnostics",
    "lint_file",
    "lint_jaxpr",
    "lint_package",
    "lint_parsed",
    "lint_step",
    "lint_topology",
    "raise_if_errors",
    "recompile_audit",
    "trace_step",
]
