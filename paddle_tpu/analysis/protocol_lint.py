"""Protocol conformance lint — P-rules over the distributed planes.

The master RPC plane (master.py), its HA/journal durability planes
(master_ha.py / master_journal.py), the typed wire codec (master_wire.py)
and the serving fleet (serving/router.py / serving/scheduler.py) form one
distributed protocol, but each invariant used to be asserted by exactly
one hand-written drill along one interleaving.  These passes cross-check
the protocol SURFACES against each other statically, so a change that
drifts one surface (a new RPC method, journal record type, request
status, fencing comparison, or timeout path) fires the lint everywhere
the other surfaces depend on it:

  P501  RPC surface conformance: every method in a ``_METHODS``-style
        whitelist has a handler on its service class; no
        codec-unrepresentable value is constructed on a reply path; the
        client/server plumbing is wired to the DECLARED whitelist.
  P502  Journal record conformance: every ``_journal({"t": ...})``
        literal is a registered record type with an ``_apply_*`` replay
        op; every registered type is emitted somewhere; payload-carrying
        types are re-emitted by compaction (the snapshot stays pure
        JSON); no orphan replay op.
  P503  Status-ledger exhaustiveness: every status literal assigned or
        compared anywhere in the serving planes is a member of the ONE
        declared disjoint set (``scheduler.TERMINAL_STATUSES``); every
        declared status is actually assigned; any parallel status-set
        literal must equal the declared set exactly.
  P504  Lease/fence monotonicity: epoch fences compare by EQUALITY
        (ordering accepts stale holders), journal sequences compare by
        ORDERING (equality breaks replay dedupe), and lease deadlines
        are written only with the registry lock held (a small entry-held
        inference over self-calls — the static leg PR 9's concurrency
        plane runs package-wide, specialized to the lease fields).
  P505  Timeout completeness: every RPC client ``_call`` has a deadline
        identifier and a raise path; no unbounded ``Connection.poll()``;
        no RPC client constructed with ``call_timeout_s=None``.

``# proto: allow[P504] <why>`` pragmas escape intentional findings (the
shared analysis/pragmas.py grammar); P500 is the bookkeeping rule for
malformed pragmas and missing/unparseable protocol surfaces.

Mutation tests inject a violation by rewriting ONE source in the map
passed to :func:`lint_protocol_sources`; ``paddle-tpu lint --protocol``
(:func:`lint_protocol_package`) lints the installed package and must
report zero findings.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis import pragmas as _pragmas
from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = [
    "PROTOCOL_FILES",
    "lint_protocol_package",
    "lint_protocol_sources",
]

# the protocol surfaces, relative to the package root
PROTOCOL_FILES = (
    "master.py",
    "master_ha.py",
    "master_journal.py",
    "master_wire.py",
    "serving/router.py",
    "serving/scheduler.py",
)

# (file with the whitelist literal, whitelist name, handler class)
_RPC_SURFACES = (
    ("master.py", "_METHODS", "Service"),
    ("serving/router.py", "ROUTER_METHODS", "Router"),
    ("serving/router.py", "ENGINE_METHODS", "EngineAgent"),
)

# constructors whose result the typed wire codec cannot represent
# (master_wire encodes None/bool/int/float/str/bytes/list/tuple/dict/
# ndarray only) — conservative: only PROVABLE constructions are flagged
_UNWIRE_CALLS = frozenset({
    "set", "frozenset", "complex", "bytearray", "memoryview", "iter",
    "map", "filter", "zip", "range", "enumerate", "reversed", "slice",
    "object", "open",
})

# the one transient (non-terminal) request status
_TRANSIENT_STATUSES = frozenset({"pending"})


def _err(rule: str, message: str, source: str, line: Optional[int],
         hint: str) -> Diagnostic:
    return Diagnostic(rule=rule, severity=Severity.ERROR, message=message,
                      source=source, line=line, hint=hint)


def _name_of(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute chain (``a.b.c``), else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_elts(node: ast.AST) -> Optional[List[Tuple[str, int]]]:
    """``[(value, line)]`` for a tuple/list/set/frozenset-of-str literal."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "frozenset" and node.args):
        node = node.args[0]
    if not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return None
    out = []
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.append((e.value, e.lineno))
    return out


def _module_assign(tree: ast.Module, name: str) -> Optional[ast.AST]:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return stmt.value
    return None


def _find_class(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef) and stmt.name == name:
            return stmt
    return None


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _own_returns(fn: ast.FunctionDef) -> List[ast.Return]:
    """Return statements of ``fn`` itself (nested defs excluded)."""
    out: List[ast.Return] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Return):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _unwireable(expr: ast.AST) -> List[Tuple[ast.AST, str]]:
    """Provably codec-unrepresentable constructions inside ``expr``."""
    bad: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(expr):
        if isinstance(node, (ast.Set, ast.SetComp)):
            bad.append((node, "set literal"))
        elif isinstance(node, ast.GeneratorExp):
            bad.append((node, "generator expression"))
        elif isinstance(node, ast.Lambda):
            bad.append((node, "lambda"))
        elif isinstance(node, ast.Constant) and (
                node.value is Ellipsis or isinstance(node.value, complex)):
            bad.append((node, f"constant {node.value!r}"))
        elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in _UNWIRE_CALLS):
            bad.append((node, f"{node.func.id}(...) call"))
    return bad


# ---------------------------------------------------------------------------
# P501 — RPC surface conformance
# ---------------------------------------------------------------------------

def _p501(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for rel, wl_name, cls_name in _RPC_SURFACES:
        tree = trees.get(rel)
        if tree is None:
            continue
        wl_node = _module_assign(tree, wl_name)
        methods = _str_elts(wl_node) if wl_node is not None else None
        if methods is None:
            diags.append(_err(
                "P500",
                f"RPC whitelist {wl_name} is not a module-level literal "
                f"tuple of method-name strings",
                rel, getattr(wl_node, "lineno", None),
                f"declare {wl_name} = (\"method\", ...) at module scope — "
                "the conformance plane cross-checks it statically",
            ))
            continue
        cls = _find_class(tree, cls_name)
        if cls is None:
            diags.append(_err(
                "P500", f"handler class {cls_name} not found", rel, None,
                f"{wl_name} names {cls_name} as its handler surface",
            ))
            continue
        handlers = _class_methods(cls)
        for meth, line in methods:
            fn = handlers.get(meth)
            if fn is None:
                diags.append(_err(
                    "P501",
                    f"RPC method {meth!r} in {wl_name} has no handler on "
                    f"{cls_name} — a client call would dispatch into "
                    f"AttributeError",
                    rel, line,
                    f"define {cls_name}.{meth}(...) or drop {meth!r} from "
                    f"{wl_name}",
                ))
                continue
            for ret in _own_returns(fn):
                if ret.value is None:
                    continue
                for node, what in _unwireable(ret.value):
                    diags.append(_err(
                        "P501",
                        f"reply path of RPC handler {cls_name}.{meth} "
                        f"constructs a codec-unrepresentable value "
                        f"({what}) — the typed wire codec would raise "
                        f"WireTypeError at reply time",
                        rel, getattr(node, "lineno", ret.lineno),
                        "reply with the wire universe only (None/bool/int/"
                        "float/str/bytes/list/tuple/dict/ndarray); e.g. "
                        "sorted(...) instead of a set",
                    ))
    # client/server plumbing must be wired to the DECLARED whitelists
    for rel, cls_name, wl_name in (
        ("master.py", "Client", "_METHODS"),
        ("master_ha.py", "HAClient", "_METHODS"),
    ):
        tree = trees.get(rel)
        if tree is None:
            continue
        cls = _find_class(tree, cls_name)
        if cls is None:
            diags.append(_err("P500", f"class {cls_name} not found", rel,
                              None, "the RPC client surface moved?"))
            continue
        if not any(isinstance(n, ast.Name) and n.id == wl_name
                   for n in ast.walk(cls)):
            diags.append(_err(
                "P501",
                f"{cls_name} does not delegate from {wl_name} — its "
                f"surface can silently drift from the server whitelist",
                rel, cls.lineno,
                f"route __getattr__ delegation through {wl_name} (one "
                "definition for the whole surface)",
            ))
    router = trees.get("serving/router.py")
    if router is not None:
        wired: Set[str] = set()
        for node in ast.walk(router):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "methods" and isinstance(kw.value, ast.Name):
                        wired.add(kw.value.id)
        for wl_name in ("ROUTER_METHODS", "ENGINE_METHODS"):
            if wl_name not in wired:
                diags.append(_err(
                    "P501",
                    f"no Server/Client is constructed with "
                    f"methods={wl_name} — the declared whitelist is not "
                    f"what the wire actually enforces",
                    "serving/router.py", None,
                    f"pass methods={wl_name} (the NAME, not a copied "
                    "literal) to the Server/Client constructor",
                ))
    return diags


# ---------------------------------------------------------------------------
# P502 — journal record conformance
# ---------------------------------------------------------------------------

def _journal_dicts(tree: ast.Module) -> List[Tuple[ast.Call, ast.AST]]:
    """Every ``*._journal(<arg>)`` call in ``tree`` with its first arg."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_journal" and node.args):
            out.append((node, node.args[0]))
    return out


def _dict_t(d: ast.AST) -> Optional[Tuple[str, bool]]:
    """(record type, carries-"result"-key) of a literal journal dict."""
    if not isinstance(d, ast.Dict):
        return None
    t_val, has_result = None, False
    for k, v in zip(d.keys, d.values):
        key = getattr(k, "value", None)
        if key == "t":
            if not (isinstance(v, ast.Constant) and isinstance(v.value, str)):
                return None
            t_val = v.value
        elif key == "result":
            has_result = True
    return (t_val, has_result) if t_val is not None else None


def _p502(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    mj = trees.get("master_journal.py")
    m = trees.get("master.py")
    if mj is None or m is None:
        return diags
    rt_node = _module_assign(mj, "RECORD_TYPES")
    rt = _str_elts(rt_node) if rt_node is not None else None
    if rt is None:
        diags.append(_err(
            "P500", "RECORD_TYPES is not a module-level frozenset literal "
            "of record-type strings", "master_journal.py",
            getattr(rt_node, "lineno", None),
            "declare RECORD_TYPES = frozenset({\"lease\", ...}) — every "
            "journal surface keys on it",
        ))
        return diags
    record_types = {v for v, _ in rt}
    rt_line = rt[0][1] if rt else None
    svc = _find_class(m, "Service")
    if svc is None:
        diags.append(_err("P500", "class Service not found", "master.py",
                          None, "the journal emission surface moved?"))
        return diags
    handlers = _class_methods(svc)
    apply_ops = {name[len("_apply_"):]: fn.lineno
                 for name, fn in handlers.items()
                 if name.startswith("_apply_")}
    compact = handlers.get("_compact")
    compact_emits: Set[str] = set()
    if compact is not None:
        for node in ast.walk(compact):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append" and node.args
                    and len(node.args) >= 2):
                got = _dict_t(node.args[1])
                if got is not None:
                    compact_emits.add(got[0])
    emitted: Dict[str, Tuple[int, bool]] = {}
    for call, arg in _journal_dicts(m):
        got = _dict_t(arg)
        if got is None:
            diags.append(_err(
                "P502",
                "_journal() argument is not a literal dict with a literal "
                "\"t\" record type — the conformance plane (and journal "
                "replay) cannot check a computed record type",
                "master.py", call.lineno,
                "emit _journal({\"t\": \"<type>\", ...}) with the type as "
                "a string literal",
            ))
            continue
        t, has_result = got
        prev = emitted.get(t)
        emitted[t] = (call.lineno, has_result or (prev[1] if prev else False))
    for t, (line, has_result) in sorted(emitted.items()):
        if t not in record_types:
            diags.append(_err(
                "P502",
                f"journal record type {t!r} is emitted but not registered "
                f"in master_journal.RECORD_TYPES — replay would hard-error "
                f"as version skew",
                "master.py", line,
                f"add {t!r} to RECORD_TYPES and define Service._apply_{t}",
            ))
        if t not in apply_ops:
            diags.append(_err(
                "P502",
                f"journal record type {t!r} has no Service._apply_{t} "
                f"replay op — recovery would AttributeError on it",
                "master.py", line,
                f"define Service._apply_{t}(rec) (pure state, never "
                "journals)",
            ))
        if has_result and t not in compact_emits:
            diags.append(_err(
                "P502",
                f"record type {t!r} carries a \"result\" payload but is "
                f"not re-emitted by Service._compact — compaction would "
                f"silently drop the payloads (the snapshot stays pure "
                f"JSON and never carries them)",
                "master.py", line,
                f"re-emit retained {t!r} records into the new generation "
                "inside _compact",
            ))
    for t in sorted(record_types):
        if t not in emitted and t not in compact_emits:
            diags.append(_err(
                "P502",
                f"registered record type {t!r} is never emitted by any "
                f"_journal()/compaction site — dead protocol surface "
                f"(or the emission no longer uses a literal)",
                "master_journal.py", rt_line,
                f"drop {t!r} from RECORD_TYPES or restore its emission",
            ))
    for t, line in sorted(apply_ops.items()):
        if t not in record_types:
            diags.append(_err(
                "P502",
                f"Service._apply_{t} replays a record type {t!r} that is "
                f"not in RECORD_TYPES — unreachable replay op",
                "master.py", line,
                f"register {t!r} in RECORD_TYPES or delete the handler",
            ))
    return diags


# ---------------------------------------------------------------------------
# P503 — status-ledger exhaustiveness
# ---------------------------------------------------------------------------

def _status_literals(value: ast.AST) -> List[Tuple[str, int]]:
    """String constants reachable through IfExp/BoolOp arms of ``value``."""
    out: List[Tuple[str, int]] = []
    stack = [value]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append((node.value, node.lineno))
        elif isinstance(node, ast.IfExp):
            stack.extend((node.body, node.orelse))
        elif isinstance(node, ast.BoolOp):
            stack.extend(node.values)
    return out


def _is_status_target(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "status"
    return isinstance(node, ast.Name) and node.id == "status"


def _p503(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    sched = trees.get("serving/scheduler.py")
    if sched is None:
        return diags
    decl_node = _module_assign(sched, "TERMINAL_STATUSES")
    decl = _str_elts(decl_node) if decl_node is not None else None
    if decl is None:
        diags.append(_err(
            "P500",
            "TERMINAL_STATUSES is not a module-level literal tuple — the "
            "disjoint status ledger has no declared universe to check "
            "against",
            "serving/scheduler.py", getattr(decl_node, "lineno", None),
            "declare TERMINAL_STATUSES = (\"served\", ...) once in "
            "serving/scheduler.py; every other surface must reference it",
        ))
        return diags
    declared = {v for v, _ in decl}
    allowed = declared | _TRANSIENT_STATUSES
    assigned: Set[str] = set()
    for rel in ("serving/scheduler.py", "serving/router.py"):
        tree = trees.get(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            lits: List[Tuple[str, int]] = []
            is_assign = False
            if isinstance(node, ast.Assign):
                if any(_is_status_target(t) for t in node.targets):
                    lits = _status_literals(node.value)
                    is_assign = True
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "status":
                        lits.extend(_status_literals(kw.value))
                        is_assign = True
                fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                         else getattr(node.func, "id", None))
                if fname == "_finalize" and len(node.args) >= 2:
                    lits.extend(_status_literals(node.args[1]))
                    is_assign = True
            elif isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if (any(_is_status_target(s) for s in sides)
                        and all(isinstance(op, (ast.Eq, ast.NotEq))
                                for op in node.ops)):
                    for s in sides:
                        lits.extend(_status_literals(s))
            for value, line in lits:
                if is_assign:
                    assigned.add(value)
                if value not in allowed:
                    diags.append(_err(
                        "P503",
                        f"status literal {value!r} is not in the declared "
                        f"disjoint set TERMINAL_STATUSES (nor the "
                        f"transient {sorted(_TRANSIENT_STATUSES)}) — "
                        f"summaries/ledgers keyed on the declared set "
                        f"would drop it",
                        rel, line,
                        "add it to serving/scheduler.py TERMINAL_STATUSES "
                        "(ONE source of truth) or use a declared status",
                    ))
        # a parallel status-set literal that drifted from the declaration
        for node in ast.walk(tree):
            if node is decl_node or not isinstance(node, (ast.Tuple, ast.Set,
                                                          ast.List)):
                continue
            elts = _str_elts(node)
            if elts is None:
                continue
            vals = {v for v, _ in elts}
            if len(vals & declared) >= 2 and vals != declared:
                diags.append(_err(
                    "P503",
                    f"status-set literal {sorted(vals)} diverges from the "
                    f"declared TERMINAL_STATUSES {sorted(declared)} — a "
                    f"status added in one place is invisible to the other",
                    rel, node.lineno if hasattr(node, "lineno") else None,
                    "reference scheduler.TERMINAL_STATUSES instead of "
                    "copying the literal",
                ))
    for v, line in decl:
        if v not in assigned:
            diags.append(_err(
                "P503",
                f"declared terminal status {v!r} is never assigned at any "
                f"transition site in the serving planes — dead ledger "
                f"category",
                "serving/scheduler.py", line,
                f"drop {v!r} from TERMINAL_STATUSES or restore the "
                "transition that lands on it",
            ))
    return diags


# ---------------------------------------------------------------------------
# P504 — lease/fence monotonicity hazards
# ---------------------------------------------------------------------------

def _field_kind(node: ast.AST) -> Optional[str]:
    """\"epoch\"/\"seq\" when the expression is an epoch/sequence field."""
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    if name is None:
        return None
    if name == "epoch":
        return "epoch"
    if name in ("seq", "_seq", "last_seq", "base_seq"):
        return "seq"
    return None


def _p504_compare(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for rel in ("master.py", "master_ha.py", "master_journal.py"):
        tree = trees.get(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            kinds = (_field_kind(node.left),
                     _field_kind(node.comparators[0]))
            op = node.ops[0]
            if kinds == ("epoch", "epoch") and isinstance(
                    op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE)):
                diags.append(_err(
                    "P504",
                    "epoch fence compared with an ORDERING operator — the "
                    "epoch guard is an equality fence (rotation resets "
                    "epochs to 0, so ordering accepts a stale holder's "
                    "ack as current)",
                    rel, node.lineno,
                    "compare epochs with ==/!= (the service.go task-epoch "
                    "discipline)",
                ))
            if kinds == ("seq", "seq") and isinstance(
                    op, (ast.Eq, ast.NotEq)):
                diags.append(_err(
                    "P504",
                    "journal sequence compared with EQUALITY — the replay "
                    "dedupe guard is monotonic (a reordered/duplicated "
                    "record must compare by ordering, or replay either "
                    "re-applies or drops records)",
                    rel, node.lineno,
                    "compare sequences with <=/< against the high-water "
                    "mark",
                ))
    return diags


def _clock_plus_timeout(value: ast.AST) -> bool:
    """``<clock call> + <timeout-ish name>`` anywhere inside ``value``."""
    for node in ast.walk(value):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            sides = (node.left, node.right)
            has_clock = any(
                isinstance(s, ast.Call)
                and (_name_of(s.func) or "").rsplit(".", 1)[-1]
                in ("_clock", "clock", "monotonic", "time", "perf_counter")
                for s in sides
            )
            has_timeout = any(
                "timeout" in ((_name_of(s) or "").rsplit(".", 1)[-1])
                for s in sides
            )
            if has_clock and has_timeout:
                return True
    return False


def _deadline_write(stmt: ast.AST) -> Optional[int]:
    """Line of a lease-deadline write in ``stmt`` (Assign/AugAssign only)."""
    if not isinstance(stmt, (ast.Assign, ast.AugAssign)):
        return None
    targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
    shared = [t for t in targets
              if isinstance(t, (ast.Attribute, ast.Subscript))]
    if not shared:
        return None
    named = any("deadline" in (getattr(t, "attr", "") or "").lower()
                for t in shared if isinstance(t, ast.Attribute))
    if named or _clock_plus_timeout(stmt.value):
        return stmt.lineno
    return None


def _lock_names(cls: ast.ClassDef) -> Set[str]:
    """Attribute names assigned a lock in ``__init__`` (make_lock/RLock)."""
    init = _class_methods(cls).get("__init__")
    out: Set[str] = set()
    if init is None:
        return out
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = (_name_of(node.value.func) or "").rsplit(".", 1)[-1]
            if ctor in ("make_lock", "make_rlock", "Lock", "RLock"):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
    return out


def _under_lock(path: Sequence[ast.AST], locks: Set[str]) -> bool:
    for node in path:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = _name_of(item.context_expr)
                if name and name.rsplit(".", 1)[-1] in locks:
                    return True
    return False


def _self_call_sites(cls: ast.ClassDef) -> Dict[str, List[Tuple[str, bool]]]:
    """callee -> [(caller, call-site-under-lock)] over ``self.x(...)``
    calls, with the journal plane's ``getattr(self, f"_apply_{t}")(...)``
    dynamic dispatch expanded onto every ``_apply_*`` method."""
    locks = _lock_names(cls)
    methods = _class_methods(cls)
    sites: Dict[str, List[Tuple[str, bool]]] = {}

    def _walk(node: ast.AST, caller: str, path: List[ast.AST]) -> None:
        held = _under_lock(path, locks)
        if isinstance(node, ast.Call):
            callee = None
            if (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                callee = node.func.attr
            elif (isinstance(node.func, ast.Call)
                  and isinstance(node.func.func, ast.Name)
                  and node.func.func.id == "getattr"
                  and node.func.args
                  and isinstance(node.func.args[0], ast.Name)
                  and node.func.args[0].id == "self"):
                # getattr(self, <expr mentioning "_apply_">)(...) — the
                # replay dispatch: a call site for every _apply_* method
                dumped = ast.dump(node.func)
                if "_apply_" in dumped:
                    for m in methods:
                        if m.startswith("_apply_"):
                            sites.setdefault(m, []).append((caller, held))
            if callee in methods:
                sites.setdefault(callee, []).append((caller, held))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            _walk(child, caller, path + [node])

    for name, fn in methods.items():
        for stmt in fn.body:
            _walk(stmt, name, [])
    return sites


def _entry_held(cls: ast.ClassDef) -> Set[str]:
    """Methods whose EVERY reachable call site holds the class lock (a
    fixpoint over self-calls — the miniature of PR 9's entry-held
    inference, enough for the lease-deadline fields)."""
    sites = _self_call_sites(cls)
    methods = set(_class_methods(cls))
    held = {m for m in methods if m in sites}  # optimistic start
    changed = True
    while changed:
        changed = False
        for m in sorted(held):
            ok = all(under or (caller in held and caller != m)
                     for caller, under in sites.get(m, ()))
            if not ok:
                held.discard(m)
                changed = True
    return held


def _p504_lease_locks(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for rel in ("master.py", "master_ha.py", "serving/router.py"):
        tree = trees.get(rel)
        if tree is None:
            continue
        for cls in (s for s in tree.body if isinstance(s, ast.ClassDef)):
            locks = _lock_names(cls)
            if not locks:
                continue
            entry_held = _entry_held(cls)
            for name, fn in _class_methods(cls).items():
                if name == "__init__" or name in entry_held:
                    continue

                def _scan(node: ast.AST, path: List[ast.AST]) -> None:
                    line = _deadline_write(node)
                    if line is not None and not _under_lock(path, locks):
                        diags.append(_err(
                            "P504",
                            f"lease deadline written in "
                            f"{cls.name}.{name} without holding the "
                            f"registry lock ({'/'.join(sorted(locks))}) — "
                            f"a concurrent prune/renew can tear the lease "
                            f"table",
                            rel, line,
                            "move the write under `with self._lock:` (or "
                            "make every call site hold it)",
                        ))
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda, ast.ClassDef)):
                            continue
                        _scan(child, path + [node])

                for stmt in fn.body:
                    _scan(stmt, [])
    return diags


# ---------------------------------------------------------------------------
# P505 — timeout completeness
# ---------------------------------------------------------------------------

def _p505(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for rel in ("master.py", "master_ha.py", "serving/router.py"):
        tree = trees.get(rel)
        if tree is None:
            continue
        for cls in (s for s in tree.body if isinstance(s, ast.ClassDef)):
            fn = _class_methods(cls).get("_call")
            if fn is None:
                continue
            names = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
            attrs = {n.attr for n in ast.walk(fn)
                     if isinstance(n, ast.Attribute)}
            bounded = any("timeout" in s or "deadline" in s
                          for s in names | attrs)
            raises = any(isinstance(n, ast.Raise) for n in ast.walk(fn))
            if not (bounded and raises):
                diags.append(_err(
                    "P505",
                    f"RPC client {cls.name}._call has no deadline path — "
                    f"a dead or frozen peer would hang the caller forever "
                    f"instead of raising MasterTimeoutError",
                    rel, fn.lineno,
                    "bound the call with call_timeout_s/deadline and "
                    "raise MasterTimeoutError (or re-raise) when it "
                    "elapses",
                ))
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "poll"):
                unbounded = (not node.args and not node.keywords) or any(
                    isinstance(a, ast.Constant) and a.value is None
                    for a in node.args)
                if unbounded:
                    diags.append(_err(
                        "P505",
                        "unbounded Connection.poll() on an RPC plane — "
                        "blocks forever with no route to "
                        "MasterTimeoutError",
                        rel, node.lineno,
                        "pass a finite timeout (poll(remaining)) derived "
                        "from the call deadline",
                    ))
            if (isinstance(node, ast.Call)
                    and (_name_of(node.func) or "").rsplit(".", 1)[-1]
                    in ("Client", "HAClient")):
                for kw in node.keywords:
                    if (kw.arg == "call_timeout_s"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is None):
                        diags.append(_err(
                            "P505",
                            "RPC client constructed with "
                            "call_timeout_s=None — every call site needs "
                            "a deadline path to MasterTimeoutError",
                            rel, node.lineno,
                            "pass a finite call_timeout_s (the default "
                            "is already bounded)",
                        ))
    return diags


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_sources(root: Optional[str] = None) -> Dict[str, str]:
    root = root or _package_root()
    out: Dict[str, str] = {}
    for rel in PROTOCOL_FILES:
        path = os.path.join(root, *rel.split("/"))
        with open(path, encoding="utf-8") as f:
            out[rel] = f.read()
    return out


def lint_protocol_sources(sources: Mapping[str, str]) -> List[Diagnostic]:
    """Run every P-rule over ``{relpath: source}`` (mutation tests pass a
    rewritten copy; :func:`lint_protocol_package` passes the real tree)."""
    diags: List[Diagnostic] = []
    trees: Dict[str, ast.Module] = {}
    prag: Dict[str, Dict[int, _pragmas.Pragma]] = {}
    for rel, src in sources.items():
        prag[rel] = _pragmas.collect(src, "proto", rel, diags)
        try:
            trees[rel] = ast.parse(src)
        except SyntaxError as exc:
            diags.append(_err(
                "P500", f"unparseable protocol surface: {exc.msg}", rel,
                exc.lineno, "fix the syntax error",
            ))
    findings: List[Diagnostic] = []
    findings.extend(_p501(trees))
    findings.extend(_p502(trees))
    findings.extend(_p503(trees))
    findings.extend(_p504_compare(trees))
    findings.extend(_p504_lease_locks(trees))
    findings.extend(_p505(trees))
    used: Dict[str, Set[int]] = {rel: set() for rel in sources}
    for d in findings:
        p = prag.get(d.source, {}).get(d.line or -1)
        if p is not None and p.suppresses(d.rule):
            used.setdefault(d.source, set()).add(d.line)
            continue
        diags.append(d)
    for rel in sources:
        diags.extend(_pragmas.stale_findings(
            prag.get(rel, {}), used.get(rel, ()), "proto", rel,
            severity=Severity.ERROR,
        ))
    return diags


def lint_protocol_package(root: Optional[str] = None) -> List[Diagnostic]:
    """Lint the installed package's protocol surfaces (``paddle-tpu lint
    --protocol`` / the ``make lint`` leg).  Zero findings is the gate."""
    return lint_protocol_sources(_load_sources(root))
