"""Shared diagnostic model for every static-analysis pass.

The reference front-loads ~hundreds of per-layer ``config_assert`` checks in
``config_parser.py`` (reference: python/paddle/trainer/config_parser.py:178
``config_assert(bool, msg)`` → ``logger.fatal`` with layer provenance) so a
bad ModelConfig dies at parse time instead of mid-training inside the gserver
interpreter.  This module is the TPU-native equivalent's common currency: one
:class:`Diagnostic` record (rule id, severity, layer/file provenance, fix
hint) shared by the graph linter (``analysis.graph_lint``), the jaxpr trace
linter (``analysis.trace_lint``) and the AST self-linter
(``analysis.ast_rules``), plus the formatter every error path routes through
so users always see *which layer* (or file) produced a finding.

Rule-id namespaces:  ``G###`` graph lint · ``T###`` trace hygiene ·
``A###`` AST self-lint · ``C###`` concurrency · ``N###`` numerics ·
``P###`` protocol conformance (``analysis.protocol_lint`` + the runtime
:class:`ProtocolError` raises in the serving/wire planes).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterable, List, Optional, Sequence


class Severity(enum.IntEnum):
    """INFO < WARNING < ERROR; ERROR means the graph cannot run correctly,
    WARNING a silent perf/correctness hazard, INFO a notable observation."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error" not "Severity.ERROR" in output
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding.  ``layer`` carries graph provenance (dotted path for a
    layer inside a recurrent_group sub-topology); ``source``/``line`` carry
    file provenance (the v1 config that created the layer, or the analyzed
    source file for AST rules); ``hint`` is the config_assert-style fix
    suggestion."""

    rule: str
    severity: Severity
    message: str
    layer: Optional[str] = None
    source: Optional[str] = None
    line: Optional[int] = None
    hint: Optional[str] = None

    def format(self) -> str:
        where = ""
        if self.source:
            where = f" --> {self.source}" + (f":{self.line}" if self.line else "")
        head = f"{self.severity}[{self.rule}]"
        if self.layer is not None:
            head += f" layer {self.layer!r}"
        out = f"{head}: {self.message}"
        if where:
            out += f"\n   {where}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out


def format_diagnostics(diags: Sequence[Diagnostic]) -> str:
    """Multi-finding report, errors first, with a one-line tally footer —
    the shape of the reference's config_parser failure dump."""
    if not diags:
        return "no diagnostics"
    ordered = sorted(diags, key=lambda d: (-int(d.severity), d.rule))
    lines = [d.format() for d in ordered]
    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    n_warn = sum(1 for d in diags if d.severity == Severity.WARNING)
    lines.append(f"{len(diags)} diagnostic(s): {n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)


class DiagnosticError(ValueError):
    """Raised where the reference would ``config_assert``-abort.  Subclasses
    ValueError so every pre-existing ``except ValueError`` / pytest.raises
    site keeps working; carries the structured diagnostics for programmatic
    consumers (the CLI, tests asserting rule ids)."""

    def __init__(self, diagnostics):
        if isinstance(diagnostics, Diagnostic):
            diagnostics = [diagnostics]
        self.diagnostics: List[Diagnostic] = list(diagnostics)
        super().__init__(format_diagnostics(self.diagnostics))

    @property
    def rules(self) -> List[str]:
        return [d.rule for d in self.diagnostics]


class ProtocolError(DiagnosticError, RuntimeError):
    """A distributed-protocol misuse (P-rule namespace): calling into a
    closed client, violating a lifecycle contract, breaking a lease/fence
    invariant at runtime.  Doubly inherits RuntimeError so the historical
    bare ``raise RuntimeError(...)`` sites in the serving/RPC planes can
    upgrade to structured diagnostics without breaking any existing
    ``except RuntimeError`` handler (and DiagnosticError keeps ``except
    ValueError`` consumers working too)."""


def protocol_error(
    rule: str,
    message: str,
    *,
    source: Optional[str] = None,
    hint: Optional[str] = None,
) -> ProtocolError:
    """Build a single-finding :class:`ProtocolError` (the raise-site
    shorthand the serving/wire planes use for lifecycle misuse)."""
    return ProtocolError(
        Diagnostic(
            rule=rule,
            severity=Severity.ERROR,
            message=message,
            source=source,
            hint=hint,
        )
    )


def config_assert(
    cond: bool,
    rule: str,
    message: str,
    *,
    layer: Optional[str] = None,
    source: Optional[str] = None,
    hint: Optional[str] = None,
) -> None:
    """The reference's ``config_assert`` (config_parser.py:178): raise a
    :class:`DiagnosticError` with full provenance when ``cond`` is false."""
    if not cond:
        raise DiagnosticError(
            Diagnostic(
                rule=rule,
                severity=Severity.ERROR,
                message=message,
                layer=layer,
                source=source,
                hint=hint,
            )
        )


def errors(diags: Iterable[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == Severity.ERROR]


def raise_if_errors(diags: Sequence[Diagnostic]) -> None:
    """Abort (DiagnosticError) when any ERROR-severity finding is present;
    warnings/info never raise."""
    errs = errors(diags)
    if errs:
        raise DiagnosticError(errs)
