"""Precision-flow lint — dtype/numerics dataflow over the compiled jaxprs.

The trace linter (``analysis.trace_lint``) finds structural TPU hazards
(f64 leaks, captured weights); this pass reasons about PRECISION: where a
low-precision value accumulates, escapes into master state, or walks into
an op whose domain it cannot survive.  It is the static gate that makes
aggressive low-precision work (ROADMAP item 2: quantized collectives,
bf16 master-weight training, int8 weight-only serving; EQuARX,
arXiv:2506.17615) cheap — a bad precision config is a lint finding, not a
burned convergence run.  Like ``trace_lint`` it sees the whole compiled
step as one static dataflow graph, recursing scan/cond/pjit sub-jaxprs.

Rules (``N###``):

  N401 low-precision-accumulation   dot/conv/reduce/scan-carry
                                    accumulating in bf16/f16 without an
                                    f32 accumulator
                                    (``preferred_element_type``)
  N402 master-precision-escape      a params/opt-state output leaf of the
                                    train step is produced below master
                                    precision, or its update math ran in
                                    a sub-f32 dtype outside the
                                    sanctioned forward-cast site
  N403 unguarded-domain-hazard      exp/log/rsqrt/div whose input is not
                                    range-guarded by the masked-softmax
                                    max-subtraction (ops/rnn.py
                                    ``_att_softmax`` is the positive
                                    pattern) or an epsilon idiom
  N404 sentinel-literal-overflow    a finite mask/fill literal (the
                                    ``-1e9`` idiom) cast to a dtype whose
                                    finite range it exceeds — under f16
                                    it lands as ±inf and poisons softmax
  N405 low-precision-psum           a cross-replica psum at sub-f32 dtype
                                    with no block-scale structure (no f32
                                    scale psum beside it) — the static
                                    gate a quantized allreduce must pass
  N406 dtype-roundtrip-churn        convert chains f32→bf16→f32: HBM
                                    bandwidth spent quantizing a value
                                    that is immediately promoted back

Allowlist pragma (shared grammar, analysis.pragmas), anchored on the
source line that ISSUES the primitive (``eqn.source_info``)::

    alpha = jnp.exp(score)  # num: allow[N403] scores are clipped upstream

``certify_precision_plan(topology, plan)`` statically verifies a proposed
compute-dtype/master-dtype split over the real ``make_train_step`` body
and renders a per-layer precision certificate — the documented gate for
ROADMAP item 2's quantized/low-precision configs.

Run via ``paddle-tpu lint --numerics [--config ... --compute-dtype ...]``
(``make lint``: package probes + the shipped demo corpus at f32 must be
zero-diagnostic; the bf16 flagship leg is triaged to zero via fixes or
justified pragmas).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from paddle_tpu.analysis import pragmas as _pragmas
from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = [
    "PrecisionCertificate",
    "certify_precision_plan",
    "lint_numerics_jaxpr",
    "lint_numerics_step",
    "lint_numerics_config",
    "lint_numerics_package",
]

# sub-f32 floating dtypes ("low precision" throughout)
_LOW_FLOATS = {"float16", "bfloat16", "float8_e4m3fn", "float8_e5m2"}
# reductions under this extent are numerically safe even in bf16 (the
# partial-sum count is too small to lose mantissa); dot contractions and
# long reduces above it need an f32 accumulator
ACCUM_EXTENT_THRESHOLD = 32

# call-like primitives we inline (operand substitution keeps constants
# and guard facts flowing through — jnp.where wraps its fill literal in a
# pjit, and the -1e9-under-f16 check (N404) must see through it)
_INLINE_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "remat", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_jvp_call_jaxpr",
    "custom_vjp_call_jaxpr", "custom_vjp_call_jaxpr_p",
})
# ops a guard/constant fact flows through unchanged
_TRANSPARENT = frozenset({
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "stop_gradient", "slice", "squeeze", "expand_dims", "copy",
    "reduce_precision", "sharding_constraint", "device_put",
})
# ops with intrinsically bounded outputs (exp of them cannot overflow)
_BOUNDED_PRIMS = frozenset({
    "logistic", "tanh", "erf", "sin", "cos", "sign", "clamp",
})
# ops with non-negative outputs (log/div/rsqrt of them + eps is safe)
_POSITIVE_PRIMS = frozenset({"exp", "abs", "square", "logistic"})

_LAYER_RE = re.compile(r"([A-Za-z_][\w.]*):([\w./@-]+)")


def _is_low(dtype) -> bool:
    return dtype is not None and str(dtype) in _LOW_FLOATS


def _is_float(dtype) -> bool:
    # jnp.issubdtype, not np: the ml_dtypes floats (bfloat16, f8) are not
    # numpy.floating subtypes and np would call every bf16 "not float"
    import jax.numpy as jnp

    try:
        return dtype is not None and jnp.issubdtype(
            np.dtype(dtype), jnp.floating
        )
    except TypeError:
        return False


def _finfo(dtype):
    import jax.numpy as jnp

    return jnp.finfo(np.dtype(dtype))  # ml_dtypes-aware (np.finfo is not)


def _aval_dtype(x):
    aval = getattr(x, "aval", None)
    return getattr(aval, "dtype", None)


# ---------------------------------------------------------------------------
# abstract values + region walk
# ---------------------------------------------------------------------------


class _Val:
    """One dataflow value: producing primitive, input links, optionally a
    statically-known scalar constant."""

    __slots__ = ("kind", "prim", "eqn", "ins", "const", "dtype", "tag")

    def __init__(self, kind, dtype, prim="", eqn=None, ins=(), const=None,
                 tag=""):
        self.kind = kind          # "input" | "const" | "op" | "opaque"
        self.dtype = dtype
        self.prim = prim
        self.eqn = eqn
        self.ins = tuple(ins)
        self.const = const        # known scalar float, else None
        self.tag = tag            # input label (arg path) when known


@dataclasses.dataclass
class _Visit:
    """One analyzed eqn occurrence with resolved operand values."""

    eqn: Any
    invals: Tuple[_Val, ...]
    outvals: Tuple[_Val, ...]
    region: str    # "" top level; "scan", "scan/cond", ... for bodies


def _scalar_const(v) -> Optional[float]:
    try:
        arr = np.asarray(v)
        if arr.size != 1:
            return None
        # via float64, not .kind: ml_dtypes scalars (bfloat16/f8) carry
        # numpy kind 'V' and would lose their const-ness otherwise
        return float(np.asarray(arr, dtype=np.float64).reshape(()))
    except Exception:  # noqa: BLE001 — exotic consts just lose const-ness
        return None


def _sub_jaxprs(params: Dict[str, Any]):
    """Every ClosedJaxpr reachable from an eqn's params."""
    from jax.core import Jaxpr

    def walk(v):
        if hasattr(v, "jaxpr") or isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                yield from walk(x)

    for v in params.values():
        yield from walk(v)


class _Walker:
    """Flatten a closed jaxpr into `_Visit`s, inlining call-like eqns with
    operand substitution and descending into scan/while/cond bodies with
    opaque boundary values."""

    def __init__(self) -> None:
        self.visits: List[_Visit] = []
        self.scan_carries: List[Tuple[Any, int, _Val, str]] = []
        # (scan eqn, carry index, carry-out val inside body, region)

    # -- entry ----------------------------------------------------------
    def walk_closed(self, closed, in_vals: Optional[Sequence[_Val]] = None,
                    region: str = "") -> List[_Val]:
        jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        consts = list(getattr(closed, "consts", ()) or ())
        env: Dict[Any, _Val] = {}
        for var, cval in zip(jaxpr.constvars, consts):
            env[var] = _Val("const", _aval_dtype(var) or getattr(cval, "dtype", None),
                            const=_scalar_const(cval))
        if in_vals is None:
            in_vals = [
                _Val("input", _aval_dtype(v), tag=f"arg{i}")
                for i, v in enumerate(jaxpr.invars)
            ]
        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = val
        self._eqns(jaxpr, env, region)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _read(self, env, var) -> _Val:
        from jax.core import Literal

        if isinstance(var, Literal):
            return _Val("const", _aval_dtype(var), const=_scalar_const(var.val))
        got = env.get(var)
        if got is None:
            got = _Val("opaque", _aval_dtype(var))
        return got

    def _eqns(self, jaxpr, env, region) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            invals = tuple(self._read(env, v) for v in eqn.invars)
            outvals = self._eqn(eqn, prim, invals, region)
            for var, val in zip(eqn.outvars, outvals):
                env[var] = val

    def _eqn(self, eqn, prim, invals, region) -> Tuple[_Val, ...]:
        if prim in _INLINE_PRIMS:
            subs = [s for s in _sub_jaxprs(eqn.params)]
            for sub in subs:
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                if len(inner.invars) == len(invals) and len(
                    inner.outvars
                ) == len(eqn.outvars):
                    return tuple(self.walk_closed(sub, invals, region))
            # arity mismatch (hidden consts): analyze bodies opaquely so
            # in-body hazards still fire, outputs stay opaque
            for sub in subs:
                self.walk_closed(sub, None, region or prim)
            return tuple(_Val("opaque", _aval_dtype(v)) for v in eqn.outvars)

        if prim == "scan":
            self._scan(eqn, invals, region)
        elif prim == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    self.walk_closed(sub, None, _join(region, "while"))
        elif prim == "shard_map":
            # descend into the per-shard program: the quantized-allreduce
            # psums (trainer/step.py's quantized path) live here, and N405
            # must see the payload psum AND its f32 scale psum in the SAME
            # region to accept the pair
            sub = eqn.params.get("jaxpr")
            if sub is not None:
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                ops = invals if len(inner.invars) == len(invals) else None
                self.walk_closed(sub, ops, _join(region, "shard_map"))
        elif prim == "cond":
            for sub in eqn.params.get("branches", ()):
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                ops = invals[1:]
                if len(inner.invars) == len(ops):
                    self.walk_closed(sub, ops, _join(region, "cond"))
                else:
                    self.walk_closed(sub, None, _join(region, "cond"))

        out = tuple(
            _Val("op", _aval_dtype(v), prim=prim, eqn=eqn, ins=invals,
                 const=self._const_out(prim, eqn, invals, v))
            for v in eqn.outvars
        )
        self.visits.append(_Visit(eqn=eqn, invals=invals, outvals=out,
                                  region=region))
        return out

    def _const_out(self, prim, eqn, invals, outvar) -> Optional[float]:
        """Propagate known scalar constants through shape-transparent ops
        and converts — the -1e9 literal must still be known when the
        convert to f16 happens inside the inlined `_where` pjit."""
        if prim in _TRANSPARENT and invals and invals[0].const is not None:
            return invals[0].const
        if prim == "neg" and invals and invals[0].const is not None:
            return -invals[0].const
        return None

    def _scan(self, eqn, invals, region) -> None:
        params = eqn.params
        sub = params.get("jaxpr")
        if sub is None:
            return
        inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        n_consts = int(params.get("num_consts", 0))
        n_carry = int(params.get("num_carry", 0))
        in_vals: List[_Val] = []
        for i, var in enumerate(inner.invars):
            if i < n_consts and i < len(invals):
                in_vals.append(invals[i])
            else:
                in_vals.append(_Val("opaque", _aval_dtype(var)))
        outs = self.walk_closed(sub, in_vals, _join(region, "scan"))
        carry_ins = in_vals[n_consts:n_consts + n_carry]
        carry_outs = outs[:n_carry]
        for i, (cin, cout) in enumerate(zip(carry_ins, carry_outs)):
            if _is_low(cout.dtype) and _accumulates(cout, cin):
                self.scan_carries.append((eqn, i, cout, region))


def _join(region: str, part: str) -> str:
    return f"{region}/{part}" if region else part


def _accumulates(out: _Val, carry_in: _Val, depth: int = 0) -> bool:
    """True when a scan carry output is an add-chain over its own carry
    input — a running accumulator (the numerically lossy pattern in low
    precision), as opposed to a recurrent state that is overwritten."""
    if depth > 6:
        return False
    if out is carry_in:
        return False
    if out.kind != "op":
        return False
    if out.prim in ("add", "add_any"):
        for op in out.ins:
            if op is carry_in:
                return True
            if op.kind == "op" and op.prim in _TRANSPARENT and op.ins and (
                op.ins[0] is carry_in
            ):
                return True
        return any(_accumulates(op, carry_in, depth + 1) for op in out.ins)
    if out.prim in _TRANSPARENT and out.ins:
        return _accumulates(out.ins[0], carry_in, depth + 1)
    return False


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------


def _eqn_site(eqn) -> Tuple[Optional[str], Optional[int]]:
    """(file, line) of the user code that issued this primitive — the
    anchor the ``# num:`` allowlist pragma attaches to."""
    try:
        from jax._src import source_info_util as siu

        frame = siu.user_frame(eqn.source_info)
        if frame is None:
            return None, None
        return frame.file_name, int(frame.start_line)
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return None, None


def _eqn_layer(eqn) -> Optional[str]:
    """Layer provenance from the jax.named_scope stack the apply loop
    pushes per layer (``type:name`` — the T100 note plane's vocabulary);
    survives jvp()/transpose() decoration on backward-pass eqns."""
    try:
        ns = str(eqn.source_info.name_stack)
    except Exception:  # noqa: BLE001
        return None
    m = _LAYER_RE.search(ns)
    if m:
        return m.group(2)
    return None


def _relpath(path: Optional[str]) -> Optional[str]:
    if not path:
        return path
    marker = "paddle_tpu" + os.sep
    idx = path.rfind(marker)
    if idx >= 0:
        return path[idx:]
    return path


# ---------------------------------------------------------------------------
# guard analysis (N403)
# ---------------------------------------------------------------------------


def _bounded_above(val: _Val, depth: int = 0) -> bool:
    """Conservatively true when ``val`` cannot grow without bound upward —
    exp of it cannot overflow.  The masked-softmax idiom (subtract the
    stop-gradiented running max; ops/rnn.py:_att_softmax) is the canonical
    positive pattern."""
    if depth > 12:
        return False
    if val.const is not None:
        return bool(np.isfinite(val.const))
    if val.kind != "op":
        return False
    p = val.prim
    if p in _BOUNDED_PRIMS:
        return True
    if p in _TRANSPARENT or p in ("reduce_max", "reduce_min", "max", "min"):
        # min/max against a bounded operand bounds the result
        if p in ("max", "min"):
            return any(_bounded_above(x, depth + 1) for x in val.ins)
        return bool(val.ins) and _bounded_above(val.ins[0], depth + 1)
    if p == "sub":
        # x - max(x): the softmax max-subtraction — subtracting a value
        # derived from a running max of the SAME tensor bounds above at 0.
        # Statically we accept: subtrahend chain contains a reduce_max.
        return len(val.ins) == 2 and _contains_prim(
            val.ins[1], "reduce_max", depth + 1
        )
    if p == "neg":
        return bool(val.ins) and _non_negative(val.ins[0], depth + 1)
    if p in ("mul",):
        # scaling by a finite constant preserves boundedness
        return any(x.const is not None and np.isfinite(x.const)
                   for x in val.ins) and any(
            _bounded_above(x, depth + 1) for x in val.ins
        )
    if p == "add":
        return all(_bounded_above(x, depth + 1) for x in val.ins)
    return False


def _contains_prim(val: _Val, prim: str, depth: int = 0) -> bool:
    if depth > 12 or val.kind != "op":
        return False
    if val.prim == prim:
        return True
    if val.prim in _TRANSPARENT or val.prim in ("max", "min", "mul", "add",
                                                "sub", "select_n"):
        return any(_contains_prim(x, prim, depth + 1) for x in val.ins)
    return False


def _non_negative(val: _Val, depth: int = 0) -> bool:
    if depth > 12:
        return False
    if val.const is not None:
        return val.const >= 0.0
    if val.kind != "op":
        return False
    p = val.prim
    if p in _POSITIVE_PRIMS:
        return True
    if p in _TRANSPARENT:
        return bool(val.ins) and _non_negative(val.ins[0], depth + 1)
    if p in ("reduce_sum", "reduce_max", "reduce_min", "cumsum"):
        return bool(val.ins) and _non_negative(val.ins[0], depth + 1)
    if p in ("add", "mul", "max", "min", "div"):
        if p == "max":
            return any(_non_negative(x, depth + 1) for x in val.ins)
        return all(_non_negative(x, depth + 1) for x in val.ins)
    if p == "integer_pow" and int(val.eqn.params.get("y", 0)) % 2 == 0:
        return True
    if p == "sqrt":
        return True
    return False


def _is_tie_count(val: _Val, depth: int = 0) -> bool:
    """``convert(eq(x, broadcast(reduce_max(x))))`` — the membership mask
    the max/min gradient divides its tie count by; at least one element
    equals its own running max, so the summed count is >= 1."""
    if depth > 12 or val.kind != "op":
        return False
    if val.prim in _TRANSPARENT:
        return bool(val.ins) and _is_tie_count(val.ins[0], depth + 1)
    if val.prim in ("eq", "ge", "le"):
        return any(
            _contains_prim(x, "reduce_max", depth + 1)
            or _contains_prim(x, "reduce_min", depth + 1)
            for x in val.ins
        )
    return False


def _nonzero_rescale_of(val: _Val, t: _Val, depth: int = 0) -> bool:
    """True when ``val`` is ``t`` itself scaled only by finite nonzero
    constants (through shape-transparent ops) — nonzero whenever ``t``
    is, which the zero-switch ``where(t == 0, c, val)`` guarantees on the
    branch that selects it."""
    if depth > 8:
        return False
    if val is t:
        return True
    if val.kind != "op":
        return False
    if val.prim in _TRANSPARENT:
        return bool(val.ins) and _nonzero_rescale_of(val.ins[0], t, depth + 1)
    if val.prim in ("mul", "div"):
        hit = False
        for x in val.ins:
            if _nonzero_rescale_of(x, t, depth + 1):
                hit = True
            elif not (
                x.const is not None and np.isfinite(x.const) and x.const != 0.0
            ):
                return False
        return hit
    return False


def _positive_guarded(val: _Val, depth: int = 0) -> bool:
    """True when ``val`` is bounded away from zero from below — an
    epsilon idiom (`x + 1e-6`, `max(x, eps)`), a nonzero constant, or a
    softmax denominator (sum of exp where the max-subtraction pins one
    term at exp(0)=1)."""
    if depth > 12:
        return False
    if val.const is not None:
        return np.isfinite(val.const) and val.const != 0.0
    if val.kind != "op":
        return False
    p = val.prim
    if p in _TRANSPARENT:
        return bool(val.ins) and _positive_guarded(val.ins[0], depth + 1)
    if p == "add":
        # x + eps with eps a positive constant (the documented epsilon
        # idiom — accepted without proving x >= 0, like Adam's
        # sqrt(v)+eps), or a sum of guarded terms
        if any(x.const is not None and x.const > 0.0 for x in val.ins):
            return True
        return all(_positive_guarded(x, depth + 1) for x in val.ins)
    if p == "max":
        return any(
            (x.const is not None and x.const > 0.0)
            or _positive_guarded(x, depth + 1)
            for x in val.ins
        )
    if p == "exp":
        # exp(x - max(x)): at least one term is exp(0) = 1 — and any exp
        # whose argument is max-subtracted cannot be all-zero
        return bool(val.ins) and _contains_prim(val.ins[0], "reduce_max",
                                                depth + 1)
    if p == "select_n":
        # every selectable branch guarded (jax.nn.softmax's backward
        # divides by select(all_masked, 1, 2) — both branches constants)
        if len(val.ins) > 1 and all(
            _positive_guarded(x, depth + 1) for x in val.ins[1:]
        ):
            return True
        # the zero-switch idiom `where(t == 0, c, t*s)` (ops.quantize's
        # block-scale guard): the branch reached when t != 0 is a pure
        # nonzero rescaling of t, so the select output never lands at zero
        pred = val.ins[0] if val.ins else None
        if (
            len(val.ins) == 3 and pred is not None and pred.kind == "op"
            and pred.prim == "eq" and pred.ins
        ):
            t = next((x for x in pred.ins if x.const is None), None)
            against_zero = any(
                x.const == 0.0 for x in pred.ins if x.const is not None
            )
            if (
                t is not None and against_zero
                and _positive_guarded(val.ins[2], depth + 1)
                and _nonzero_rescale_of(val.ins[1], t)
            ):
                return True
        return False
    if p in ("reduce_sum", "cumsum"):
        if bool(val.ins) and _is_tie_count(val.ins[0], depth + 1):
            # sum of eq(x, max(x)) — the max-gradient tie count: the max
            # itself always matches, so the count is >= 1
            return True
        return bool(val.ins) and _positive_guarded(val.ins[0], depth + 1)
    if p in ("mul", "div"):
        return all(_positive_guarded(x, depth + 1) for x in val.ins)
    if p == "sqrt" or p == "rsqrt":
        return bool(val.ins) and _positive_guarded(val.ins[0], depth + 1)
    return False


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _dot_contraction_extent(eqn) -> int:
    try:
        (lhs_c, _rhs_c), _ = eqn.params["dimension_numbers"]
        shape = eqn.invars[0].aval.shape
        ext = 1
        for d in lhs_c:
            ext *= int(shape[d])
        return ext
    except Exception:  # noqa: BLE001
        return ACCUM_EXTENT_THRESHOLD


def _reduce_extent(eqn) -> int:
    try:
        axes = eqn.params.get("axes")
        if axes is None:  # cumsum spells its one axis `axis`
            axes = (eqn.params["axis"],)
        shape = eqn.invars[0].aval.shape
        ext = 1
        for d in axes:
            ext *= int(shape[d])
        return ext
    except Exception:  # noqa: BLE001
        return ACCUM_EXTENT_THRESHOLD


def _diag(rule, severity, message, eqn, hint=None) -> Diagnostic:
    path, line = _eqn_site(eqn)
    return Diagnostic(
        rule=rule, severity=severity, message=message,
        layer=_eqn_layer(eqn), source=_relpath(path), line=line, hint=hint,
    )


def _rule_n401(visits, scan_carries, diags) -> None:
    for v in visits:
        prim = v.eqn.primitive.name
        if prim in ("dot_general", "conv_general_dilated"):
            opdt = [x.dtype for x in v.invals[:2]]
            if not all(_is_low(d) for d in opdt):
                continue
            pet = v.eqn.params.get("preferred_element_type")
            if pet is not None and not _is_low(np.dtype(pet)):
                continue
            if prim == "dot_general" and _dot_contraction_extent(
                v.eqn
            ) < ACCUM_EXTENT_THRESHOLD:
                continue
            diags.append(_diag(
                "N401", Severity.ERROR,
                f"{prim} accumulates in {opdt[0]} (contraction extent "
                f"{_dot_contraction_extent(v.eqn) if prim == 'dot_general' else '?'})"
                " — partial sums truncate every step",
                v.eqn,
                hint="pass preferred_element_type=jnp.float32 (accumulate "
                "in f32, cast the result) — the MXU gives f32 "
                "accumulation for free",
            ))
        elif prim in ("reduce_sum", "cumsum"):
            x = v.invals[0] if v.invals else None
            if x is None or not _is_low(x.dtype):
                continue
            if not _is_low(v.outvals[0].dtype):
                continue  # already accumulating upward
            if _reduce_extent(v.eqn) < ACCUM_EXTENT_THRESHOLD:
                continue
            diags.append(_diag(
                "N401", Severity.ERROR,
                f"{prim} over {_reduce_extent(v.eqn)} elements in "
                f"{x.dtype} — a long low-precision reduction loses "
                "mantissa with every partial",
                v.eqn,
                hint="reduce in f32: x.astype(jnp.float32).sum(...) and "
                "cast back (jax.nn.softmax's own sum does exactly this)",
            ))
    for eqn, idx, cout, _region in scan_carries:
        diags.append(_diag(
            "N401", Severity.ERROR,
            f"scan carry {idx} accumulates (add-chain over its own "
            f"previous value) in {cout.dtype} — the running sum "
            "quantizes every step",
            eqn,
            hint="carry the accumulator in f32 (cast at the scan "
            "boundary); recurrent STATE that is overwritten each step "
            "may stay low-precision",
        ))


def _rule_n402(out_vals, out_labels, master_dtype, diags) -> None:
    master = np.dtype(master_dtype)
    for val, label in zip(out_vals, out_labels):
        if not _is_float(val.dtype):
            continue
        if np.dtype(val.dtype) != master:
            eqn = val.eqn if val.kind == "op" else None
            d = Diagnostic(
                rule="N402", severity=Severity.ERROR,
                message=f"master-state leaf {label} leaves the train step "
                f"at {val.dtype}, not master {master} — repeated updates "
                "at low precision stall convergence (the update quantizes "
                "before it lands)",
                hint="keep params/opt-state at the master dtype; cast to "
                "the compute dtype only on the forward read (the "
                "layer-boundary cast site, core/compiler.py "
                "resolve_layer_call)",
            )
            if eqn is not None:
                path, line = _eqn_site(eqn)
                d = dataclasses.replace(
                    d, layer=_eqn_layer(eqn), source=_relpath(path), line=line
                )
            diags.append(d)
            continue
        low_src = _lowprec_update_source(val)
        if low_src is not None:
            diags.append(_diag(
                "N402", Severity.ERROR,
                f"master-state leaf {label} is produced by upcasting a "
                f"{low_src.dtype} value — the update math itself ran "
                "below master precision (outside the sanctioned "
                "forward-cast site)",
                low_src.eqn if low_src.eqn is not None else val.eqn,
                hint="compute the optimizer update on the f32 master "
                "values; only the forward pass reads the compute-dtype "
                "cast",
            ))


def _lowprec_update_source(val: _Val, depth: int = 0) -> Optional[_Val]:
    """The sub-f32 value a master-state output was upcast from, if its
    producing chain ends in convert(low→master).  Walks through the
    sentinel's per-leaf select (healthy ? new : old) and tuple-ish
    transparents only — anything else is the legitimate f32 math path."""
    if depth > 6 or val.kind != "op":
        return None
    if val.prim == "convert_element_type":
        src = val.ins[0] if val.ins else None
        if src is not None and _is_low(src.dtype) and src.kind == "op":
            return src
        return None
    if val.prim == "select_n":
        for cand in val.ins[1:]:
            hit = _lowprec_update_source(cand, depth + 1)
            if hit is not None:
                return hit
    return None


def _rule_n403(visits, diags) -> None:
    for v in visits:
        prim = v.eqn.primitive.name
        if prim == "exp":
            x = v.invals[0]
            if not _is_float(x.dtype):
                continue
            if _bounded_above(x):
                continue
            diags.append(_diag(
                "N403", Severity.WARNING,
                f"exp of an unguarded {x.dtype} value — overflows to inf "
                "once the argument drifts past the dtype's exp ceiling "
                "(~88 at f32/bf16, ~11 at f16)",
                v.eqn,
                hint="subtract the running max first (the masked-softmax "
                "idiom, ops/rnn.py:_att_softmax) or clamp the argument",
            ))
        elif prim in ("log", "log1p"):
            if prim == "log1p":
                continue  # log1p(0) = 0: safe by construction
            x = v.invals[0]
            if not _is_float(x.dtype):
                continue
            if _positive_guarded(x):
                continue
            diags.append(_diag(
                "N403", Severity.WARNING,
                f"log of an unguarded {x.dtype} value — -inf at zero, "
                "nan below it",
                v.eqn,
                hint="add an epsilon (jnp.log(x + 1e-6)) or route through "
                "the fused log-softmax path (cost layers already do)",
            ))
        elif prim == "rsqrt":
            x = v.invals[0]
            if not _is_float(x.dtype):
                continue
            if _positive_guarded(x):
                continue
            diags.append(_diag(
                "N403", Severity.WARNING,
                f"rsqrt of an unguarded {x.dtype} value — inf at zero",
                v.eqn,
                hint="rsqrt(x + eps), the Adam/LayerNorm epsilon idiom",
            ))
        elif prim == "div":
            if len(v.invals) < 2:
                continue
            den = v.invals[1]
            if not _is_float(den.dtype):
                continue
            if _positive_guarded(den):
                continue
            diags.append(_diag(
                "N403", Severity.WARNING,
                f"division by an unguarded {den.dtype} value — inf/nan "
                "the moment the denominator underflows to zero",
                v.eqn,
                hint="guard the denominator: jnp.maximum(d, eps) or "
                "d + eps (ops/rnn.py:_att_softmax's masked mean is the "
                "positive pattern)",
            ))


def _rule_n404(visits, diags) -> None:
    for v in visits:
        if v.eqn.primitive.name != "convert_element_type":
            continue
        x = v.invals[0] if v.invals else None
        out = v.outvals[0]
        if x is None or x.const is None or not np.isfinite(x.const):
            continue
        if not _is_low(out.dtype):
            continue
        try:
            fmax = float(_finfo(out.dtype).max)
        except ValueError:
            continue
        if abs(x.const) > fmax:
            diags.append(_diag(
                "N404", Severity.ERROR,
                f"sentinel literal {x.const:g} overflows {out.dtype} "
                f"(finite max {fmax:g}) — the mask fill lands as ±inf and "
                "a fully-masked row softmaxes to nan",
                v.eqn,
                hint="derive the fill from the tensor dtype: "
                "jnp.asarray(jnp.finfo(x.dtype).min, x.dtype) or use the "
                "dtype-aware mask helper",
            ))


def _rule_n405(visits, diags) -> None:
    by_region: Dict[str, List[_Visit]] = {}
    for v in visits:
        if v.eqn.primitive.name == "psum":
            by_region.setdefault(v.region, []).append(v)
    for _region, group in by_region.items():
        has_f32 = any(
            any(str(x.dtype) == "float32" for x in v.invals) for v in group
        )
        for v in group:
            for x in v.invals:
                if not (_is_low(x.dtype) or str(x.dtype) == "int8"):
                    continue
                if has_f32:
                    continue  # block-scale structure: scales ride at f32
                diags.append(_diag(
                    "N405", Severity.ERROR,
                    f"cross-replica psum at {x.dtype} with no f32 scale "
                    "psum beside it — quantized gradients allreduce "
                    "without block-scale structure and the reduction "
                    "saturates/biases",
                    v.eqn,
                    hint="block-scale the quantized allreduce (EQuARX, "
                    "arXiv:2506.17615): psum int8/bf16 blocks AND their "
                    "f32 scales, dequantize after — "
                    "ops.quantize.quantized_psum emits the accepted pair "
                    "(quantize_block_scaled/dequantize_block_scaled are "
                    "the building blocks; trainer/step.py's "
                    "quantized_allreduce path uses them)",
                ))


def _rule_n406(visits, diags) -> None:
    for v in visits:
        if v.eqn.primitive.name != "convert_element_type":
            continue
        x = v.invals[0] if v.invals else None
        out = v.outvals[0]
        if x is None or x.kind != "op" or x.prim != "convert_element_type":
            continue
        origin = x.ins[0] if x.ins else None
        if origin is None:
            continue
        if not (_is_float(origin.dtype) and _is_float(x.dtype)
                and _is_float(out.dtype)):
            continue
        if np.dtype(origin.dtype) != np.dtype(out.dtype):
            continue
        try:
            mid_bits = _finfo(x.dtype).nmant
            end_bits = _finfo(out.dtype).nmant
        except ValueError:
            continue
        if mid_bits >= end_bits:
            continue
        diags.append(_diag(
            "N406", Severity.WARNING,
            f"dtype round-trip {origin.dtype}→{x.dtype}→{out.dtype}: "
            "the value is quantized and immediately promoted back — "
            "bandwidth spent destroying mantissa",
            v.eqn,
            hint="keep the value at one dtype across the boundary (hoist "
            "the cast, or drop the intermediate narrow cast)",
        ))


# ---------------------------------------------------------------------------
# pragma filtering
# ---------------------------------------------------------------------------


class _PragmaFilter:
    """Suppress findings whose issuing source line carries a justified
    ``# num: allow[<rule>]`` pragma; tracks per-file pragma usage so
    stale annotations can report uniformly with the lock plane."""

    def __init__(self) -> None:
        self._tables: Dict[str, Dict[int, _pragmas.Pragma]] = {}
        self._roots: Dict[str, str] = {}
        self.used: Dict[str, Set[int]] = {}
        self.pragma_diags: List[Diagnostic] = []

    def _table(self, relpath: str) -> Dict[int, _pragmas.Pragma]:
        got = self._tables.get(relpath)
        if got is not None:
            return got
        table: Dict[int, _pragmas.Pragma] = {}
        path = self._resolve(relpath)
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                table = _pragmas.collect(src, "num", relpath,
                                         self.pragma_diags)
            except OSError:
                table = {}
        self._tables[relpath] = table
        return table

    def _resolve(self, relpath: str) -> Optional[str]:
        if os.path.isabs(relpath):
            return relpath
        import paddle_tpu

        base = os.path.dirname(os.path.dirname(
            os.path.abspath(paddle_tpu.__file__)
        ))
        return os.path.join(base, relpath)

    def filter(self, diags: List[Diagnostic]) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for d in diags:
            if d.source and d.line:
                pragma = self._table(d.source).get(d.line)
                if pragma is not None and pragma.suppresses(d.rule):
                    self.used.setdefault(d.source, set()).add(d.line)
                    continue
            out.append(d)
        return out

    def stale(self) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for relpath, table in sorted(self._tables.items()):
            out.extend(_pragmas.stale_findings(
                table, self.used.get(relpath, ()), "num", relpath,
            ))
        return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def lint_numerics_jaxpr(
    closed,
    *,
    in_vals: Optional[Sequence[_Val]] = None,
    apply_pragmas: bool = True,
    _filter: Optional[_PragmaFilter] = None,
) -> List[Diagnostic]:
    """All structural N-rules (N401/N403/N404/N405/N406) over one closed
    jaxpr; N402 needs the train-step arg/out mapping — use
    :func:`lint_numerics_step`."""
    walker = _Walker()
    walker.walk_closed(closed, in_vals)
    diags: List[Diagnostic] = []
    _rule_n401(walker.visits, walker.scan_carries, diags)
    _rule_n403(walker.visits, diags)
    _rule_n404(walker.visits, diags)
    _rule_n405(walker.visits, diags)
    _rule_n406(walker.visits, diags)
    if apply_pragmas:
        f = _filter or _PragmaFilter()
        diags = f.filter(diags)
    return diags


def _trace_and_lint(
    fn,
    example_args,
    master_argnums: Sequence[int],
    master_dtype,
) -> Tuple[List[Diagnostic], _Walker]:
    """The ONE trace+rules body behind :func:`lint_numerics_step` and
    :func:`certify_precision_plan` — trace ``fn`` on the example args,
    walk the jaxpr, run every structural rule, and run the N402
    master-precision check over the flattened outputs of the argnums that
    hold master state.  Returns the UNFILTERED diagnostics plus the
    walker (the certificate reads its visits for per-layer rows)."""
    import jax

    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*example_args)
    walker = _Walker()

    in_vals: Optional[List[_Val]] = []
    for argnum, arg in enumerate(example_args):
        for path, leaf in jax.tree_util.tree_leaves_with_path(arg):
            label = f"arg{argnum}{jax.tree_util.keystr(path)}"
            in_vals.append(_Val("input", getattr(leaf, "dtype", None),
                                tag=label))
    if len(in_vals) != len(closed.jaxpr.invars):
        in_vals = None  # structure we can't map: rules still run

    out_vals = walker.walk_closed(closed, in_vals)

    out_labels: List[str] = []
    master_flags: List[bool] = []
    parts = out_shape if isinstance(out_shape, tuple) else (out_shape,)
    for outnum, part in enumerate(parts):
        for path, _leaf in jax.tree_util.tree_leaves_with_path(part):
            out_labels.append(f"out{outnum}{jax.tree_util.keystr(path)}")
            master_flags.append(outnum in master_argnums)

    diags: List[Diagnostic] = []
    _rule_n401(walker.visits, walker.scan_carries, diags)
    _rule_n403(walker.visits, diags)
    _rule_n404(walker.visits, diags)
    _rule_n405(walker.visits, diags)
    _rule_n406(walker.visits, diags)
    if len(out_labels) == len(out_vals):
        masters = [
            (v, lbl) for v, lbl, flag in
            zip(out_vals, out_labels, master_flags) if flag
        ]
        _rule_n402([v for v, _ in masters], [l for _, l in masters],
                   master_dtype, diags)
    return diags, walker


def lint_numerics_step(
    fn,
    *example_args,
    master_argnums: Sequence[int] = (0, 2),
    master_dtype=np.float32,
    apply_pragmas: bool = True,
    _filter: Optional[_PragmaFilter] = None,
) -> List[Diagnostic]:
    """Trace ``fn`` (a train-step body: ``(params, state, opt_state,
    batch, rng) -> (params, state, opt_state, metrics)``) on example args
    and run every N-rule, including the N402 master-precision check over
    the argnums that hold master state."""
    diags, _walker = _trace_and_lint(
        fn, example_args, master_argnums, master_dtype
    )
    if apply_pragmas:
        f = _filter or _PragmaFilter()
        diags = f.filter(diags)
    return diags


# -- probe construction ------------------------------------------------------


_LABEL_CONSUMERS = frozenset({
    "cross_entropy", "softmax_with_cost", "classification_cost",
    "multi_class_cross_entropy", "classification_error", "huber_cost",
    "crf", "crf_decoding", "ctc", "warp_ctc", "nce", "hsigmoid",
})


def _infer_probe_types(topology) -> Dict[str, Any]:
    """Probe-type overrides for v1 configs parsed WITHOUT a data provider:
    their slots sit at the parse-time dense placeholder, but the consumers
    pin what a real feed would be — an embedding input is an id sequence,
    a cost layer's label input is integer ids (sequence-shaped when the
    prediction side is a recurrent_group's per-step output)."""
    from paddle_tpu.core.data_types import (
        integer_value,
        integer_value_sequence,
    )

    data_names = set(topology.data_layers())
    overrides: Dict[str, Any] = {}
    for _name, conf in topology.layers.items():
        ins = list(conf.inputs)
        if conf.type == "embedding" and ins and ins[0] in data_names:
            dim = topology.layers[ins[0]].size
            overrides[ins[0]] = integer_value_sequence(dim)
        elif conf.type in _LABEL_CONSUMERS and len(ins) >= 2 \
                and ins[1] in data_names:
            dim = topology.layers[ins[1]].size
            pred = topology.layers.get(ins[0])
            seqish = pred is not None and pred.type in (
                "recurrent_group", "gru_step", "lstm_step",
            )
            overrides[ins[1]] = (
                integer_value_sequence(dim) if seqish else integer_value(dim)
            )
    return overrides


def _probe_rows(topology, batch_size: int = 4, seq_len: int = 6,
                overrides: Optional[Dict[str, Any]] = None):
    """Synthesize one deterministic feeder batch for a topology from its
    declared data types — the numerics lint needs real shapes/dtypes, not
    real data."""
    from paddle_tpu.core.data_types import SeqLevel, SlotKind

    overrides = overrides or {}
    rows = []
    for r in range(batch_size):
        row = []
        for _name, t in topology.data_types():
            t = overrides.get(_name, t)
            if t.kind == SlotKind.DENSE:
                v = [0.25 + 0.01 * r] * t.dim
            elif t.kind == SlotKind.INDEX:
                v = (r % max(t.dim, 1))
            else:  # sparse slots: a couple of active ids
                v = [0, min(1, t.dim - 1)]
            if t.seq == SeqLevel.SEQ:
                v = [v] * seq_len if t.kind != SlotKind.INDEX else [
                    (r + i) % max(t.dim, 1) for i in range(seq_len)
                ]
            elif t.seq == SeqLevel.SUB_SEQ:
                inner = [v] * 2 if t.kind != SlotKind.INDEX else [
                    r % max(t.dim, 1)
                ] * 2
                v = [inner, inner]
            row.append(v)
        rows.append(tuple(row))
    return rows


def _probe_batch(topology, batch_size: int = 4, seq_len: int = 6,
                 overrides: Optional[Dict[str, Any]] = None):
    from paddle_tpu.reader.feeder import DataFeeder, feed_dtypes_of

    overrides = overrides or {}
    types = [
        (name, overrides.get(name, t)) for name, t in topology.data_types()
    ]
    feeder = DataFeeder(types, feed_dtypes=feed_dtypes_of(topology))
    return feeder(_probe_rows(topology, batch_size, seq_len, overrides))


def _step_parts(topology, optimizer=None, compute_dtype=None,
                master_dtype=None, batch_size: int = 4, seq_len: int = 6,
                infer_types: bool = False):
    """(step_body, example_args) for the REAL train step of a topology at
    the given precision plan — the jaxpr certify/lint run over."""
    import jax

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.trainer.step import _train_step_body

    if optimizer is None:
        import paddle_tpu.optimizer as O

        optimizer = O.Adam(learning_rate=1e-3)
    kwargs: Dict[str, Any] = {}
    if master_dtype is not None:
        kwargs["dtype"] = np.dtype(master_dtype)
    if compute_dtype is not None:
        kwargs["compute_dtype"] = np.dtype(compute_dtype)
    net = CompiledNetwork(topology, **kwargs)
    overrides = _infer_probe_types(topology) if infer_types else None
    batch = _probe_batch(topology, batch_size, seq_len, overrides)
    params, state = net.init(jax.random.PRNGKey(0))
    if getattr(net, "has_dynamic_widths", False):
        params, chg = net.resolve_dynamic_widths(params, batch)
        del chg
    opt_state = optimizer.init(params)
    step = _train_step_body(net, optimizer, sentinel=True)
    return step, (params, state, opt_state, batch, jax.random.PRNGKey(1))


def lint_numerics_config(
    config_path: str,
    config_args: str = "",
    compute_dtype=None,
    master_dtype=None,
    apply_pragmas: bool = True,
    _filter: Optional[_PragmaFilter] = None,
) -> List[Diagnostic]:
    """Parse a v1 config and precision-lint its REAL train step (the
    parsed settings' optimizer, a synthesized probe batch) at the given
    dtype plan — the ``paddle-tpu lint --numerics --config`` body."""
    from paddle_tpu.v1_compat import make_optimizer, parse_config

    parsed = parse_config(os.path.abspath(config_path), config_args)
    try:
        optimizer = make_optimizer(parsed.settings)
    except Exception:  # noqa: BLE001 — exotic settings: probe with Adam
        optimizer = None
    step, args = _step_parts(
        parsed.topology, optimizer,
        compute_dtype=compute_dtype, master_dtype=master_dtype,
        infer_types=True,
    )
    return lint_numerics_step(
        step, *args,
        master_dtype=np.dtype(master_dtype or np.float32),
        apply_pragmas=apply_pragmas, _filter=_filter,
    )


def lint_numerics_package(
    compute_dtype=None,
    master_dtype=None,
    check_stale_pragmas: Optional[bool] = None,
) -> List[Diagnostic]:
    """The package leg of ``paddle-tpu lint --numerics``: precision-lint
    the shipped step builders over probe topologies that exercise the
    planes the flagships use (dense MLP, LSTM sequence path, the fused
    attention-GRU decoder), plus ``# num:`` pragma hygiene.  Stale-pragma
    reporting defaults to ON for sub-f32 runs (the dtype context the
    pragmas exist for) and OFF at f32."""
    if check_stale_pragmas is None:
        check_stale_pragmas = compute_dtype is not None and _is_low(
            np.dtype(compute_dtype)
        )
    f = _PragmaFilter()
    diags: List[Diagnostic] = []
    for topo in _probe_topologies():
        step, args = _step_parts(
            topo, None, compute_dtype=compute_dtype,
            master_dtype=master_dtype,
        )
        diags.extend(lint_numerics_step(step, *args, _filter=f))
    if check_stale_pragmas:
        # load EVERY package file's pragmas first: the hygiene findings
        # (empty justifications) they append must land in pragma_diags
        # BEFORE it is folded into the result below
        _load_package_pragmas(f)
        diags.extend(f.pragma_diags)
        diags.extend(f.stale())
    else:
        diags.extend(f.pragma_diags)
    return diags


def _probe_topologies():
    """Small topologies covering the numerics-relevant layer planes: the
    MLP (dense dot + softmax CE), the LSTM text path (embedding, scan
    recurrence, pooling), and the attention decoder (masked softmax, the
    fused GRU core)."""
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import Topology, reset_auto_names

    L, A = paddle.layer, paddle.activation
    topos = []

    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(64))
    h = L.fc(x, size=64, act=A.Relu())
    pred = L.fc(h, size=10, act=A.Softmax())
    y = L.data("y", paddle.data_type.integer_value(10))
    topos.append(Topology([L.classification_cost(input=pred, label=y)]))

    reset_auto_names()
    w = L.data("w", paddle.data_type.integer_value_sequence(50))
    emb = L.embedding(w, size=32)
    lstm = paddle.networks.simple_lstm(input=emb, size=32)
    pooled = L.pooling(lstm, pooling_type=paddle.pooling.Max())
    out = L.fc(pooled, size=4, act=A.Softmax())
    lab = L.data("lab", paddle.data_type.integer_value(4))
    topos.append(Topology([L.classification_cost(input=out, label=lab)]))

    reset_auto_names()
    from paddle_tpu.models.seq2seq import seq2seq_cost

    cost, _ = seq2seq_cost(40, 45, word_dim=16, hidden_dim=16)
    topos.append(Topology([cost]))

    # a plain recurrent_group (no fused-core match) so the GENERIC scan
    # path — and its backward's carried weight-cotangent accumulation —
    # is exercised at the probe dtype too
    reset_auto_names()
    w2 = L.data("w2", paddle.data_type.integer_value_sequence(30))
    emb2 = L.embedding(w2, size=16)

    def _step(x):
        prev = L.memory("h", 16)
        return L.fc([x, prev], size=16, act=A.Tanh(), name="h")

    rec = L.recurrent_group(step=_step, input=emb2)
    pooled2 = L.pooling(rec, pooling_type=paddle.pooling.Max())
    out2 = L.fc(pooled2, size=4, act=A.Softmax())
    lab2 = L.data("lab2", paddle.data_type.integer_value(4))
    topos.append(Topology([L.classification_cost(input=out2, label=lab2)]))
    return topos


def _load_package_pragmas(f: _PragmaFilter) -> None:
    """Ensure every package file's ``# num:`` pragmas are in the filter's
    tables so stale reporting covers pragmas in files the probe traces
    never reached."""
    import paddle_tpu

    root = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            f._table(_relpath(path))


# ---------------------------------------------------------------------------
# precision-plan certification
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PrecisionCertificate:
    """The static verdict on one compute/master dtype split: per-layer
    rows plus the N-rule findings the plan would ship with."""

    ok: bool
    compute_dtype: str
    master_dtype: str
    diagnostics: List[Diagnostic]
    rows: List[Dict[str, Any]]  # name, type, dtype, n_dot, acc, hazards

    def format(self) -> str:
        head = (
            f"precision certificate: compute={self.compute_dtype} "
            f"master={self.master_dtype} -> "
            f"{'ACCEPT' if self.ok else 'REJECT'}"
        )
        w = max([16] + [len(r["layer"]) for r in self.rows]) + 1
        lines = [head, f"{'layer':<{w}}{'type':<18}{'compute':<10}"
                 f"{'dots(acc)':<12}{'hazards':<8}"]
        for r in self.rows:
            lines.append(
                f"{r['layer']:<{w}}{r['type']:<18}{r['dtype']:<10}"
                f"{str(r['dots']) + '(' + r['acc'] + ')':<12}"
                f"{r['hazards']:<8}"
            )
        if self.diagnostics:
            from paddle_tpu.analysis.diagnostics import format_diagnostics

            lines.append(format_diagnostics(self.diagnostics))
        return "\n".join(lines)


def certify_precision_plan(
    topology,
    plan: Dict[str, Any],
    optimizer=None,
) -> PrecisionCertificate:
    """Statically verify a precision plan over the REAL train-step jaxpr.

    ``plan``: ``{"compute_dtype": ..., "master_dtype": ...,
    "quantized_weights": bool}`` (names or dtypes; master defaults to
    float32).  ACCEPT iff no ERROR-severity N-rule fires — in particular a
    plan whose master dtype is sub-f32 (params updated in bf16) is
    rejected by N402, while the sanctioned master-f32/compute-bf16 split
    passes on the shipped flagships.  This is the gate a ROADMAP-item-2
    quantized/low-precision config must clear before it is allowed near a
    convergence run.

    ``quantized_weights`` declares weight-ONLY int8 (the serving decode
    bundle as int8 blocks + f32 scales, dequantized in-graph): it leaves
    the traced train plane untouched, so the sanctioned splits still
    ACCEPT.  A NON-FLOAT master or compute dtype (int8 master params /
    optimizer state) is rejected outright, without tracing: integer state
    cannot carry the update accumulation at all."""
    compute = np.dtype(plan.get("compute_dtype") or np.float32)
    master = np.dtype(plan.get("master_dtype") or np.float32)
    for role, dt in (("master", master), ("compute", compute)):
        if not _is_float(dt):
            d = Diagnostic(
                rule="N402", severity=Severity.ERROR,
                message=f"precision plan asks for {role} dtype {dt} — "
                "integer master params/optimizer state cannot accumulate "
                "updates (every step requantizes the whole trajectory); "
                "quantization must stay weight-only",
                hint="keep master/compute dtypes float; declare int8 "
                "serving weights via plan['quantized_weights']=True "
                "(ops.quantize.quantize_weight_bundle)",
            )
            return PrecisionCertificate(
                ok=False, compute_dtype=str(compute),
                master_dtype=str(master), diagnostics=[d], rows=[],
            )

    f = _PragmaFilter()
    step, args = _step_parts(
        topology, optimizer, compute_dtype=compute, master_dtype=master,
        infer_types=True,
    )
    # the SAME trace+rules body the lint runs — the gate can never be
    # weaker than `paddle-tpu lint --numerics` on the same plan
    diags, walker = _trace_and_lint(step, args, (0, 2), master)
    diags = f.filter(diags)
    # malformed (empty-justification) pragmas in the files this trace
    # touched keep the certificate honest: hygiene findings reject too
    diags = diags + f.pragma_diags

    # per-layer rows from the named-scope groups of the traced step
    per_layer: Dict[str, Dict[str, Any]] = {}
    layer_types = {
        name: conf.type for name, conf in topology.layers.items()
    }
    for v in walker.visits:
        layer = _eqn_layer(v.eqn)
        if layer is None or layer not in layer_types:
            continue
        row = per_layer.setdefault(layer, {
            "layer": layer, "type": layer_types[layer],
            "dtype": "-", "dots": 0, "acc": "-", "hazards": 0,
        })
        prim = v.eqn.primitive.name
        if prim in ("dot_general", "conv_general_dilated"):
            row["dots"] += 1
            opdt = v.invals[0].dtype if v.invals else None
            # the LOWEST operand dtype seen is the layer's compute dtype
            # (backward-pass dots at f32 must not mask a bf16 forward)
            if opdt is not None and (row["dtype"] == "-" or _is_low(opdt)):
                row["dtype"] = str(opdt)
                pet = v.eqn.params.get("preferred_element_type")
                row["acc"] = str(np.dtype(pet)) if pet is not None else str(
                    opdt
                )
    hazard_lines = {
        (d.layer, d.rule) for d in diags if d.layer is not None
    }
    for layer, rule in hazard_lines:
        if layer in per_layer:
            per_layer[layer]["hazards"] += 1
    rows = [per_layer[k] for k in topology.order if k in per_layer]

    from paddle_tpu.analysis.diagnostics import errors

    return PrecisionCertificate(
        ok=not errors(diags),
        compute_dtype=str(compute),
        master_dtype=str(master),
        diagnostics=diags,
        rows=rows,
    )
