"""Shared allowlist-pragma parser — ONE tokenizer for every lint plane.

Three analysis planes grew line-anchored escape hatches independently:
the C-rules' ``# lock: allow[C304] <why>`` (concurrency_lint), the
numerics plane's ``# num: allow[N403] <why>`` (numerics_lint), and the
A205 wall-clock escape ``# obs: allow-wall-clock <why>`` (ast_rules).
They share one discipline — a pragma is a COMMENT token (never a string
literal showing the syntax), it names the rules it suppresses, and its
justification string is REQUIRED — so they share one parser.

Per plane the grammar differs only in spelling:

    # lock: allow[C304,C306] why      rules come from the bracket list
    # num: allow[N401] why            same grammar, N-rule namespace
    # wire: allow[A206] why           same grammar, the raw-deserialization
                                      ban (ast_rules A206)
    # proto: allow[P504] why          same grammar, the protocol
                                      conformance plane (protocol_lint)
    # obs: allow-wall-clock why       keyword form; always rule A205

``collect`` returns ``{line: Pragma}`` plus uniform findings for
malformed pragmas (empty rule list / empty justification) under the
plane's bookkeeping rule id; ``stale_findings`` reports pragmas that
suppressed nothing — the annotated hazard moved or stopped firing — so
every plane's allowlist stays an honest record of intentional hazards.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["Pragma", "collect", "comment_tokens", "stale_findings", "PLANES"]


@dataclasses.dataclass(frozen=True)
class Pragma:
    """One parsed allowlist annotation: the rules it suppresses on its
    line and the (non-empty) justification its author supplied."""

    line: int
    rules: frozenset
    justification: str

    def suppresses(self, rule: str) -> bool:
        return rule in self.rules


@dataclasses.dataclass(frozen=True)
class _Plane:
    name: str                      # comment prefix: "# <name>: ..."
    pattern: re.Pattern            # groups: (rules-or-None, justification)
    fixed_rules: Optional[frozenset]  # keyword planes map to one rule set
    bookkeeping_rule: str          # id for empty/stale pragma findings
    example: str                   # fix-hint template


def _allow_plane(name: str, bookkeeping_rule: str, example_rule: str) -> _Plane:
    return _Plane(
        name=name,
        pattern=re.compile(
            r"#\s*" + name + r":\s*allow\[([A-Z0-9, ]*)\]\s*(.*)$"
        ),
        fixed_rules=None,
        bookkeeping_rule=bookkeeping_rule,
        example=f"# {name}: allow[{example_rule}] <why this is intentional>",
    )


PLANES: Dict[str, _Plane] = {
    "lock": _allow_plane("lock", "C300", "C304"),
    "num": _allow_plane("num", "N400", "N403"),
    "wire": _allow_plane("wire", "A206", "A206"),
    "proto": _allow_plane("proto", "P500", "P504"),
    "obs": _Plane(
        name="obs",
        pattern=re.compile(r"#\s*obs:\s*allow-wall-clock\s*(())?(.*)$"),
        fixed_rules=frozenset({"A205"}),
        bookkeeping_rule="A205",
        example="# obs: allow-wall-clock <why this wall read can never "
        "stamp a span>",
    ),
}


def comment_tokens(src: str) -> List[Tuple[int, str]]:
    """``(line, text)`` for every COMMENT token in ``src`` — a pragma
    spelled inside a string literal (a docstring showing the syntax, a
    fix-hint template) is documentation, not an annotation.  An
    unparseable tail returns the comments seen so far (the AST pass
    reports the syntax error on its own)."""
    comments: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return comments


def collect(
    src: str,
    plane: str,
    relpath: str,
    diags: Optional[List[Diagnostic]] = None,
) -> Dict[int, Pragma]:
    """Parse every ``plane`` pragma in ``src``.  Malformed pragmas (empty
    rule list or empty justification) append a finding to ``diags`` under
    the plane's bookkeeping rule and are NOT returned — a rejected pragma
    must never suppress the hazard it annotates."""
    spec = PLANES[plane]
    out: Dict[int, Pragma] = {}
    for line, comment in comment_tokens(src):
        m = spec.pattern.search(comment)
        if not m:
            continue
        if spec.fixed_rules is not None:
            rules: Set[str] = set(spec.fixed_rules)
            justification = (m.group(3) or "").strip()
        else:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            justification = (m.group(2) or "").strip()
        if not rules or not justification:
            if diags is not None:
                diags.append(Diagnostic(
                    rule=spec.bookkeeping_rule, severity=Severity.ERROR,
                    message=f"empty `# {spec.name}:` allowlist pragma "
                    "without a justification string (every intentional "
                    "hazard must say WHY)",
                    source=relpath, line=line,
                    hint=spec.example,
                ))
            continue
        out[line] = Pragma(line=line, rules=frozenset(rules),
                           justification=justification)
    return out


def stale_findings(
    pragmas: Dict[int, Pragma],
    used_lines: Iterable[int],
    plane: str,
    relpath: str,
    severity: Severity = Severity.WARNING,
) -> List[Diagnostic]:
    """A pragma that suppressed nothing is a stale annotation — the
    hazard it justified moved or stopped firing.  Reported under the
    plane's bookkeeping rule so the allowlist stays honest."""
    spec = PLANES[plane]
    used = set(used_lines)
    out: List[Diagnostic] = []
    for line in sorted(pragmas):
        if line in used:
            continue
        p = pragmas[line]
        out.append(Diagnostic(
            rule=spec.bookkeeping_rule, severity=severity,
            message=f"unused `# {spec.name}:` allowlist pragma "
            f"allow[{','.join(sorted(p.rules))}] — no finding on this "
            "line is suppressed by it (stale annotation)",
            source=relpath, line=line,
            hint="delete the pragma, or re-anchor it on the line that "
            "actually needs the exemption",
        ))
    return out
