"""Master high availability: leader election + hot standby failover.

Reference: the Go master wins leadership through an etcd campaign, keeps it
with a lease, snapshots its queues into etcd, and a standby that wins the
next campaign recovers from the snapshot while clients re-resolve the
master address from etcd (go/master/etcd_client.go).

etcd-free equivalent over shared storage (a TPU pod's coordinator hosts
share a filesystem): leadership is a LEASE FILE renewed by mtime heartbeat,
takeover is an atomic rename of a claim file, the queue snapshot is the
Service's existing JSON file, and the leader publishes its RPC address in
an endpoint file clients poll — the same four etcd roles (campaign, lease,
state, discovery), one directory.

Warm standby (the journaled state plane of master.py/master_journal.py):
while a candidate loses the campaign it TAILS the leader's snapshot +
append-only journal into an in-memory replica Service, applying each
CRC-verified record as it lands.  Winning the next campaign is then
``promote()`` — refresh lease deadlines, compact into a generation this
instance owns, publish the endpoint — not a restart: task leases stay
warm, per-task result payloads survive, and a failover mid-pass completes
the pass with ZERO recomputed tasks.  ``last_takeover`` records the
takeover span and how many journal records the replica replayed — the
recovery-time-after-fault metrics the failover bench commits.

    ha = HAMaster(dir, patterns)      # every candidate host runs this
    ha.start()                        # blocks until leader OR standby-watch
    ...
    client = HAClient(dir)            # discovers + follows the leader
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from paddle_tpu import master_journal as _mj
from paddle_tpu import master_wire as _wire
from paddle_tpu.master import Client, MasterRPCError, Server, Service

__all__ = ["LeaseFile", "HAMaster", "HAClient", "discover_endpoint"]

_log = logging.getLogger("paddle_tpu.master_ha")


class LeaseFile:
    """Heartbeat-lease leader election in a directory.

    The leader owns ``leader.lease`` and renews its mtime; a candidate may
    claim leadership only when the lease is missing or stale (now - mtime >
    lease_timeout).  Claims go through an exclusively-created claim file +
    atomic rename so two candidates racing for a stale lease cannot both
    win (the one whose rename lands second just overwrites with its own
    identity and the loser detects the foreign owner on verify).

    ``clock``/``sleep`` are injectable (the PR-5 injectable-sleep pattern):
    staleness is judged against ``clock()`` and every heartbeat/claim stamps
    the file's mtime from the same clock, so lease-expiry tests advance a
    fake clock instead of sleeping real wall time."""

    def __init__(
        self,
        dir_: str,
        owner_id: str,
        lease_timeout: float = 5.0,
        clock=time.time,
        sleep=time.sleep,
    ):
        self.dir = dir_
        self.owner_id = owner_id
        self.lease_timeout = lease_timeout
        self._clock = clock
        self._sleep = sleep
        self.path = os.path.join(dir_, "leader.lease")
        os.makedirs(dir_, exist_ok=True)

    # -- inspection ------------------------------------------------------
    def current_owner(self) -> Optional[str]:
        try:
            with open(self.path) as f:
                return json.load(f)["owner"]
        except (OSError, ValueError, KeyError):
            return None

    def is_stale(self) -> bool:
        try:
            return self._clock() - os.path.getmtime(self.path) > self.lease_timeout
        except OSError:
            return True  # missing == stale

    def held_by_me(self) -> bool:
        return self.current_owner() == self.owner_id and not self.is_stale()

    # -- campaign --------------------------------------------------------
    def try_acquire(self) -> bool:
        if not self.is_stale():
            return self.current_owner() == self.owner_id
        claim = os.path.join(self.dir, f".claim-{self.owner_id}")
        now = self._clock()
        with open(claim, "w") as f:
            json.dump({"owner": self.owner_id, "t": now}, f)
        os.utime(claim, (now, now))  # mtime from the SAME clock is_stale reads
        # Re-check right before the rename: a stalled-but-alive leader may
        # have renewed since our staleness read (shrinks the clobber window
        # to the check->rename gap; the remaining dual-leader window is
        # bounded by the deposed side's next renew(), which detects the
        # foreign owner and steps down — snapshot writes are fenced).
        if not self.is_stale():
            try:
                os.remove(claim)
            except OSError:
                pass
            return False
        os.replace(claim, self.path)
        # verify after the dust settles: a racing rename may have landed on
        # top of ours (last-writer-wins is exactly one winner)
        self._sleep(0.01)
        return self.current_owner() == self.owner_id

    def renew(self) -> bool:
        if self.current_owner() != self.owner_id:
            return False  # usurped (we were stale and someone claimed)
        from paddle_tpu.robustness import chaos as _chaos

        if _chaos.fire("stale_lease"):
            # chaos drill: the leader BELIEVES it renewed but the heartbeat
            # never reached shared storage (GC pause, NFS stall) — the lease
            # goes stale underneath it and a standby must take over while
            # this side detects the usurper and steps down
            return True
        now = self._clock()
        try:
            os.utime(self.path, (now, now))
        except OSError:
            return False  # lease file vanished under us: treat as usurped
        return True

    def release(self) -> None:
        if self.current_owner() == self.owner_id:
            try:
                os.remove(self.path)
            except OSError:
                pass


def _endpoint_path(dir_: str) -> str:
    return os.path.join(dir_, "endpoint.json")


def discover_endpoint(dir_: str) -> Optional[tuple]:
    """(host, port) of the current leader, or None (reference: clients
    watch the etcd master-addr key, etcd_client.go GetKey)."""
    try:
        with open(_endpoint_path(dir_)) as f:
            d = json.load(f)
        return (d["host"], d["port"])
    except (OSError, ValueError, KeyError):
        return None


class HAMaster:
    """One master candidate.  start() campaigns; the winner serves the
    task queues (recovering them from the shared snapshot), losers keep
    watching and take over when the lease goes stale."""

    def __init__(
        self,
        dir_: str,
        patterns: Sequence[str],
        owner_id: Optional[str] = None,
        lease_timeout: float = 5.0,
        renew_interval: Optional[float] = None,
        address=("127.0.0.1", 0),
        **service_kw,
    ):
        self.dir = dir_
        self.patterns = list(patterns)
        self.owner_id = owner_id or f"{os.uname().nodename}:{os.getpid()}"
        self.lease = LeaseFile(dir_, self.owner_id, lease_timeout)
        self.renew_interval = renew_interval or lease_timeout / 3.0
        self._address = address
        self._service_kw = dict(service_kw)
        self._service_kw.setdefault(
            "snapshot_path", os.path.join(dir_, "master_state.json")
        )
        # HA candidates run the durable state plane by default: every queue
        # transition is an fsync'd journal record, so OUR standby peers can
        # tail it and take over warm (journal=False opts back into the
        # legacy debounced-snapshot mode)
        self._service_kw.setdefault("journal", True)
        self.service: Optional[Service] = None
        self.server: Optional[Server] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.is_leader = threading.Event()
        # -- warm-standby replica (journal tail) ---------------------------
        self._replica: Optional[Service] = None
        self._replica_key = None  # (journal_file, base_seq) it loaded from
        self._tail_path: Optional[str] = None
        self._tail_offset = 0
        self._tail_corrupt_warned = False
        self._snap_stat = None  # (mtime_ns, size, ino) of the parsed snapshot
        self._legacy_snapshot = False  # last parse found no journal_file
        # set each time this candidate assumes leadership: {"warm",
        # "replayed_records", "takeover_s", "t_leader"} — the recovery-
        # time-after-fault observables
        self.last_takeover: Optional[Dict[str, Any]] = None
        # a poisoned journal (unknown record type: version skew) is fatal
        # for the whole CANDIDATE, not just its campaign thread — a silent
        # thread death would leave a zombie that never takes over.  The
        # CLI loop polls this and exits nonzero.
        self.fatal: Optional[str] = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="paddle-ha-campaign", daemon=True
        )
        self._thread.start()

    def wait_leader(self, timeout: Optional[float] = None) -> bool:
        return self.is_leader.wait(timeout)

    # -- warm standby: tail the leader's journal into a replica ----------
    def _drop_replica(self) -> None:
        self._replica = None
        self._replica_key = None
        self._tail_path = None
        self._tail_offset = 0
        self._tail_corrupt_warned = False
        self._snap_stat = None
        self._legacy_snapshot = False

    def _standby_tick(self) -> None:
        """Advance the in-memory replica: (re)load the snapshot when the
        leader compacted into a new journal generation, then apply every
        complete CRC-verified record appended since our last read.  Any
        failure just leaves the replica where it was — takeover falls back
        to cold recovery, which replays the same files."""
        if not self._service_kw.get("journal"):
            return  # legacy mode: nothing to tail
        snap = self._service_kw["snapshot_path"]
        try:
            st = os.stat(snap)
        except OSError:
            return  # no leader yet: next tick retries
        snap_stat = (st.st_mtime_ns, st.st_size, st.st_ino)
        if self._tail_path is not None and not os.path.exists(self._tail_path):
            # our generation vanished: a compaction we missed swept it.
            # The stat-compare below can (rarely) miss the new snapshot —
            # coarse mtime + equal size + recycled inode — and a missed
            # generation change would freeze the replica FOREVER, so the
            # swept tail forces the full reparse
            self._snap_stat = None
        # the snapshot only moves at compaction (every ~512 records), but
        # it embeds every task's chunk metadata — skip the full JSON parse
        # on the overwhelmingly common unchanged tick; the journal tail
        # below carries everything newer than the snapshot anyway
        if ((self._replica is None and not self._legacy_snapshot)
                or snap_stat != self._snap_stat):
            try:
                with open(snap) as f:
                    state = json.load(f)
            except (OSError, ValueError):
                return  # no leader yet, or mid-rename: next tick retries
            jf = state.get("journal_file")
            self._legacy_snapshot = jf is None
            if jf is None:
                # legacy (journal-less) snapshot: no replica to build, but
                # REMEMBER the stat — else every tick re-parses the whole
                # snapshot of a --no-journal leader forever
                self._drop_replica()
                self._legacy_snapshot = True
                self._snap_stat = snap_stat
                return
            self._snap_stat = snap_stat
            key = (jf, int(state.get("seq", 0)))
            if self._replica is None or self._replica_key != key:
                kw = {
                    k: v for k, v in self._service_kw.items()
                    if k not in ("snapshot_path", "journal", "journal_fsync",
                                 "journal_compact_every")
                }
                svc = Service(snapshot_path=None, journal=False, **kw)
                svc.load_state(state, warm=True)
                # remember the generation so promotion compacts into gen+1
                # and never truncates the very file the snapshot still
                # references
                svc._journal_gen = _mj.parse_generation(jf)
                self._replica = svc
                self._replica_key = key
                self._tail_path = os.path.join(
                    os.path.dirname(snap) or ".", jf
                )
                self._tail_offset = 0
        if self._tail_path and os.path.exists(self._tail_path):
            try:
                records, info = _mj.read_records(
                    self._tail_path, self._tail_offset
                )
            except FileNotFoundError:
                # swept between the exists() check and the open() — the
                # leader compacted in that window.  Same handling as the
                # vanished-tail fast path above: force the reparse next
                # tick instead of letting the error destroy the replica
                self._snap_stat = None
                return
            for seq, rec in records:
                self._replica.apply_record(seq, rec)
            # a torn tail is an append IN FLIGHT: stay put and re-read the
            # frame once the leader finishes (or died — then promotion
            # replays the same consistent prefix).  A CRC-corrupt COMPLETE
            # frame is different: the tail is permanently stuck at the rot,
            # so a takeover from here silently loses every transition the
            # leader fsync'd past it — warn ONCE so the operator hears it
            # while the leader is still alive to re-compact past the rot.
            if info["corrupt"] and not self._tail_corrupt_warned:
                self._tail_corrupt_warned = True
                _log.warning(
                    "standby %s: journal %s: %s — replica tail is stuck at "
                    "the good prefix; a takeover from here would drop "
                    "every later acked transition",
                    self.owner_id, self._tail_path, info["error"],
                )
            self._tail_offset = info["end_offset"]

    def _become_leader(self) -> None:
        t0 = time.monotonic()
        warm = False
        svc = None
        if self._replica is not None:
            # final catch-up read, then promote the tailed replica: leases
            # refresh, a fresh journal generation is compacted, and the
            # takeover carries ZERO recomputed tasks.  A JournalError here
            # (unknown record type) propagates to the campaign loop's
            # fatal path — never assume a lossy recovery.
            self._standby_tick()
            # the tick itself can DROP the replica it was catching up (a
            # deposed --no-journal leader published a legacy snapshot in
            # the campaign window) — fall through to cold recovery rather
            # than promote None
            svc = self._replica
            self._drop_replica()
        if svc is not None:
            svc.promote(
                self._service_kw["snapshot_path"],
                journal_fsync=self._service_kw.get("journal_fsync"),
                journal_compact_every=self._service_kw.get(
                    "journal_compact_every"
                ),
            )
            self.service = svc
            warm = True
        else:
            # cold path (first leader, or nothing tailed yet): recover the
            # queues from the shared snapshot + bounded journal replay (a
            # fresh cluster has none; set_dataset is idempotent against
            # recovered state)
            self.service = Service(**self._service_kw)
        self.service.set_dataset(self.patterns)
        self.server = Server(self.service, address=self._address)
        host, port = self.server.address
        tmp = _endpoint_path(self.dir) + f".{self.owner_id}"
        with open(tmp, "w") as f:
            json.dump({"host": host, "port": port, "owner": self.owner_id}, f)
        os.replace(tmp, _endpoint_path(self.dir))
        self.last_takeover = {
            "warm": warm,
            "replayed_records": self.service.replayed_records,
            "takeover_s": time.monotonic() - t0,
            "t_leader": time.time(),
        }
        _log.info(
            "master %s assumed leadership (%s, %d journal records replayed, "
            "%.3fs)", self.owner_id, "warm" if warm else "cold",
            self.last_takeover["replayed_records"],
            self.last_takeover["takeover_s"],
        )
        self.is_leader.set()

    def _step_down(self) -> None:
        self.is_leader.clear()
        if self.server is not None:
            self.server.close()  # stops accepting AND drops live conns
            self.server = None
        if self.service is not None:
            self.service.fence()  # never write the shared files again
        self.service = None
        self._drop_replica()  # rebuild against the NEW leader's generation

    def _run(self) -> None:
        try:
            self._campaign_loop()
        except _mj.JournalError as exc:
            # poisoned journal (unknown record type: version skew).  The
            # candidate is DEAD, not just its thread: record it where
            # wait_fatal()/the CLI loop sees it, release any leadership,
            # and crash the thread loudly.
            self.fatal = f"poisoned journal: {exc}"
            _log.error("master %s is dead: %s", self.owner_id, self.fatal)
            if self.is_leader.is_set():
                self._step_down()
                self.lease.release()
            raise

    def _campaign_loop(self) -> None:
        while not self._stop.is_set():
            if self.is_leader.is_set():
                if not self.lease.renew():
                    self._step_down()  # usurped after a stall
                self._stop.wait(self.renew_interval)
            else:
                if self.lease.try_acquire():
                    try:
                        self._become_leader()
                    except _mj.JournalError:
                        # never campaign again against a journal we refuse
                        # to interpret — a lossy takeover would recompute
                        # or, worse, double-apply acked transitions
                        self._step_down()
                        self.lease.release()
                        raise
                    except Exception:
                        # corrupt snapshot / bind failure: surface it, give
                        # the lease back, keep campaigning after a backoff
                        _log.exception(
                            "master %s failed to assume leadership",
                            self.owner_id,
                        )
                        self._step_down()
                        self.lease.release()
                        self._stop.wait(self.lease.lease_timeout)
                else:
                    try:
                        self._standby_tick()
                    except _mj.JournalError:
                        raise  # poisoned journal: crash loudly, don't lurk
                    except Exception:  # noqa: BLE001 — replica is advisory
                        _log.exception(
                            "standby %s: journal tail failed; takeover "
                            "will recover cold", self.owner_id,
                        )
                        self._drop_replica()
                    self._stop.wait(self.renew_interval)
        if self.is_leader.is_set():
            self._step_down()
            self.lease.release()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # test hook: simulate a crashed leader (no release, no renewals)
    def freeze(self) -> None:
        self._stop.set()
        self.is_leader.clear()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.server is not None:
            self.server.close()
            self.server = None


class HAClient:
    """Client that discovers the leader from the endpoint file and
    re-resolves + reconnects when the master fails over (the reference
    client watches etcd and reconnects, client.go)."""

    def __init__(self, dir_: str, timeout: float = 30.0,
                 sleep=time.sleep, **client_kw):
        self.dir = dir_
        self.timeout = timeout
        self._sleep = sleep  # injectable: discovery/re-dial poll loops
        self._client_kw = client_kw
        self._client: Optional[Client] = None
        self._endpoint = None

    def _connect(self) -> Client:
        deadline = time.time() + self.timeout
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            ep = discover_endpoint(self.dir)
            if ep is not None:
                try:
                    c = Client(ep, sleep=self._sleep, **self._client_kw)
                    self._endpoint = ep
                    return c
                except (ConnectionError, OSError) as e:
                    last_err = e
            self._sleep(0.1)
        raise TimeoutError(f"no master leader in {self.dir}: {last_err}")

    def _call(self, method, *args):
        deadline = time.time() + self.timeout
        while True:
            if self._client is None:
                self._client = self._connect()
            try:
                return getattr(self._client, method)(*args)
            except MasterRPCError:
                raise  # the master executed the call: a real app error
            except _wire.WireTypeError:
                raise  # unencodable payload: deterministic, re-dialing is futile
            except _wire.WireOversizeError:
                raise  # over rpc_max_message_mb: deterministic, same story
            except (_wire.MasterWireError, ConnectionError, EOFError, OSError):
                # leader died mid-call — or the Client's bounded retry
                # exhausted against a storm of corrupt/duplicated frames
                # (netem drills): drop the connection, re-discover the
                # leader, ride the failover window.  Send-side wire
                # errors (type/oversize) re-raised above: those are OUR
                # payload's fault, not the network's.
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None
                if time.time() > deadline:
                    raise
                self._sleep(0.2)

    # -- surface (the Client subset trainers use) ------------------------
    def set_dataset(self, patterns):
        return self._call("set_dataset", patterns)

    def next_record(self):
        return self._call("next_record")

    def start_new_pass(self, target_pass=None, worker_id=None):
        return self._call("start_new_pass", target_pass, worker_id)

    def request_save_model(self, block_secs: float = 60.0):
        return self._call("request_save_model", block_secs)

    def __getattr__(self, name):
        """The elastic cluster surface (get_task, task_finished, registry,
        fences, pass_results, ...) delegates from ``master._METHODS`` with
        the reconnect-on-failover discipline of :meth:`_call` — mirrors
        Client.__getattr__, one definition for the whole surface."""
        from paddle_tpu.master import _METHODS

        if name in _METHODS:
            return lambda *args: self._call(name, *args)
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}"
        )

    def reader(self):
        from paddle_tpu.master import reader_over

        return reader_over(self.next_record)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
