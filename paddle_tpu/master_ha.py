"""Master high availability: leader election + hot standby failover.

Reference: the Go master wins leadership through an etcd campaign, keeps it
with a lease, snapshots its queues into etcd, and a standby that wins the
next campaign recovers from the snapshot while clients re-resolve the
master address from etcd (go/master/etcd_client.go).

etcd-free equivalent over shared storage (a TPU pod's coordinator hosts
share a filesystem): leadership is a LEASE FILE renewed by mtime heartbeat,
takeover is an atomic rename of a claim file, the queue snapshot is the
Service's existing JSON file, and the leader publishes its RPC address in
an endpoint file clients poll — the same four etcd roles (campaign, lease,
state, discovery), one directory.

    ha = HAMaster(dir, patterns)      # every candidate host runs this
    ha.start()                        # blocks until leader OR standby-watch
    ...
    client = HAClient(dir)            # discovers + follows the leader
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import List, Optional, Sequence

from paddle_tpu.master import Client, MasterRPCError, Server, Service

__all__ = ["LeaseFile", "HAMaster", "HAClient", "discover_endpoint"]

_log = logging.getLogger("paddle_tpu.master_ha")


class LeaseFile:
    """Heartbeat-lease leader election in a directory.

    The leader owns ``leader.lease`` and renews its mtime; a candidate may
    claim leadership only when the lease is missing or stale (now - mtime >
    lease_timeout).  Claims go through an exclusively-created claim file +
    atomic rename so two candidates racing for a stale lease cannot both
    win (the one whose rename lands second just overwrites with its own
    identity and the loser detects the foreign owner on verify).

    ``clock``/``sleep`` are injectable (the PR-5 injectable-sleep pattern):
    staleness is judged against ``clock()`` and every heartbeat/claim stamps
    the file's mtime from the same clock, so lease-expiry tests advance a
    fake clock instead of sleeping real wall time."""

    def __init__(
        self,
        dir_: str,
        owner_id: str,
        lease_timeout: float = 5.0,
        clock=time.time,
        sleep=time.sleep,
    ):
        self.dir = dir_
        self.owner_id = owner_id
        self.lease_timeout = lease_timeout
        self._clock = clock
        self._sleep = sleep
        self.path = os.path.join(dir_, "leader.lease")
        os.makedirs(dir_, exist_ok=True)

    # -- inspection ------------------------------------------------------
    def current_owner(self) -> Optional[str]:
        try:
            with open(self.path) as f:
                return json.load(f)["owner"]
        except (OSError, ValueError, KeyError):
            return None

    def is_stale(self) -> bool:
        try:
            return self._clock() - os.path.getmtime(self.path) > self.lease_timeout
        except OSError:
            return True  # missing == stale

    def held_by_me(self) -> bool:
        return self.current_owner() == self.owner_id and not self.is_stale()

    # -- campaign --------------------------------------------------------
    def try_acquire(self) -> bool:
        if not self.is_stale():
            return self.current_owner() == self.owner_id
        claim = os.path.join(self.dir, f".claim-{self.owner_id}")
        now = self._clock()
        with open(claim, "w") as f:
            json.dump({"owner": self.owner_id, "t": now}, f)
        os.utime(claim, (now, now))  # mtime from the SAME clock is_stale reads
        # Re-check right before the rename: a stalled-but-alive leader may
        # have renewed since our staleness read (shrinks the clobber window
        # to the check->rename gap; the remaining dual-leader window is
        # bounded by the deposed side's next renew(), which detects the
        # foreign owner and steps down — snapshot writes are fenced).
        if not self.is_stale():
            try:
                os.remove(claim)
            except OSError:
                pass
            return False
        os.replace(claim, self.path)
        # verify after the dust settles: a racing rename may have landed on
        # top of ours (last-writer-wins is exactly one winner)
        self._sleep(0.01)
        return self.current_owner() == self.owner_id

    def renew(self) -> bool:
        if self.current_owner() != self.owner_id:
            return False  # usurped (we were stale and someone claimed)
        from paddle_tpu.robustness import chaos as _chaos

        if _chaos.fire("stale_lease"):
            # chaos drill: the leader BELIEVES it renewed but the heartbeat
            # never reached shared storage (GC pause, NFS stall) — the lease
            # goes stale underneath it and a standby must take over while
            # this side detects the usurper and steps down
            return True
        now = self._clock()
        try:
            os.utime(self.path, (now, now))
        except OSError:
            return False  # lease file vanished under us: treat as usurped
        return True

    def release(self) -> None:
        if self.current_owner() == self.owner_id:
            try:
                os.remove(self.path)
            except OSError:
                pass


def _endpoint_path(dir_: str) -> str:
    return os.path.join(dir_, "endpoint.json")


def discover_endpoint(dir_: str) -> Optional[tuple]:
    """(host, port) of the current leader, or None (reference: clients
    watch the etcd master-addr key, etcd_client.go GetKey)."""
    try:
        with open(_endpoint_path(dir_)) as f:
            d = json.load(f)
        return (d["host"], d["port"])
    except (OSError, ValueError, KeyError):
        return None


class HAMaster:
    """One master candidate.  start() campaigns; the winner serves the
    task queues (recovering them from the shared snapshot), losers keep
    watching and take over when the lease goes stale."""

    def __init__(
        self,
        dir_: str,
        patterns: Sequence[str],
        owner_id: Optional[str] = None,
        lease_timeout: float = 5.0,
        renew_interval: Optional[float] = None,
        address=("127.0.0.1", 0),
        **service_kw,
    ):
        self.dir = dir_
        self.patterns = list(patterns)
        self.owner_id = owner_id or f"{os.uname().nodename}:{os.getpid()}"
        self.lease = LeaseFile(dir_, self.owner_id, lease_timeout)
        self.renew_interval = renew_interval or lease_timeout / 3.0
        self._address = address
        self._service_kw = dict(service_kw)
        self._service_kw.setdefault(
            "snapshot_path", os.path.join(dir_, "master_state.json")
        )
        self.service: Optional[Service] = None
        self.server: Optional[Server] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.is_leader = threading.Event()

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def wait_leader(self, timeout: Optional[float] = None) -> bool:
        return self.is_leader.wait(timeout)

    def _become_leader(self) -> None:
        # Recover the queues from the shared snapshot (a fresh cluster has
        # none; set_dataset is idempotent against recovered state).
        self.service = Service(**self._service_kw)
        self.service.set_dataset(self.patterns)
        self.server = Server(self.service, address=self._address)
        host, port = self.server.address
        tmp = _endpoint_path(self.dir) + f".{self.owner_id}"
        with open(tmp, "w") as f:
            json.dump({"host": host, "port": port, "owner": self.owner_id}, f)
        os.replace(tmp, _endpoint_path(self.dir))
        self.is_leader.set()

    def _step_down(self) -> None:
        self.is_leader.clear()
        if self.server is not None:
            self.server.close()  # stops accepting AND drops live conns
            self.server = None
        if self.service is not None:
            self.service.fence()  # never write the shared snapshot again
        self.service = None

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.is_leader.is_set():
                if not self.lease.renew():
                    self._step_down()  # usurped after a stall
                self._stop.wait(self.renew_interval)
            else:
                if self.lease.try_acquire():
                    try:
                        self._become_leader()
                    except Exception:
                        # corrupt snapshot / bind failure: surface it, give
                        # the lease back, keep campaigning after a backoff
                        _log.exception(
                            "master %s failed to assume leadership",
                            self.owner_id,
                        )
                        self._step_down()
                        self.lease.release()
                        self._stop.wait(self.lease.lease_timeout)
                else:
                    self._stop.wait(self.renew_interval)
        if self.is_leader.is_set():
            self._step_down()
            self.lease.release()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # test hook: simulate a crashed leader (no release, no renewals)
    def freeze(self) -> None:
        self._stop.set()
        self.is_leader.clear()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.server is not None:
            self.server.close()
            self.server = None


class HAClient:
    """Client that discovers the leader from the endpoint file and
    re-resolves + reconnects when the master fails over (the reference
    client watches etcd and reconnects, client.go)."""

    def __init__(self, dir_: str, timeout: float = 30.0, **client_kw):
        self.dir = dir_
        self.timeout = timeout
        self._client_kw = client_kw
        self._client: Optional[Client] = None
        self._endpoint = None

    def _connect(self) -> Client:
        deadline = time.time() + self.timeout
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            ep = discover_endpoint(self.dir)
            if ep is not None:
                try:
                    c = Client(ep, **self._client_kw)
                    self._endpoint = ep
                    return c
                except (ConnectionError, OSError) as e:
                    last_err = e
            time.sleep(0.1)
        raise TimeoutError(f"no master leader in {self.dir}: {last_err}")

    def _call(self, method, *args):
        deadline = time.time() + self.timeout
        while True:
            if self._client is None:
                self._client = self._connect()
            try:
                return getattr(self._client, method)(*args)
            except MasterRPCError:
                raise  # the master executed the call: a real app error
            except (ConnectionError, EOFError, OSError):
                # leader died mid-call: drop the connection, re-discover
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None
                if time.time() > deadline:
                    raise
                time.sleep(0.2)

    # -- surface (the Client subset trainers use) ------------------------
    def set_dataset(self, patterns):
        return self._call("set_dataset", patterns)

    def next_record(self):
        return self._call("next_record")

    def start_new_pass(self, target_pass=None):
        return self._call("start_new_pass", target_pass)

    def request_save_model(self, block_secs: float = 60.0):
        return self._call("request_save_model", block_secs)

    def __getattr__(self, name):
        """The elastic cluster surface (get_task, task_finished, registry,
        fences, pass_results, ...) delegates from ``master._METHODS`` with
        the reconnect-on-failover discipline of :meth:`_call` — mirrors
        Client.__getattr__, one definition for the whole surface."""
        from paddle_tpu.master import _METHODS

        if name in _METHODS:
            return lambda *args: self._call(name, *args)
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}"
        )

    def reader(self):
        from paddle_tpu.master import reader_over

        return reader_over(self.next_record)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
