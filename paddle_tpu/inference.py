"""paddle.infer / Inference — the v2 inference user surface (reference:
python/paddle/v2/inference.py:8-87; C ABI paddle/capi/gradient_machine.h:27-86).

The reference builds a testing-mode GradientMachine and feeds CSR arguments;
here the topology compiles to ONE jitted XLA forward (cached per batch
shape — the feeder's bucketed padding keeps the shape set small) and field
extraction unpads sequence outputs back to the reference's concatenated-rows
convention.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from paddle_tpu.core.batch import (
    DEFAULT_BATCH_LADDER,
    DEFAULT_LADDER,
    SeqTensor,
    ladder_len,
    pad_batch_rows,
    slice_batch_rows,
)
from paddle_tpu.core.compiler import CompiledNetwork, get_default_compute_dtype
from paddle_tpu.core.topology import LayerOutput, Topology

__all__ = ["infer", "Inference"]


def _extract_field(out: SeqTensor, field: str) -> np.ndarray:
    """reference forwardTest fields: 'value' (activations / scores) and 'id'
    (integer outputs).  Sequence outputs are unpadded to the reference's
    concatenated-valid-rows form; nested outputs concatenate both levels."""
    data = np.asarray(out.data)
    if field == "id":
        data = data.astype(np.int64)
    if not out.is_seq:
        return data
    lengths = np.asarray(out.lengths)
    rows: List[np.ndarray] = []
    if out.is_nested:
        sub_lengths = np.asarray(out.sub_lengths)
        for i in range(data.shape[0]):
            for j in range(int(lengths[i])):
                rows.append(data[i, j, : int(sub_lengths[i, j])])
    else:
        for i in range(data.shape[0]):
            rows.append(data[i, : int(lengths[i])])
    return np.concatenate(rows, axis=0) if rows else data[:0].reshape(0, *data.shape[2:])


class Inference:
    """Compiled inference over one or more output layers.

    ::

        inferer = Inference(output_layer=prediction, parameters=parameters)
        probs = inferer.infer(input=samples)
    """

    def __init__(
        self,
        output_layer: Union[LayerOutput, Sequence[LayerOutput]],
        parameters,
    ):
        outs = (
            list(output_layer)
            if isinstance(output_layer, (list, tuple))
            else [output_layer]
        )
        self.output_names = [o.name for o in outs]
        self.topology = Topology(outs)
        self.network = CompiledNetwork(
            self.topology, compute_dtype=get_default_compute_dtype()
        )
        if not hasattr(parameters, "network"):
            # topology-free bag from the static Parameters.from_tar(f):
            # build parameters for this inference topology, merge by name
            from paddle_tpu.parameters import create_from_network

            detached = parameters
            parameters = create_from_network(self.network, seed=0)
            detached.merge_into(parameters)
        # inherit the training network's mesh so mesh-aware layers (ring
        # attention) keep their parallelism at inference time
        self.network.mesh = getattr(parameters.network, "mesh", None)
        # Parameters may come from a larger (training) topology; apply() looks
        # up layers by name, so the superset simply carries unused entries.
        self._params = parameters.params
        self._state = parameters.state

        # distinct compiled variants this instance has traced — the
        # compile-count regression surface: with the batch-rung + sequence-
        # ladder canonicalization below, repeated infer() calls with varying
        # batch sizes/lengths stay bounded by the rungs they realize,
        # instead of retracing per distinct shape
        self.trace_count = 0

        def fwd(params, state, batch):
            self.trace_count += 1
            all_outs, _ = self.network.apply(params, batch, state=state, train=False)
            # Keep auxiliary side outputs of the selected layers too
            # ("<name>@scores" from beam_search, "<name>@cell" from lstm_step).
            keep = set(self.output_names)
            return {
                n: v
                for n, v in all_outs.items()
                if n in keep or n.split("@")[0] in keep
            }

        self._fwd = jax.jit(fwd)

    # ------------------------------------------------------------------
    def iter_infer(
        self,
        input: Sequence[Any],
        feeding=None,
        batch_size: Optional[int] = None,
    ):
        from paddle_tpu.reader.feeder import DataFeeder, feed_dtypes_of

        if not len(input):
            raise ValueError("infer() needs at least one input sample")
        # same wire dtypes as training (narrow uint8 feeds normalize on
        # device via the data layer's feed_scale/feed_shift) — a float-fed
        # batch would skip the on-device normalize and skew inference.
        # Sequence extents ride the canonical shape ladder and the BATCH
        # axis pads to a DEFAULT_BATCH_LADDER rung (dead rows sliced back
        # off every output), so repeated inference with ragged batch
        # sizes/lengths dispatches a BOUNDED set of compiled variants
        # (core/batch.py; `trace_count` counter-asserts it in tests).
        feeder = DataFeeder(
            self.topology.data_types(), feeding,
            feed_dtypes=feed_dtypes_of(self.topology),
            ladder=DEFAULT_LADDER,
        )
        # chunk at the top batch rung: an oversized batch runs as exact
        # full rungs + one padded remainder, instead of padding the whole
        # thing up to the next multiple of the top rung
        bs = min(batch_size or len(input), DEFAULT_BATCH_LADDER[-1])
        for lo in range(0, len(input), bs):
            rows = list(input[lo : lo + bs])
            batch = pad_batch_rows(
                feeder(rows), ladder_len(len(rows), DEFAULT_BATCH_LADDER)
            )
            outs = self._fwd(self._params, self._state, batch)
            yield slice_batch_rows(outs, len(rows))

    def iter_infer_field(self, field, **kwargs):
        fields = list(field) if isinstance(field, (list, tuple)) else [field]
        for result in self.iter_infer(**kwargs):
            yield [
                _extract_field(result[name], f)
                for name in self.output_names
                for f in fields
            ]

    def infer(
        self,
        input: Sequence[Any],
        field: Union[str, Sequence[str]] = "value",
        feeding=None,
        batch_size: Optional[int] = None,
    ):
        """Returns one ndarray per (output_layer × field), concatenated over
        batches; a single array when there is exactly one."""
        collected: Optional[List[List[np.ndarray]]] = None
        for res in self.iter_infer_field(
            field=field, input=input, feeding=feeding, batch_size=batch_size
        ):
            if collected is None:
                collected = [[] for _ in res]
            for i, item in enumerate(res):
                collected[i].append(item)
        assert collected, "empty input"
        merged = [np.concatenate(c, axis=0) for c in collected]
        return merged[0] if len(merged) == 1 else merged


def infer(output_layer, parameters, input, feeding=None, field="value",
          batch_size: Optional[int] = None):
    """One-shot inference (reference paddle.infer, v2/inference.py:87)."""
    return Inference(output_layer, parameters).infer(
        input=input, field=field, feeding=feeding, batch_size=batch_size
    )
