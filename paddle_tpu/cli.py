"""The ``paddle train`` command-line face.

The reference's primary entry point is a command line
(``paddle/trainer/TrainerMain.cpp:32-65``): ``paddle_trainer --config=...
--save_dir=... --num_passes=...`` wrapped by the ``paddle`` shell script
(``paddle/scripts/submit_local.sh.in``), with ``--job`` selecting
train / test / time / checkgrad (TrainerBenchmark.cpp:71 for ``time``).
This module is that face over the TPU-native stack: ``paddle-tpu train
--config=conf.py`` (or ``python -m paddle_tpu train ...``) runs any v1
config file unmodified — parse → compile → jitted-step pass loop, with
``pass-%05d/`` checkpoint dirs exactly like the reference trainer writes.

Flags mirror the reference gflags (Flags.cpp) in ``--name=value`` form;
argparse also accepts ``--name value``.
"""

from __future__ import annotations

import argparse
import json
import logging
import re
import os
import sys
import time
from typing import List, Optional

import numpy as np


def _build_train_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="paddle-tpu train",
        description="Train/test/time a v1 config file "
        "(reference paddle_trainer, TrainerMain.cpp).",
    )
    ap.add_argument("--config", required=True, help="v1 config file (.py/.conf)")
    ap.add_argument(
        "--config_args", default="",
        help="comma-separated key=value pairs passed to the config "
        "(get_config_arg)",
    )
    ap.add_argument(
        "--job", default="train",
        choices=["train", "test", "time", "checkgrad"],
        help="one of (train, test, time, checkgrad) — TrainerMain.cpp:51-62",
    )
    ap.add_argument("--save_dir", default=None, help="write pass-%%05d/ checkpoints here")
    ap.add_argument("--num_passes", type=int, default=1)
    ap.add_argument("--start_pass", type=int, default=0)
    ap.add_argument(
        "--init_model_path", default=None,
        help="load initial parameters from this pass dir (ParamUtil.cpp)",
    )
    ap.add_argument("--saving_period", type=int, default=1)
    ap.add_argument("--saving_period_by_batches", type=int, default=0)
    ap.add_argument("--batch_size", type=int, default=0,
                    help="override the config's settings(batch_size=...)")
    ap.add_argument("--log_period", type=int, default=None)
    ap.add_argument("--dot_period", type=int, default=1,
                    help="print a '.' every N batches (reference TrainerInternal)")
    ap.add_argument("--show_parameter_stats_period", type=int, default=None)
    ap.add_argument("--test_period", type=int, default=50,
                    help="--job=time: number of timed batches "
                    "(TrainerBenchmark.cpp:79)")
    ap.add_argument("--feed_data", action="store_true",
                    help="--job=time: refetch a fresh batch every timed step "
                    "instead of reusing one (TrainerBenchmark.cpp:80-83)")
    ap.add_argument("--seed", type=int, default=None)
    # accepted for surface compatibility; the platform comes from jax
    ap.add_argument("--use_tpu", type=_flag_bool, default=True, nargs="?", const=True)
    ap.add_argument("--use_gpu", type=_flag_bool, default=False, nargs="?", const=True)
    ap.add_argument("--trainer_count", type=int, default=1)
    ap.add_argument("--async_load_data", type=_flag_bool, default=True)
    ap.add_argument(
        "--cache_pass_in_mem", type=_flag_bool, default=False, nargs="?",
        const=True,
        help="device-resident pass cache: epoch 1 captures the staged "
        "batches on device, later epochs replay them with zero H2D "
        "traffic (the TPU-native CacheType.CACHE_PASS_IN_MEM; "
        "@provider(cache=...) configs enable this without the flag)",
    )
    ap.add_argument(
        "--data_echo_factor", type=int, default=None,
        help="train each epoch-1 batch N times (data echo) to amortize "
        "its host->device transfer; needs the pass cache enabled",
    )
    ap.add_argument(
        "--aot_cache_dir", default=None,
        help="persistent AOT executable cache (core/aot_cache.py): warm "
        "boots deserialize compiled train-step/epoch-program executables "
        "from here instead of retracing; prewarm with `paddle-tpu cache "
        "warm`",
    )
    ap.add_argument(
        "--whole_pass_program", type=_flag_bool, default=False, nargs="?",
        const=True,
        help="run cached epochs >= 2 as ONE on-device lax.scan program "
        "over the stacked pass cache (O(1) host dispatches per epoch, "
        "bit-exact vs stepwise); needs --cache_pass_in_mem",
    )
    ap.add_argument(
        "--checkpoint_dir", default=None,
        help="fault-tolerance plane (robustness/): write full-state "
        "checkpoints (params + optimizer state + RNG + pass/batch "
        "position) here every --checkpoint_period_batches batches and at "
        "pass boundaries; enables divergence auto-rollback and "
        "preemption-safe shutdown (SIGTERM -> final checkpoint + "
        "PREEMPTED marker)",
    )
    ap.add_argument(
        "--checkpoint_period_batches", type=int, default=None,
        help="full-state checkpoint cadence in batches (default: the "
        "checkpoint_period_batches flag); each checkpoint is the rollback "
        "anchor and the kill -9 resume point",
    )
    ap.add_argument(
        "--resume", action="store_true",
        help="restore the latest good checkpoint from --checkpoint_dir "
        "(walking past torn ones) and continue mid-pass where the "
        "interrupted run stopped",
    )
    ap.add_argument(
        "--chaos", default=None,
        help="arm chaos fault points, e.g. 'nan_batch@5,kill@12' "
        "(robustness/chaos.py; testing only)",
    )
    return ap


def _flag_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "yes")


def _echo(msg: str) -> None:
    print(msg, flush=True)


def _load_init_model(trainer, path: str) -> None:
    """--init_model_path: a pass dir (params.tar and/or v1 per-parameter
    binaries), a merged-model bundle (merge_model output), or a bare
    params.tar."""
    import tarfile

    from paddle_tpu import checkpoint as ckpt

    if os.path.isdir(path):
        ckpt.load_parameter_dir(trainer.parameters, path)
    else:
        # a merge_model bundle is a tar with a manifest + nested params.tar;
        # a bare params.tar has no manifest
        is_bundle = False
        try:
            with tarfile.open(path, "r:*") as tf:
                is_bundle = any(
                    m.name.endswith("manifest.json") for m in tf.getmembers()
                )
        except tarfile.ReadError:
            pass
        if is_bundle:
            from paddle_tpu.utils.model_tools import load_merged_model

            load_merged_model(path, trainer.parameters)
        else:
            with open(path, "rb") as f:
                trainer.parameters.from_tar(f)
    trainer._reshard_after_restore()


def _make_trainer(parsed, seed: int):
    from paddle_tpu import parameters as v2_params
    from paddle_tpu import trainer as v2_trainer
    from paddle_tpu.v1_compat import make_optimizer

    params = v2_params.create(parsed.topology, seed=seed)
    return v2_trainer.SGD(
        cost=parsed.topology,
        parameters=params,
        update_equation=make_optimizer(parsed.settings),
        evaluators=list(parsed.evaluators),
        seed=seed,
    )


# The reference trainer's registered gflags this CLI doesn't implement
# (paddle/utils/Flags.cpp + paddle/trainer/*.cpp DEFINE_*): a train.sh line
# that works against paddle_trainer must not die here — these specific names
# are accepted-and-ignored with a note.  Anything NOT in this set (typos,
# stray tokens) stays a hard error.
_IGNORED_REFERENCE_FLAGS = {
    "average_test_period", "beam_size", "checkgrad_eps", "comment",
    "distribute_test", "enable_parallel_vector", "gpu_id",
    "load_missing_parameter_strategy", "loadsave_parameters_in_pserver",
    "local", "log_period_server", "nics", "num_gradient_servers",
    "parallel_nn", "port", "ports_num", "ports_num_for_sparse",
    "prev_batch_state", "rdma_tcp", "save_only_one", "show_layer_stat",
    "start_pserver", "test_all_data_in_one_period", "test_pass",
    "test_wait", "trainer_id", "use_old_updater", "with_cost",
}


# the subset of ignored flags that take a VALUE (gflags string/int/double
# definitions per the reference Flags.cpp/trainer flags) — only these may
# consume a separate following token; the boolean remainder never does.
# NB test_wait and enable_parallel_vector LOOK boolean but are DEFINE_int32
# (Trainer.cpp:70, Flags.cpp:62).
_VALUE_REFERENCE_FLAGS = {
    "average_test_period", "beam_size", "checkgrad_eps", "comment",
    "enable_parallel_vector", "gpu_id", "load_missing_parameter_strategy",
    "log_period_server", "nics", "num_gradient_servers", "port",
    "ports_num", "ports_num_for_sparse", "rdma_tcp", "test_pass",
    "test_wait", "trainer_id",
}


def _ignored_flag_name(token: str):
    """The _IGNORED_REFERENCE_FLAGS entry this token spells, or None.
    Accepts --name, --name=value, and the gflags --no<bool> negation."""
    if not token.startswith("-"):
        return None
    name = token.lstrip("-").split("=", 1)[0]
    if name in _IGNORED_REFERENCE_FLAGS:
        return name
    if name.startswith("no") and name[2:] in _IGNORED_REFERENCE_FLAGS:
        return name[2:]
    return None


def cmd_train(argv: List[str]) -> int:
    args, unknown = _build_train_parser().parse_known_args(argv)
    ignored, fatal = [], []
    i = 0
    while i < len(unknown):
        u = unknown[i]
        name = _ignored_flag_name(u)
        if name is not None:
            ignored.append(u)
            # gflags separate-value form (`--gpu_id -1`, `--nics eth0`):
            # only VALUE-taking flags consume the next token, and only when
            # the value wasn't already attached with '='.  The token must
            # neither be a key=value (a stray `batch_size=32` after a
            # boolean stays fatal) nor LOOK like a flag itself (`--nics
            # --nolocall` must not eat the typo) — negative numbers like
            # `-1` are values, dash-then-letter is a flag.
            nxt = unknown[i + 1] if i + 1 < len(unknown) else None
            looks_like_flag = bool(
                nxt and re.match(r"--?[A-Za-z]", nxt)
            )
            if (
                "=" not in u
                and not u.lstrip("-").startswith("no")
                and name in _VALUE_REFERENCE_FLAGS
                and nxt is not None
                and "=" not in nxt
                and not looks_like_flag
            ):
                ignored.append(nxt)
                i += 1
        else:
            fatal.append(u)
        i += 1
    if ignored:
        print(
            f"note: ignoring reference trainer flags {ignored}",
            file=sys.stderr,
        )
    if fatal:
        print(
            f"error: unrecognized arguments {fatal} (not reference trainer "
            "flags; see `paddle-tpu train --help`)",
            file=sys.stderr,
        )
        return 2
    from paddle_tpu import event as v2_event
    from paddle_tpu import minibatch
    from paddle_tpu.utils import flags as _flags
    from paddle_tpu.v1_compat import make_config_reader, parse_config

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    from paddle_tpu import obs as _obs

    _obs.tracer.configure(role="trainer")
    if args.log_period is not None:
        _flags.set_flag("log_period", args.log_period)
    if args.show_parameter_stats_period is not None:
        _flags.set_flag(
            "show_parameter_stats_period", args.show_parameter_stats_period
        )
    if args.seed is not None:
        _flags.set_flag("seed", args.seed)
    if args.cache_pass_in_mem:
        _flags.set_flag("cache_pass_in_mem", True)
    if args.data_echo_factor is not None:
        _flags.set_flag("data_echo_factor", args.data_echo_factor)
    if args.aot_cache_dir:
        _flags.set_flag("aot_cache_dir", args.aot_cache_dir)
    if args.whole_pass_program:
        _flags.set_flag("whole_pass_program", True)
    if args.chaos:
        from paddle_tpu.robustness import chaos as _chaos

        _chaos.arm(args.chaos)
    _flags.set_flag("trainer_count", args.trainer_count)
    seed = _flags.get_flag("seed")

    config_path = os.path.abspath(args.config)
    config_dir = os.path.dirname(config_path)
    parsed = parse_config(config_path, args.config_args)
    if args.batch_size:
        # write the override back BEFORE building the optimizer: the
        # 'manual' LR schedule converts its sample boundaries through
        # settings.batch_size (reference numSamplesProcessed counts real
        # samples)
        parsed.settings.batch_size = args.batch_size
    batch_size = parsed.settings.batch_size
    trainer = _make_trainer(parsed, seed)

    if args.init_model_path:
        _load_init_model(trainer, args.init_model_path)
    elif args.start_pass > 0 and args.save_dir:
        # resume from the last completed pass (reference ParamUtil
        # loadParametersWithPath from save_dir/pass-%05d)
        trainer.load_pass(args.save_dir, args.start_pass - 1)

    if args.job == "train":
        return _job_train(args, parsed, trainer, batch_size, config_dir, v2_event, minibatch, make_config_reader)
    if args.job == "test":
        return _job_test(args, parsed, trainer, batch_size, config_dir, minibatch, make_config_reader)
    if args.job == "time":
        return _job_time(args, parsed, trainer, batch_size, config_dir, minibatch, make_config_reader)
    if args.job == "checkgrad":
        return _job_checkgrad(args, parsed, trainer, batch_size, config_dir, minibatch, make_config_reader)
    raise AssertionError(args.job)


def _job_train(args, parsed, trainer, batch_size, config_dir,
               v2_event, minibatch, make_config_reader) -> int:
    # batching honors the bucketing flags (use_bucketing /
    # bucketing_token_budget): reference configs get length-bucketed
    # token-budget feeding with zero config edits
    from paddle_tpu.v1_compat import make_batched_reader
    test_reader = None
    has_test = (
        parsed.test_data is not None
        or (parsed.data_sources is not None and parsed.data_sources.test_list)
    )
    if has_test:
        try:
            test_reader = make_config_reader(parsed, config_dir, train=False)
        except (ValueError, FileNotFoundError) as e:
            _echo(f"test data declared but unavailable ({e}); skipping eval")

    dot = max(args.dot_period, 0)
    t0 = time.time()

    def handler(ev) -> None:
        if isinstance(ev, v2_event.EndIteration):
            if dot and (ev.batch_id + 1) % dot == 0:
                sys.stdout.write(".")
                sys.stdout.flush()
        elif isinstance(ev, v2_event.EndPass):
            sys.stdout.write("\n")
            _echo(
                f"Pass {ev.pass_id}: mean cost "
                f"{ev.evaluator.get('mean_cost', float('nan')):.6f} "
                f"({time.time() - t0:.1f}s elapsed)"
            )
            for k, v in sorted(ev.evaluator.items()):
                if k != "mean_cost":
                    _echo(f"  {k} = {v}")
            if test_reader is not None:
                res = trainer.test(
                    reader=minibatch.batch(test_reader, batch_size),
                    feeding=parsed.feeding,
                )
                _echo(f"Test with Pass {ev.pass_id}: cost {res.cost:.6f}")
                for k, v in sorted(res.metrics.items()):
                    _echo(f"  {k} = {v}")

    trainer.train(
        reader=make_batched_reader(parsed, config_dir, batch_size, train=True),
        num_passes=args.num_passes,
        event_handler=handler,
        feeding=parsed.feeding,
        save_dir=args.save_dir,
        saving_period=args.saving_period,
        saving_period_by_batches=args.saving_period_by_batches or None,
        start_pass=args.start_pass,
        async_load_data=args.async_load_data,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_period_batches=args.checkpoint_period_batches,
        resume=args.resume,
    )
    if getattr(trainer, "preempted", False):
        _echo(
            f"PREEMPTED: state checkpointed under {args.checkpoint_dir}; "
            "restart with --resume to continue"
        )
        return 75  # EX_TEMPFAIL: restart me
    return 0


def _job_test(args, parsed, trainer, batch_size, config_dir,
              minibatch, make_config_reader) -> int:
    """--job=test (reference Tester.cpp): evaluate the loaded model on the
    config's test stream (train stream when no test stream is declared)."""
    try:
        reader = make_config_reader(parsed, config_dir, train=False)
    except (ValueError, FileNotFoundError):
        _echo("no test data declared; evaluating on the train stream")
        reader = make_config_reader(parsed, config_dir, train=True)
    res = trainer.test(
        reader=minibatch.batch(reader, batch_size), feeding=parsed.feeding
    )
    _echo(f"Test cost {res.cost:.6f}")
    for k, v in sorted(res.metrics.items()):
        _echo(f"  {k} = {v}")
    return 0


def _job_time(args, parsed, trainer, batch_size, config_dir,
              minibatch, make_config_reader) -> int:
    """--job=time (TrainerBenchmark.cpp:30-90): 10 burn-in steps on one
    batch, then ``--test_period`` timed steps; prints the StatSet table the
    reference prints via globalStat.printSegTimerStatus()."""
    import jax

    from paddle_tpu.parallel.mesh import shard_batch
    from paddle_tpu.utils.timers import global_stats, stat_timer

    from paddle_tpu.v1_compat import make_batched_reader

    # honors use_bucketing: --job=time measures the bucketed feed when the
    # flag is on (the per-bucket dispatch counters land in the StatSet table
    # this job prints)
    batch_reader = make_batched_reader(parsed, config_dir, batch_size, train=True)
    batches = batch_reader()
    feeder = trainer._make_feeder(parsed.feeding)

    def next_batch():
        nonlocal batches
        with stat_timer("GetData"):
            try:
                raw = next(batches)
            except StopIteration:
                batches = batch_reader()
                raw = next(batches)
            return shard_batch(feeder(raw), trainer.mesh)

    # --cache_pass_in_mem (or a CACHE_PASS_IN_MEM provider): stage the timed
    # batches once, seal the device-resident cache, and feed every timed
    # step from its replay — the timing then measures the compute-bound
    # cached-epoch regime instead of the H2D wire
    from paddle_tpu.utils.flags import get_flag as _get_flag

    cached_iter = None
    if _get_flag("cache_pass_in_mem") or getattr(
        batch_reader, "cache_pass_in_mem", False
    ):
        from paddle_tpu.reader.pass_cache import PassCache

        # timing feed: no echo (every timed step must be a distinct
        # dispatch); shuffle/budget/seed follow the shared flag contract
        cache = PassCache.from_flags(batch_reader, echo_factor=1)
        # stage at most ONE pass (never wrap the reader around: re-staged
        # duplicates would multiply the pass's real HBM cost), capped at
        # the timed-step count
        for raw in batch_reader():
            with stat_timer("GetData"):
                cache.observe(shard_batch(feeder(raw), trainer.mesh))
            if not cache.active or cache.n_batches >= max(args.test_period, 1):
                break
        cache.seal()
        if cache.ready:
            cached_iter = cache.stream()
            _echo(f"pass cache: {cache.summary()}")

    batch = next(cached_iter) if cached_iter is not None else next_batch()
    params, state = trainer.parameters.params, trainer.parameters.state
    opt_state = trainer._opt_state
    rng = jax.random.PRNGKey(0)

    def one_step(params, state, opt_state, batch, rng):
        rng, step_rng = jax.random.split(rng)
        params, state, opt_state, metrics = trainer._train_step(
            params, state, opt_state, batch, step_rng
        )
        return params, state, opt_state, metrics, rng

    _echo("Burning time...")
    for _ in range(10):
        params, state, opt_state, metrics, rng = one_step(
            params, state, opt_state, batch, rng
        )
    # host sync before the clock starts (axon returns early from
    # block_until_ready; a host fetch is the reliable barrier)
    float(np.asarray(metrics["cost"]))
    _echo("Burning time end.")

    n = 0
    t0 = time.time()
    for _ in range(max(args.test_period, 1)):
        if args.feed_data:
            batch = (
                next(cached_iter) if cached_iter is not None else next_batch()
            )
        with stat_timer("FwdBwd"):
            params, state, opt_state, metrics, rng = one_step(
                params, state, opt_state, batch, rng
            )
        n += 1
    float(np.asarray(metrics["cost"]))
    dt = time.time() - t0
    global_stats.print_all_status()  # prints the StatSet table itself
    _echo(
        f"{n} batches of {batch_size}: {dt * 1000 / n:.3f} ms/batch, "
        f"{n * batch_size / dt:.1f} samples/sec"
    )
    global_stats.reset()
    return 0


def _job_checkgrad(args, parsed, trainer, batch_size, config_dir,
                   minibatch, make_config_reader) -> int:
    """--job=checkgrad (Trainer::checkGradient, Trainer.cpp): compare the
    VJP gradient of the total cost against a central finite difference of
    the directional derivative, per parameter tensor.  Runs the graph in
    float64 — the reference gets its fd accuracy from the double-precision
    build (WITH_DOUBLE); in f32 the forward noise (~1e-4 relative for an
    800-wide MLP) swamps any usable eps."""
    import jax

    jax.config.update("jax_enable_x64", True)
    from paddle_tpu.parallel.mesh import shard_batch

    reader = make_config_reader(parsed, config_dir, train=True)
    raw = next(minibatch.batch(reader, min(batch_size, 8))())
    feeder = trainer._make_feeder(parsed.feeding)
    batch = shard_batch(feeder(raw), trainer.mesh)

    def _f64(x):
        arr = np.asarray(x)
        return arr.astype(np.float64) if np.issubdtype(arr.dtype, np.floating) else arr

    batch = jax.tree.map(_f64, batch)
    net = trainer.network
    state = trainer.parameters.state
    rng = jax.random.PRNGKey(0)
    out_names = list(net.topology.output_names)

    def total_cost(params):
        outs, _ = net.apply(params, batch, state=state, train=True, rng=rng)
        total = 0.0
        for name in out_names:
            v = outs[name]
            arr = v.data if hasattr(v, "data") else v
            total = total + arr.astype("float64").mean()
        return total

    def loss(params) -> float:
        return float(np.asarray(total_cost(params)))

    base = jax.tree.map(_f64, trainer.parameters.params)
    grads = jax.grad(total_cost)(base)

    # Directional derivative per parameter tensor, the reference's scheme
    # (perturb the whole parameter by a random delta, compare the cost
    # change against <grad, delta>).
    rng_np = np.random.RandomState(0)
    worst = 0.0
    failed = []
    eps = 1e-5
    for pname, g in sorted(grads.items()):
        for wname, gval in sorted(g.items()):
            gval = np.asarray(gval, np.float64)
            w0 = np.asarray(base[pname][wname], np.float64)
            d = rng_np.standard_normal(w0.shape)
            d /= max(np.linalg.norm(d), 1e-12)
            pert = dict(base)
            pert[pname] = dict(base[pname])
            pert[pname][wname] = w0 + eps * d
            lp = loss(pert)
            pert[pname][wname] = w0 - eps * d
            lm = loss(pert)
            fd = (lp - lm) / (2 * eps)
            an = float((gval * d).sum())
            denom = max(abs(fd), abs(an), 1e-8)
            rel = abs(fd - an) / denom
            worst = max(worst, rel)
            if rel > 1e-3:
                failed.append((f"{pname}.{wname}", an, fd, rel))
    if failed:
        for name, an, fd, rel in failed:
            _echo(f"FAIL {name}: analytic {an:.6g} vs fd {fd:.6g} (rel {rel:.3g})")
        return 1
    _echo(f"checkgrad PASSED ({len(grads)} parameters, worst rel err {worst:.3g})")
    return 0


# ---------------------------------------------------------------------------
# non-train subcommands (submit_local.sh.in:114-135)
# ---------------------------------------------------------------------------

def cmd_version(argv: List[str]) -> int:
    import jax

    import paddle_tpu

    print(f"paddle-tpu {paddle_tpu.__version__}, running on")
    print(f"    jax: {jax.__version__}")
    try:
        devs = jax.devices()
        print(f"    devices: {[str(d) for d in devs]}")
    except RuntimeError as e:
        print(f"    devices: unavailable ({e})")
    return 0


def cmd_dump_config(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(prog="paddle-tpu dump_config")
    ap.add_argument("config")
    ap.add_argument("--config_args", default="")
    args = ap.parse_args(argv)
    from paddle_tpu.utils.model_tools import dump_config

    print(dump_config(args.config, args.config_args))
    return 0


def cmd_make_diagram(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(prog="paddle-tpu make_diagram")
    ap.add_argument("config")
    ap.add_argument("dot_file")
    ap.add_argument("--config_args", default="")
    args = ap.parse_args(argv)
    from paddle_tpu.utils.model_tools import make_diagram
    from paddle_tpu.v1_compat import parse_config

    parsed = parse_config(os.path.abspath(args.config), args.config_args)
    make_diagram(parsed.topology, args.dot_file)
    print(f"wrote {args.dot_file}")
    return 0


def cmd_merge_model(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(prog="paddle-tpu merge_model")
    ap.add_argument("--model_dir", required=True, help="a pass-%%05d dir")
    ap.add_argument("--config_file", required=True)
    ap.add_argument("--model_file", required=True, help="output bundle path")
    ap.add_argument("--config_args", default="")
    args = ap.parse_args(argv)
    from paddle_tpu import checkpoint as ckpt
    from paddle_tpu import parameters as v2_params
    from paddle_tpu.utils.model_tools import merge_model
    from paddle_tpu.v1_compat import parse_config

    parsed = parse_config(os.path.abspath(args.config_file), args.config_args)
    params = v2_params.create(parsed.topology)
    ckpt.load_parameter_dir(params, args.model_dir)
    merge_model(params, args.model_file)
    print(f"wrote {args.model_file}")
    return 0


def cmd_plotcurve(argv: List[str]) -> int:
    from paddle_tpu.utils.plotcurve import main as plot_main

    return plot_main(argv)


def cmd_serve(argv: List[str]) -> int:
    """``paddle-tpu serve`` — the TPU-native serving plane over the NMT
    flagship (serving/): request queue + continuous batching + block-paged
    decode cache, with the production SLO surface (deadlines, bounded
    queue, shedding, chunked prefill).  Requests come from ``--requests``
    (one line of space-separated source token ids each) or ``--synthetic
    N``; arrivals follow the open-loop generator at ``--rate`` req/s.
    Prints one JSON line per completed request and a final summary line
    with the DISJOINT status ledger (served / shed / rejected / timeout /
    unfinished — the Gemma-on-TPU serving metric set plus the overload
    taxonomy).  SIGTERM drains gracefully: stop admitting, finish every
    in-flight request, exit 0 (the PreemptionGuard contract the trainer
    already honors); a second signal still kills."""
    import json as _json
    import time as _time

    ap = argparse.ArgumentParser(
        prog="paddle-tpu serve",
        description="continuous-batching serving plane (serving/engine.py)",
    )
    ap.add_argument("--model", default="",
                    help="trained parameter tar (paddle-tpu train "
                    "--save_dir output); random seeded weights when empty")
    ap.add_argument("--src-vocab", type=int, default=1000)
    ap.add_argument("--trg-vocab", type=int, default=1000)
    ap.add_argument("--word-dim", type=int, default=128)
    ap.add_argument("--hidden-dim", type=int, default=128)
    ap.add_argument("--max-length", type=int, default=32,
                    help="compiled decode ceiling (Seq2SeqGenerator)")
    ap.add_argument("--max-new-tokens", type=int, default=None)
    ap.add_argument("--max-slots", type=int, default=None)
    ap.add_argument("--hbm-budget-mb", type=int, default=None)
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="per-request end-to-end deadline; infeasible "
                    "requests are SHED at admission (default: the "
                    "serving_default_deadline_s flag; 0 = none)")
    ap.add_argument("--queue-limit", type=int, default=None,
                    help="bound on queued-ahead-of-admission requests; "
                    "beyond it submits are REJECTED immediately (default: "
                    "the serving_queue_limit flag; 0 = unbounded)")
    ap.add_argument("--prefill-chunk-tokens", type=int, default=None,
                    help="chunked prefill bound (default: the "
                    "serving_prefill_chunk_tokens flag; 0 = whole-prompt "
                    "prefill)")
    ap.add_argument("--prefix-cache", action="store_true", default=None,
                    help="arm copy-on-write prompt-prefix sharing (default: "
                    "the serving_prefix_cache flag)")
    ap.add_argument("--spec-decode", action="store_true", default=None,
                    help="arm n-gram speculative decoding (default: the "
                    "serving_spec_decode flag)")
    ap.add_argument("--drain-timeout-s", type=float, default=60.0,
                    help="graceful-drain budget after SIGTERM/SIGINT")
    ap.add_argument("--requests", default="",
                    help="file of requests (space-separated src ids/line)")
    ap.add_argument("--synthetic", type=int, default=16,
                    help="generate N random requests when --requests is empty")
    ap.add_argument("--prefix-pool", type=int, default=0,
                    help="share prompt prefixes across synthetic requests: "
                    "draw from a seeded pool of N prefixes "
                    "(reader/loadgen.PrefixMixer) — the realistic workload "
                    "for the serving_prefix_cache COW sharing path; 0 = "
                    "fully independent prompts")
    ap.add_argument("--prefix-frac", type=float, default=0.5,
                    help="fraction of synthetic requests that start with a "
                    "pool prefix (only with --prefix-pool)")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate (req/s); 0 = submit all "
                    "immediately")
    ap.add_argument("--arrival", default="poisson",
                    choices=["poisson", "uniform", "burst"],
                    help="open-loop arrival process (reader/loadgen.py)")
    ap.add_argument("--sessions", type=int, default=0,
                    help="stamp synthetic requests with session ids drawn "
                    "from a pool of N sessions (PrefixMixer.session_of — "
                    "the fleet router's affinity key); 0 = session-less")
    ap.add_argument("--priority-every", type=int, default=0,
                    help="stamp every Nth request interactive class p0 and "
                    "the rest batch class p2 (per-class SLO admission, "
                    "serving/scheduler.py); 0 = everything default class p1")
    ap.add_argument("--record-trace", default="", metavar="TRACE",
                    help="record the offered workload to a replayable "
                    ".ptt request-lifecycle trace (robustness/traces.py): "
                    "arrival offsets, ids, full source ids, deadlines, "
                    "sessions, priority classes")
    ap.add_argument("--replay", default="", metavar="TRACE",
                    help="REPLAY a recorded .ptt trace instead of offering "
                    "synthetic load: the recorded arrival clock, prompts, "
                    "ids, deadlines, sessions and priorities are "
                    "reproduced bit-for-bit (--synthetic/--rate/--arrival "
                    "are ignored)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--register", default="",
                    help="run as a FLEET ENGINE: register with the router "
                    "at host:port (serving/router.py) and serve requests "
                    "over the typed wire RPC instead of a local workload; "
                    "SIGTERM drains and deregisters")
    ap.add_argument("--engine-id", default="",
                    help="engine identity on the router's lease plane "
                    "(default: engine-<pid>; only with --register)")
    ap.add_argument("--engine-port", type=int, default=0,
                    help="data-plane listen port (0 = ephemeral; only "
                    "with --register)")
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--stats-out", default="",
                    help="write the summary JSON here too")
    ap.add_argument("--trace-dir", default=None,
                    help="arm Chrome-trace span export to this directory "
                    "(default: the trace_dir flag / PADDLE_TPU_TRACE_DIR)")
    ap.add_argument("--metrics-out", default=None,
                    help="periodic Prometheus-text metrics snapshot file "
                    "(obs/metrics.py; default: the metrics_out flag)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics on http://127.0.0.1:<port> "
                    "(default: the metrics_port flag; 0 = off)")
    args = ap.parse_args(argv)

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import obs as _obs

    _obs.tracer.configure(role="serve", trace_dir=args.trace_dir)
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen
    from paddle_tpu.robustness.preemption import PreemptionGuard
    from paddle_tpu.serving import Request, ServingEngine, ServingScheduler

    reset_auto_names()
    cost, _ = seq2seq_cost(
        args.src_vocab, args.trg_vocab,
        word_dim=args.word_dim, hidden_dim=args.hidden_dim,
    )
    params = paddle.parameters.create(cost, seed=args.seed)
    if args.model:
        with open(args.model, "rb") as f:
            params.init_from_tar(f)
    gen = Seq2SeqGenerator(
        params, args.src_vocab, args.trg_vocab,
        word_dim=args.word_dim, hidden_dim=args.hidden_dim,
        max_length=args.max_length,
    )
    engine = ServingEngine(
        gen,
        max_slots=args.max_slots,
        hbm_budget_mb=args.hbm_budget_mb,
        max_new_tokens=args.max_new_tokens,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        prefix_cache=args.prefix_cache,
        spec_decode=args.spec_decode,
    )

    if args.register:
        return _serve_as_fleet_engine(args, engine)

    session_of = None
    replay_trace = None
    sources = []
    if args.replay:
        # the recorded day IS the workload: prompts/ids/deadlines/
        # sessions/priorities all come from the trace records
        from paddle_tpu.robustness.traces import read_trace

        replay_trace = read_trace(args.replay)
    elif args.requests:
        with open(args.requests) as f:
            sources = [
                [int(t) for t in line.split()] for line in f if line.strip()
            ]
    elif args.prefix_pool > 0:
        from paddle_tpu.reader.loadgen import PrefixMixer

        mixer = PrefixMixer(
            args.src_vocab, pool_size=args.prefix_pool,
            prefix_frac=args.prefix_frac, seed=args.seed,
            sessions=args.sessions,
        )
        sources = [mixer.source(i) for i in range(args.synthetic)]
        if args.sessions > 0:
            session_of = mixer.session_of
    else:
        rng = np.random.RandomState(args.seed)
        sources = [
            rng.randint(2, args.src_vocab, size=rng.randint(3, 24)).tolist()
            for _ in range(args.synthetic)
        ]
    if args.sessions > 0 and session_of is None and replay_trace is None:
        # no prefix pool to correlate with: sessions spread round-robin
        session_of = lambda i: f"sess{i % args.sessions}"  # noqa: E731
    priority_of = None
    if args.priority_every > 0 and replay_trace is None:
        priority_of = (
            lambda i: 0 if i % args.priority_every == 0 else 2
        )

    done = []

    def on_done(r):
        done.append(r)
        print(_json.dumps({
            "req": r.req_id,
            "status": r.status,
            "tokens": r.tokens,
            "error": r.error,
            "latency_ms": round((r.t_done - r.t_submit) * 1e3, 3),
        }), flush=True)

    deadline_s = args.deadline_s
    if replay_trace is not None:
        # replay: every request carries the RECORDED identity — ids,
        # deadlines, sessions, priority classes.  The live flags must
        # not re-derive any of it (the loadgen's stamp-if-absent
        # contract keeps recorded values authoritative).
        reqs = [
            Request(
                list(rec["src"]), rec.get("mnt"),
                req_id=str(rec["id"]), callback=on_done,
                deadline_s=rec.get("dl"), session_id=rec.get("sess"),
                priority=rec.get("prio"),
            )
            for rec in replay_trace.requests()
        ]
    else:
        reqs = [
            Request(src, callback=on_done, deadline_s=deadline_s)
            for src in sources
        ]
    drained_clean = None
    t0 = _time.perf_counter()
    # live metrics export (obs/metrics.py): the SLO gauges the scheduler
    # registers (queue depth, pages in use, predicted wait) + the StatSet
    # ledger, as Prometheus text — file snapshot and/or localhost endpoint
    from paddle_tpu.obs.metrics import MetricsExporter
    from paddle_tpu.utils import flags as _serve_flags

    # --metrics-port 0 forces the endpoint OFF even when the metrics_port
    # flag/env is set (the help's "0 = off"); unset falls through to the
    # flag; a positive port wins outright
    metrics = MetricsExporter(
        path=args.metrics_out,
        port=(None if args.metrics_port is None
              else (args.metrics_port if args.metrics_port > 0 else -1)),
    ) if (
        args.metrics_out or args.metrics_port
        or _serve_flags.get_flag("metrics_out")
        or _serve_flags.get_flag("metrics_port")
    ) else None
    if metrics is not None and metrics.port:
        _echo(f"metrics: http://127.0.0.1:{metrics.port}/metrics")
    writer = None
    if args.record_trace:
        from paddle_tpu.robustness.traces import TraceWriter

        writer = TraceWriter(args.record_trace, meta={
            "cmd": "serve", "seed": args.seed, "rate": args.rate,
            "arrival": args.arrival,
        })
    with PreemptionGuard() as guard:
        sched = ServingScheduler(
            engine, queue_limit=args.queue_limit,
            default_deadline_s=(
                args.deadline_s if args.deadline_s is not None else None
            ),
        )

        def _submit(r):
            # record AFTER the loadgen stamped deadline/session/priority
            # (run() stamps before calling submit), so the trace carries
            # the values the scheduler actually saw
            if writer is not None:
                writer.record_request(r)
            return sched.submit(r)

        try:
            submitted = []
            if replay_trace is not None:
                from paddle_tpu.robustness.traces import TraceReplayLoadGen

                it = iter(reqs)
                submitted = TraceReplayLoadGen(
                    replay_trace,
                    request_factory=lambda rec: next(it),
                ).run(
                    _submit, stop=lambda: guard.triggered,
                    cancel=lambda rid, reason: sched.cancel(
                        rid, reason or "timeout: canceled"),
                )
            elif args.rate > 0:
                submitted = OpenLoopLoadGen(
                    args.rate, len(reqs), lambda i: reqs[i],
                    seed=args.seed, process=args.arrival,
                    session_of=session_of, priority_of=priority_of,
                ).run(_submit, stop=lambda: guard.triggered)
            else:
                for i, r in enumerate(reqs):
                    if guard.triggered:
                        break
                    if session_of is not None:
                        r.session_id = session_of(i)
                    if priority_of is not None:
                        pri = priority_of(i)
                        if pri is not None:
                            r.priority = int(pri)
                    _submit(r)
                    submitted.append(r)
            if guard.triggered:
                # graceful drain: stop admitting, finish what's in flight,
                # leave the untransmitted tail of the schedule unsubmitted
                _echo("draining: SIGTERM/SIGINT — finishing in-flight "
                      f"requests ({len(submitted)} submitted)")
                drained_clean = sched.drain(args.drain_timeout_s)
                reqs = list(submitted)
            else:
                wait_deadline = _time.perf_counter() + args.timeout_s
                for r in reqs:
                    # bounded poll; past the deadline, done() costs zero per
                    # remaining request instead of a full wait() quantum
                    while not r.done():
                        if guard.triggered or (
                            _time.perf_counter() > wait_deadline
                        ):
                            break
                        r.wait(0.2)
                    if guard.triggered:
                        break
                if guard.triggered:
                    drained_clean = sched.drain(args.drain_timeout_s)
        finally:
            sched.close()
            if writer is not None:
                writer.close()
            if metrics is not None:
                metrics.close()
    from paddle_tpu.serving import percentile, status_counts

    # the status ledger is judged AFTER close() (which finalizes every
    # outstanding request), so categories are DISJOINT and sum to total
    wall = _time.perf_counter() - t0
    by_status = status_counts(reqs)
    ok = [r for r in reqs if r.status == "served"]
    tpots = [
        (r.t_done - r.t_admit) / len(r.tokens)
        for r in ok if r.tokens and r.t_admit is not None
    ]

    def pct(xs, p):
        v = percentile(xs, p)
        return None if v is None else round(v * 1e3, 3)

    summary = {
        "served": by_status["served"],
        "shed": by_status["shed"],
        "rejected": by_status["rejected"],
        "timeout": by_status["timeout"],
        "unfinished": by_status["closed"],
        "drained_clean": drained_clean,
        "wall_s": round(wall, 3),
        "sustained_req_per_sec": round(len(ok) / wall, 3) if wall > 0 else None,
        "p50_token_ms": pct(tpots, 0.50),
        "p99_token_ms": pct(tpots, 0.99),
        "engine": engine.summary(),
    }
    class_labels = sorted({r.class_label for r in reqs})
    if len(class_labels) > 1:
        # per-class status ledger — the p0-stays-served-while-p2-sheds
        # evidence the per-class admission plane exists to produce
        summary["classes"] = {
            c: status_counts([r for r in reqs if r.class_label == c])
            for c in class_labels
        }
    if replay_trace is not None:
        summary["replayed_trace"] = args.replay
    if writer is not None:
        summary["recorded_trace"] = args.record_trace
    print(_json.dumps(summary), flush=True)
    if args.stats_out:
        _obs.write_stats_json(args.stats_out, summary)
    _obs.tracer.dump()  # per-process trace file (no-op without trace_dir)
    if drained_clean is not None:
        # SIGTERM path: exit 0 iff the drain finished every in-flight
        # request (no 'closed' stragglers) — the graceful-exit contract
        return 0 if (drained_clean and not by_status["closed"]) else 1
    return 0 if (ok and not by_status["closed"]) else 1


def _serve_as_fleet_engine(args, engine) -> int:
    """The `paddle-tpu serve --register host:port` mode: this process is
    one FLEET ENGINE — a ServingScheduler wrapped in an EngineAgent that
    registers on the router's heartbeat-lease plane and serves requests
    arriving over the typed wire RPC (serving/router.py).  No local
    workload; SIGTERM drains the scheduler, deregisters, exits 0 on a
    clean drain — the rolling-restart contract."""
    import json as _json
    import os as _os
    import time as _time

    from paddle_tpu import obs as _obs
    from paddle_tpu.obs.metrics import MetricsExporter
    from paddle_tpu.robustness.preemption import PreemptionGuard
    from paddle_tpu.serving import EngineAgent, ServingScheduler
    from paddle_tpu.utils import flags as _serve_flags

    host, _, port = args.register.rpartition(":")
    if not host or not port.isdigit():
        print(f"--register wants host:port, got {args.register!r}",
              file=sys.stderr)
        return 2
    engine_id = args.engine_id or f"engine-{_os.getpid()}"
    metrics = MetricsExporter(
        path=args.metrics_out,
        port=(None if args.metrics_port is None
              else (args.metrics_port if args.metrics_port > 0 else -1)),
    ) if (
        args.metrics_out or args.metrics_port
        or _serve_flags.get_flag("metrics_out")
        or _serve_flags.get_flag("metrics_port")
    ) else None
    drained_clean = False
    with PreemptionGuard() as guard:
        sched = ServingScheduler(
            engine, queue_limit=args.queue_limit,
            default_deadline_s=args.deadline_s,
        )
        agent = EngineAgent(
            sched, engine_id, (host, int(port)),
            address=("127.0.0.1", args.engine_port),
        )
        # the harness parses this line for identity + data-plane port
        print(_json.dumps({
            "engine_id": engine_id,
            "data_plane": list(agent.address),
            "router": [host, int(port)],
        }), flush=True)
        try:
            while not guard.triggered:
                _time.sleep(0.1)
            _echo(f"draining: engine {engine_id} finishing in-flight work")
            drained_clean = sched.drain(args.drain_timeout_s)
        finally:
            agent.close()
            sched.close()
            if metrics is not None:
                metrics.close()
    summary = {
        "engine_id": engine_id,
        "drained_clean": drained_clean,
        "engine": engine.summary(),
    }
    print(_json.dumps(summary), flush=True)
    if args.stats_out:
        _obs.write_stats_json(args.stats_out, summary)
    _obs.tracer.dump()
    return 0 if drained_clean else 1


def cmd_route(argv: List[str]) -> int:
    """``paddle-tpu route`` — the serving-fleet router frontend
    (serving/router.py): admission (deadlines, bounded queue, shed) +
    least-predicted-wait dispatch with prefix/session affinity over the
    engines registered on its heartbeat-lease plane (`paddle-tpu serve
    --register`).  With ``--synthetic N`` it also DRIVES an open-loop
    workload through the fleet and prints the per-request lines + final
    summary (the `paddle-tpu serve` report shape, one tier up); with
    ``--synthetic 0`` it routes for external clients until SIGTERM."""
    import json as _json
    import time as _time

    ap = argparse.ArgumentParser(
        prog="paddle-tpu route",
        description="SLO-aware affinity-routing fleet frontend "
                    "(serving/router.py)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="router RPC port (0 = ephemeral, printed on the "
                    "ready line)")
    ap.add_argument("--journal", default="",
                    help="append-only JSON-lines routing journal; restart "
                    "with the predecessor's journal to refuse re-serving "
                    "its finalized request ids (HA failover)")
    ap.add_argument("--lease-timeout-s", type=float, default=None,
                    help="engine heartbeat lease (default: the "
                    "router_lease_timeout_s flag)")
    ap.add_argument("--queue-limit", type=int, default=None,
                    help="bound on requests inside admission+dispatch "
                    "(default: the router_queue_limit flag; 0 = unbounded)")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="default per-request deadline stamped at the "
                    "frontend (default: the serving_default_deadline_s "
                    "flag; 0 = none)")
    ap.add_argument("--no-affinity", action="store_true",
                    help="disable prefix/session affinity (pure "
                    "least-predicted-wait) — the A/B lever for the "
                    "prefix-hit-rate comparison")
    ap.add_argument("--affinity-slack-s", type=float, default=None)
    ap.add_argument("--stats-poll-s", type=float, default=None)
    ap.add_argument("--expect-engines", type=int, default=0,
                    help="wait until N engines hold live leases before "
                    "offering traffic")
    ap.add_argument("--expect-timeout-s", type=float, default=30.0)
    ap.add_argument("--synthetic", type=int, default=0,
                    help="drive N open-loop synthetic requests through the "
                    "fleet; 0 = daemon mode (route for external clients "
                    "until SIGTERM)")
    ap.add_argument("--src-vocab", type=int, default=1000)
    ap.add_argument("--max-new-tokens", type=int, default=None)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate (req/s); 0 = submit all "
                    "immediately")
    ap.add_argument("--arrival", default="poisson",
                    choices=["poisson", "uniform", "burst"])
    ap.add_argument("--prefix-pool", type=int, default=0,
                    help="share prompt prefixes across synthetic requests "
                    "(reader/loadgen.PrefixMixer) — what affinity routing "
                    "concentrates per engine")
    ap.add_argument("--prefix-frac", type=float, default=0.5)
    ap.add_argument("--sessions", type=int, default=0,
                    help="stamp session ids from a pool of N "
                    "(PrefixMixer.session_of) — the affinity key")
    ap.add_argument("--priority-every", type=int, default=0,
                    help="stamp every Nth synthetic request interactive "
                    "class p0 and the rest batch class p2; 0 = all p1")
    ap.add_argument("--record-trace", default="", metavar="TRACE",
                    help="record the fleet workload to a replayable .ptt "
                    "request-lifecycle trace (robustness/traces.py)")
    ap.add_argument("--replay", default="", metavar="TRACE",
                    help="replay a recorded .ptt trace through the fleet "
                    "instead of synthetic load (recorded arrivals/ids/"
                    "deadlines/sessions/priorities; --synthetic/--rate "
                    "are ignored; recorded cancels are dropped — the "
                    "fleet client has no cancel RPC)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout-s", type=float, default=120.0,
                    help="wait budget for the synthetic workload")
    ap.add_argument("--stats-out", default="",
                    help="write the summary JSON here too")
    ap.add_argument("--trace-dir", default=None)
    ap.add_argument("--metrics-out", default=None,
                    help="periodic Prometheus snapshot: fleet gauges "
                    "(paddle_tpu_fleet_engines, per-engine queue depth/"
                    "pages/predicted wait) + the fleet request ledger")
    ap.add_argument("--metrics-port", type=int, default=None)
    args = ap.parse_args(argv)

    import numpy as np

    from paddle_tpu import obs as _obs

    _obs.tracer.configure(role="route", trace_dir=args.trace_dir)
    from paddle_tpu.obs.metrics import MetricsExporter
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
    from paddle_tpu.robustness.preemption import PreemptionGuard
    from paddle_tpu.serving import FleetClient, Request, Router
    from paddle_tpu.serving import percentile, status_counts
    from paddle_tpu.utils import flags as _route_flags

    metrics = MetricsExporter(
        path=args.metrics_out,
        port=(None if args.metrics_port is None
              else (args.metrics_port if args.metrics_port > 0 else -1)),
    ) if (
        args.metrics_out or args.metrics_port
        or _route_flags.get_flag("metrics_out")
        or _route_flags.get_flag("metrics_port")
    ) else None
    if metrics is not None and metrics.port:
        _echo(f"metrics: http://127.0.0.1:{metrics.port}/metrics")

    router = Router(
        address=(args.host, args.port),
        journal_path=args.journal or None,
        lease_timeout_s=args.lease_timeout_s,
        queue_limit=args.queue_limit,
        default_deadline_s=args.deadline_s,
        affinity=False if args.no_affinity else None,
        affinity_slack_s=args.affinity_slack_s,
        stats_poll_s=args.stats_poll_s,
    )
    # the harness parses this line for the routing address
    print(_json.dumps({"router": list(router.address)}), flush=True)
    rc = 0
    t0 = _time.perf_counter()
    try:
        with PreemptionGuard() as guard:
            if args.expect_engines > 0:
                deadline = _time.perf_counter() + args.expect_timeout_s
                while (len(router.live_engines()) < args.expect_engines
                       and _time.perf_counter() < deadline
                       and not guard.triggered):
                    _time.sleep(0.05)
                live = len(router.live_engines())
                if live < args.expect_engines:
                    _echo(f"only {live}/{args.expect_engines} engines "
                          "registered before the deadline")
                    return 1
                _echo(f"fleet ready: {live} engine(s)")
            if args.synthetic <= 0 and not args.replay:
                # daemon mode: route until SIGTERM
                while not guard.triggered:
                    _time.sleep(0.1)
                return 0
            mixer = PrefixMixer(
                args.src_vocab,
                pool_size=max(1, args.prefix_pool),
                prefix_frac=args.prefix_frac if args.prefix_pool > 0 else 0.0,
                seed=args.seed, sessions=args.sessions,
            )
            t0 = _time.perf_counter()

            done = []

            def on_done(r):
                done.append(r)
                print(_json.dumps({
                    "req": r.req_id,
                    "status": r.status,
                    "tokens": r.tokens,
                    "error": r.error,
                    "latency_ms": round((r.t_done - r.t_submit) * 1e3, 3),
                }), flush=True)

            replay_trace = None
            if args.replay:
                from paddle_tpu.robustness.traces import read_trace

                replay_trace = read_trace(args.replay)
                reqs = [
                    Request(
                        list(rec["src"]), rec.get("mnt"),
                        req_id=str(rec["id"]), callback=on_done,
                        deadline_s=rec.get("dl"),
                        session_id=rec.get("sess"),
                        priority=rec.get("prio"),
                    )
                    for rec in replay_trace.requests()
                ]
            else:
                reqs = [
                    Request(
                        mixer.source(i), args.max_new_tokens,
                        req_id=f"route-{args.seed}-{i}", callback=on_done,
                        deadline_s=args.deadline_s,
                    )
                    for i in range(args.synthetic)
                ]
            priority_of = None
            if args.priority_every > 0 and replay_trace is None:
                priority_of = (
                    lambda i: 0 if i % args.priority_every == 0 else 2
                )
            writer = None
            if args.record_trace:
                from paddle_tpu.robustness.traces import TraceWriter

                writer = TraceWriter(args.record_trace, meta={
                    "cmd": "route", "seed": args.seed, "rate": args.rate,
                    "arrival": args.arrival,
                })
            fc = FleetClient(router.address)

            def _submit(r):
                if writer is not None:
                    writer.record_request(r)
                return fc.submit(r)

            try:
                if replay_trace is not None:
                    from paddle_tpu.robustness.traces import (
                        TraceReplayLoadGen,
                    )

                    it = iter(reqs)
                    TraceReplayLoadGen(
                        replay_trace,
                        request_factory=lambda rec: next(it),
                    ).run(_submit, stop=lambda: guard.triggered)
                elif args.rate > 0:
                    OpenLoopLoadGen(
                        args.rate, len(reqs), lambda i: reqs[i],
                        seed=args.seed, process=args.arrival,
                        session_of=mixer.session_of,
                        priority_of=priority_of,
                    ).run(_submit, stop=lambda: guard.triggered)
                else:
                    for i, r in enumerate(reqs):
                        if guard.triggered:
                            break
                        sid = mixer.session_of(i)
                        if sid is not None:
                            r.session_id = sid
                        if priority_of is not None:
                            pri = priority_of(i)
                            if pri is not None:
                                r.priority = int(pri)
                        _submit(r)
                wait_deadline = _time.perf_counter() + args.timeout_s
                for r in reqs:
                    while not r.done():
                        if guard.triggered or (
                            _time.perf_counter() > wait_deadline
                        ):
                            break
                        r.wait(0.2)
                    if guard.triggered:
                        break
            finally:
                fc.close()
                if writer is not None:
                    writer.close()
    finally:
        fleet = router.fleet_stats()
        router.close()
        if metrics is not None:
            metrics.close()
    wall = _time.perf_counter() - t0
    by_status = status_counts(r for r in reqs if r.done())
    ok = [r for r in reqs if r.status == "served"]
    lats = sorted(
        (r.t_done - r.t_submit) * 1e3
        for r in ok if r.t_done is not None and r.t_submit is not None
    )

    def pct(p):
        v = percentile(lats, p)
        return None if v is None else round(v, 3)

    summary = {
        "served": by_status["served"],
        "shed": by_status["shed"],
        "rejected": by_status["rejected"],
        "timeout": by_status["timeout"],
        "unfinished": len(reqs) - sum(by_status.values()),
        "wall_s": round(wall, 3),
        "sustained_req_per_sec": (
            round(len(ok) / wall, 3) if wall > 0 else None
        ),
        "p50_latency_ms": pct(0.50),
        "p95_latency_ms": pct(0.95),
        "p99_latency_ms": pct(0.99),
        "fleet": fleet,
    }
    class_labels = sorted({r.class_label for r in reqs})
    if len(class_labels) > 1:
        summary["classes"] = {
            c: status_counts(r for r in reqs if r.class_label == c)
            for c in class_labels
        }
    print(_json.dumps(summary), flush=True)
    if args.stats_out:
        _obs.write_stats_json(args.stats_out, summary)
    _obs.tracer.dump()
    return rc if (ok or (args.synthetic <= 0 and not args.replay)) else 1


def cmd_scenario(argv: List[str]) -> int:
    """``paddle-tpu scenario`` — the production-gate scenario harness
    (robustness/scenarios.py): run named mixed-traffic/chaos scenarios
    and print one JSON metrics line each (p50/p95/p99, goodput under the
    SLO, shed/reject/timeout counts, recovery-time-after-fault).  Exit 0
    only when every requested scenario passed its gates."""
    ap = argparse.ArgumentParser(
        prog="paddle-tpu scenario",
        description="mixed-traffic SLO + chaos scenario harness "
        "(robustness/scenarios.py)",
    )
    ap.add_argument("--name", action="append", default=[],
                    help="scenario to run (repeatable); see --list")
    ap.add_argument("--all-fast", action="store_true",
                    help="run every fast (in-process) scenario")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="list known scenarios and exit")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="end-to-end SLO override (default: the "
                    "scenario_slo_ms flag, else derived from measurement)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir for fleet scenarios (default: a "
                    "temp dir)")
    ap.add_argument("--out", default="",
                    help="append one JSON line per scenario here too")
    ap.add_argument("--trace", action="store_true",
                    help="run with span tracing armed and merge every "
                    "process's trace file into ONE Perfetto-loadable "
                    "timeline per scenario (obs/; subprocess fleets "
                    "inherit the trace dir through the environment)")
    ap.add_argument("--trace-dir", default=None,
                    help="where the per-process + merged trace files land "
                    "(default: a temp dir; implies --trace)")
    args = ap.parse_args(argv)

    from paddle_tpu.robustness import scenarios as _sc

    if args.list_:
        for n in sorted(_sc.FAST_SCENARIOS):
            print(f"{n}  (fast)")
        for n in sorted(_sc.SLOW_SCENARIOS):
            print(f"{n}  (slow: spawns a worker fleet)")
        return 0
    names = list(args.name)
    if args.all_fast:
        names.extend(n for n in _sc.FAST_SCENARIOS if n not in names)
    if not names:
        print("error: give --name (repeatable), --all-fast, or --list",
              file=sys.stderr)
        return 2
    trace_dir = None
    if args.trace or args.trace_dir:
        import tempfile

        from paddle_tpu import obs as _obs

        trace_dir = args.trace_dir or tempfile.mkdtemp(
            prefix="paddle-tpu-trace-"
        )
        os.makedirs(trace_dir, exist_ok=True)
        os.environ.setdefault("PADDLE_TPU_TRACE_ID", _obs.tracer.trace_id)
    failed = []
    for name in names:
        kw = {"seed": args.seed}
        if args.slo_ms is not None:
            kw["slo_ms"] = args.slo_ms
        if name in _sc.SLOW_SCENARIOS:
            import tempfile

            kw["workdir"] = args.workdir or tempfile.mkdtemp(
                prefix=f"paddle-tpu-scenario-{name}-"
            )
        if trace_dir is not None:
            from paddle_tpu import obs as _obs
            from paddle_tpu.utils import flags as _flags

            # one subdirectory PER scenario, and the parent rings reset:
            # otherwise scenario N's merged timeline would accumulate
            # scenarios 1..N-1's events and dead workers' trace files
            sdir = os.path.join(trace_dir, name)
            os.makedirs(sdir, exist_ok=True)
            _flags.set_flag("trace_dir", sdir)
            # subprocess fleets (the elastic workers a scenario spawns)
            # arm through the environment, sharing this trace id
            os.environ["PADDLE_TPU_TRACE_DIR"] = sdir
            _obs.tracer.reset()
            _obs.tracer.configure(role="serve", trace_dir=sdir)
        res = _sc.run_scenario(name, **kw)
        res.pop("_requests", None)
        if trace_dir is not None:
            from paddle_tpu.obs.merge import merge_dir

            _obs.tracer.dump()
            merged, mpath = merge_dir(
                os.path.join(trace_dir, name),
                out_path=os.path.join(trace_dir, f"merged-{name}.json"),
            )
            res["trace"] = {
                "merged": mpath,
                "events": sum(
                    1 for e in merged["traceEvents"] if e.get("ph") != "M"
                ),
                "pids": merged["otherData"]["merged_pids"],
                "planes": sorted({
                    e.get("cat") for e in merged["traceEvents"]
                    if e.get("ph") != "M" and e.get("cat")
                }),
            }
        line = json.dumps(res)
        print(line, flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(line + "\n")
        if not res.get("passed"):
            failed.append(name)
    if failed:
        print(f"SCENARIO FAILURES: {failed}", file=sys.stderr)
    return 1 if failed else 0


def cmd_trace(argv: List[str]) -> int:
    """``paddle-tpu trace`` — the span-timeline tooling (obs/):

    * ``merge --dir D [--out F]`` — zip the per-process
      ``trace-<role>-<pid>.json`` files a launcher/scenario run left
      behind into ONE Chrome-trace timeline (opens directly in Perfetto),
      clock-skew aligned via the RPC plane's request/response pairs
      (wall-anchor fallback for processes that never talked);
    * ``validate F`` — schema-check a trace file (required event keys,
      begin/end pairing, well-formed args); exit 0 iff valid.

    One JSON summary line per run (event counts, pids, planes, applied
    per-process clock offsets)."""
    ap = argparse.ArgumentParser(
        prog="paddle-tpu trace",
        description="merge/validate span-timeline files (paddle_tpu/obs)",
    )
    ap.add_argument("action", choices=["merge", "validate"])
    ap.add_argument("paths", nargs="*",
                    help="validate: trace file(s); merge: explicit trace "
                    "files instead of --dir")
    ap.add_argument("--dir", default=None,
                    help="merge: directory of trace-*.json files")
    ap.add_argument("--out", default=None,
                    help="merge: merged timeline path "
                    "(default <dir>/merged.json)")
    args = ap.parse_args(argv)

    from paddle_tpu.obs import merge as _merge

    if args.action == "validate":
        if not args.paths:
            print("error: validate needs trace file path(s)",
                  file=sys.stderr)
            return 2
        bad = 0
        for p in args.paths:
            problems = _merge.validate_trace(_merge.load_trace(p))
            print(json.dumps({
                "file": p, "valid": not problems,
                "problems": problems[:20],
            }))
            bad += bool(problems)
        return 1 if bad else 0

    if args.paths:
        merged = _merge.merge_traces(
            [_merge.load_trace(p) for p in args.paths]
        )
        out = args.out or "merged.json"
        with open(out, "w") as f:
            json.dump(merged, f)
    elif args.dir:
        merged, out = _merge.merge_dir(args.dir, out_path=args.out)
    else:
        print("error: merge needs --dir or trace file paths",
              file=sys.stderr)
        return 2
    other = merged["otherData"]
    print(json.dumps({
        "merged": out,
        "events": sum(
            1 for e in merged["traceEvents"] if e.get("ph") != "M"
        ),
        "pids": other["merged_pids"],
        "roles": other["roles"],
        "offsets_us": other["offsets_us"],
        "rpc_pair_edges": other["rpc_pair_edges"],
    }))
    return 0


def cmd_worker(argv: List[str]) -> int:
    """``paddle-tpu worker`` — one elastic trainer process (scale-out
    plane, trainer/elastic.py): leases data-shard tasks from the master,
    contributes deterministic per-task gradients, reduces at pass fences,
    writes its sharded-checkpoint shard."""
    from paddle_tpu.trainer import elastic

    return elastic.main(argv)


def cmd_master(argv: List[str]) -> int:
    """``paddle-tpu master`` — one HA master candidate for the elastic
    cluster plane: campaigns for the file lease under --dir, serves the
    task queues when leader (publishing its endpoint for HAClient
    discovery), hot-stands-by otherwise.  Runs until SIGTERM/SIGINT."""
    import signal

    ap = argparse.ArgumentParser(
        prog="paddle-tpu master",
        description="HA master candidate (worker registry + shard leases "
        "+ pass fences; master.py/master_ha.py)",
    )
    ap.add_argument("--dir", required=True,
                    help="shared discovery/lease/snapshot directory")
    ap.add_argument("--patterns", required=True,
                    help="comma-separated recordio globs to partition")
    ap.add_argument("--chunks-per-task", type=int, default=8)
    ap.add_argument("--timeout-s", type=float, default=60.0,
                    help="per-task shard-lease timeout")
    ap.add_argument("--worker-timeout-s", type=float, default=10.0,
                    help="worker registry heartbeat-lease timeout")
    ap.add_argument("--failure-max", type=int, default=3)
    ap.add_argument("--lease-timeout", type=float, default=5.0,
                    help="leader-election lease timeout (master_ha)")
    ap.add_argument("--no-journal", action="store_true",
                    help="legacy debounced-snapshot persistence instead of "
                    "the fsync'd journal (standbys then take over cold)")
    ap.add_argument("--journal-compact-every", type=int, default=512,
                    help="journal records between snapshot compactions")
    ap.add_argument("--no-journal-fsync", action="store_true",
                    help="skip the per-record fsync (drills/benches only: "
                    "a kill -9 may then lose acked records)")
    ap.add_argument("--stats-out", default=None,
                    help="append one JSON line here each time THIS "
                    "candidate assumes leadership (warm/cold, replayed "
                    "records, takeover span) — the failover drill reads it")
    ap.add_argument("--chaos", default=None,
                    help="arm chaos points in THIS candidate, e.g. "
                    "'kill_master@8' or 'net_partition@40' (env "
                    "PADDLE_TPU_CHAOS also works)")
    ap.add_argument("--rpc-max-message-mb", type=int, default=None,
                    help="override the rpc_max_message_mb flag: hard "
                    "bound on one wire frame, enforced on send AND recv "
                    "(master_wire.py)")
    args = ap.parse_args(argv)

    from paddle_tpu import obs as _obs
    from paddle_tpu.master_ha import HAMaster

    if args.rpc_max_message_mb is not None:
        from paddle_tpu.utils import flags as _flags

        _flags.set_flag("rpc_max_message_mb", args.rpc_max_message_mb)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    _obs.tracer.configure(role="master")
    if args.chaos:
        from paddle_tpu.robustness import chaos as _chaos

        _chaos.arm(args.chaos)
    ha = HAMaster(
        args.dir,
        [p for p in args.patterns.split(",") if p],
        lease_timeout=args.lease_timeout,
        chunks_per_task=args.chunks_per_task,
        timeout_s=args.timeout_s,
        worker_timeout_s=args.worker_timeout_s,
        failure_max=args.failure_max,
        auto_rotate=False,  # elastic workers fence their pass boundaries
        journal=not args.no_journal,
        journal_fsync=not args.no_journal_fsync,
        journal_compact_every=args.journal_compact_every,
    )
    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    ha.start()
    _echo(f"master candidate {ha.owner_id} campaigning in {args.dir}")
    announced = False
    while not stop["flag"]:
        if ha.fatal is not None:
            _echo(f"FATAL {ha.fatal}")
            ha.stop()
            return 1
        # snapshot the server ref: the HA thread nulls it on step-down
        # between the leader check and the address read
        srv = ha.server
        if ha.is_leader.is_set() and srv is not None and not announced:
            host, port = srv.address
            _echo(f"LEADER {host}:{port}")
            if args.stats_out and ha.last_takeover is not None:
                # advisory (obs.write_stats_json warns instead of raising):
                # an unwritable path must not crash the just-elected leader
                # — every candidate shares the flag, so it would crash-loop
                # the cluster
                _obs.write_stats_json(
                    args.stats_out,
                    {"owner": ha.owner_id, **ha.last_takeover},
                    append=True,
                )
            announced = True
        elif not ha.is_leader.is_set():
            announced = False
        time.sleep(0.2)  # lock: allow[C306] CLI supervision loop: wall-clock by design, driven end-to-end by the failover drills
    ha.stop()
    return 0


def _donation_audit_builders():
    """T106 over the shipped step builders: trace make_train_step,
    make_multi_train_step, and the whole-pass epoch program on a probe MLP
    and audit that every large carried buffer (params/opt-state/carry) is
    donated.  Pure host-side tracing — no compile, no FLOPs."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.analysis.trace_lint import donation_audit
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.trainer.step import (
        make_epoch_program,
        make_multi_train_step,
        make_train_carry,
        make_train_step,
    )

    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(64))
    h = paddle.layer.fc(x, size=256, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=10, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(input=pred, label=y)
    net = CompiledNetwork(Topology([cost]))
    opt = paddle.optimizer.Adam(learning_rate=1e-2)
    params, state = net.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    batch = {
        "x": SeqTensor(jnp.zeros((8, 64), jnp.float32)),
        "y": SeqTensor(jnp.zeros((8,), jnp.int32)),
    }
    rng = jax.random.PRNGKey(0)
    k = 4
    stacked = jax.tree_util.tree_map(
        lambda v: jnp.stack([v] * k), batch
    )
    carry = make_train_carry(params, state, opt_state, rng)
    diags = []
    diags += donation_audit(
        make_train_step(net, opt, mesh=None),
        params, state, opt_state, batch, rng,
        source="trainer/step.py:make_train_step",
    )
    diags += donation_audit(
        make_multi_train_step(net, opt, k, mesh=None),
        params, state, opt_state, stacked, rng,
        source="trainer/step.py:make_multi_train_step",
    )
    diags += donation_audit(
        make_epoch_program(net, opt, mesh=None),
        carry, stacked, jnp.arange(k),
        source="trainer/step.py:make_epoch_program",
    )
    print(
        f"donation audit: 3 step builders traced, {len(diags)} T106 "
        "finding(s)"
    )
    return diags


def cmd_cache(argv: List[str]) -> int:
    """``paddle-tpu cache`` — the persistent AOT executable cache
    (core/aot_cache.py) maintenance face:

    * ``ls``               — entries with size + full key provenance;
    * ``warm``             — prewarm: parse a config, stage its feed, and
                             compile-or-load the train-step executable for
                             every distinct batch shape the ladder realizes
                             (fleet boots then deserialize, not retrace);
    * ``prune --max-mb N`` — drop oldest entries until the store fits;
    * ``clear``            — drop everything.

    Each run closes with one JSON summary line (the warm-boot bench and the
    StatSet counters aot_cache/{hit,miss,stale,corrupt} read it)."""
    ap = argparse.ArgumentParser(
        prog="paddle-tpu cache",
        description="persistent AOT executable cache maintenance "
        "(core/aot_cache.py)",
    )
    ap.add_argument("action", choices=["ls", "warm", "prune", "clear"])
    ap.add_argument("--dir", required=True, help="cache directory")
    ap.add_argument("--config", default=None,
                    help="warm: v1 config file whose train step to prewarm")
    ap.add_argument("--config_args", default="")
    ap.add_argument("--batch_size", type=int, default=0,
                    help="warm: override the config's batch size")
    ap.add_argument("--max-shapes", type=int, default=16,
                    help="warm: stop after this many distinct batch shapes")
    ap.add_argument("--max-mb", type=float, default=None,
                    help="prune: keep the store under this many MB")
    args = ap.parse_args(argv)

    from paddle_tpu.core.aot_cache import AOTCache

    cache = AOTCache(args.dir)
    if args.action == "ls":
        for e in cache.entries():
            key = e.get("key", {})
            prov = ", ".join(
                f"{k}={key[k]}" for k in
                ("kind", "n_steps", "batch", "topology", "jax", "backend")
                if key.get(k) is not None
            )
            print(
                f"{e['file']}  {e['bytes'] / 1e6:8.2f} MB  "
                + (f"CORRUPT: {e['corrupt']}" if "corrupt" in e else prov)
            )
        print(json.dumps(cache.summary()))
        return 0
    if args.action == "clear":
        n = cache.clear()
        print(json.dumps({**cache.summary(), "removed": n}))
        return 0
    if args.action == "prune":
        if args.max_mb is None:
            print("error: prune needs --max-mb", file=sys.stderr)
            return 2
        removed = cache.prune(int(args.max_mb * 1e6))
        print(json.dumps({**cache.summary(), "removed": removed}))
        return 0

    # warm: compile-or-load every distinct shape the config's feed realizes
    if not args.config:
        print("error: warm needs --config", file=sys.stderr)
        return 2
    from paddle_tpu.core.batch import batch_shape_key
    from paddle_tpu.parallel.mesh import shard_batch
    from paddle_tpu.utils import flags as _flags
    from paddle_tpu.v1_compat import make_batched_reader, parse_config

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    _flags.set_flag("aot_cache_dir", args.dir)
    config_path = os.path.abspath(args.config)
    parsed = parse_config(config_path, args.config_args)
    if args.batch_size:
        parsed.settings.batch_size = args.batch_size
    trainer = _make_trainer(parsed, _flags.get_flag("seed"))
    reader = make_batched_reader(
        parsed, os.path.dirname(config_path), parsed.settings.batch_size,
        train=True,
    )
    feeder = trainer._make_feeder(parsed.feeding)
    seen = set()
    t0 = time.time()
    for raw in reader():
        # shape-dedup on the HOST feeder batch: staging is shape-preserving
        # and the scan must not pay a full-dataset H2D transfer to discover
        # a handful of rungs — only the first batch of each new shape ever
        # touches the device
        fed = feeder(raw)
        key = batch_shape_key(fed)
        if key in seen:
            continue
        seen.add(key)
        trainer.warm_compile(shard_batch(fed, trainer.mesh))
        if len(seen) >= args.max_shapes:
            break
    summary = {
        **trainer._aot_cache.summary(),
        "config": args.config,
        "shapes": len(seen),
        "warm_s": round(time.time() - t0, 3),
    }
    print(json.dumps(summary))
    return 0


def cmd_lint(argv: List[str]) -> int:
    """``paddle-tpu lint`` — static analysis (analysis/):

    * no --config: AST self-lint over the paddle_tpu package source
      (+ any --extra files), rules A### — including A206, the wire-codec
      hygiene rule: raw ``pickle.loads`` / bare ``Connection.recv()``
      deserialization outside master_wire.py is forbidden
      (``# wire: allow[A206] <why>`` escapes a genuinely-local read);
    * --config=conf.py: parse the v1 config and graph-lint its topology
      (rules G###) with layer + config provenance;
    * --journal=master_journal-000001.log: verify a master journal file —
      framing/CRC (J001), unknown record types (J002, the version-skew
      hard error), sequence monotonicity (J003), torn tail (J004);
    * --donation: buffer-donation audit (rule T106) over the shipped step
      builders — trace make_train_step / make_multi_train_step / the
      whole-pass epoch program on a probe network and flag any large
      carried buffer that would be copied instead of donated;
    * --concurrency: lock-discipline lint (rules C###) over the package
      source — guarded-field consistency, static lock-order cycles,
      blocking-under-lock, thread-leak and injectable-clock checks
      (the static leg of the concurrency plane; the runtime leg is
      PADDLE_TPU_LOCK_SANITIZER=1 on the chaos drills);
    * --protocol: protocol-conformance lint (rules P###) over the
      distributed planes (master RPC/journal/wire + serving fleet) —
      RPC whitelist vs handler vs wire-universe conformance (P501),
      journal record/replay/compaction coverage (P502), status-ledger
      exhaustiveness (P503), lease/fence monotonicity (P504), timeout
      completeness (P505); ``# proto: allow[P###] <why>`` escapes an
      intentional finding (skips the self-lint);
    * --numerics: precision-flow lint (rules N###) over the compiled
      train-step jaxprs — low-precision accumulation, master-precision
      escapes, unguarded domain hazards, overflowing mask literals,
      sub-f32 psums, convert churn.  Alone it lints the package step
      builders over probe topologies; with --config it lints each
      config's REAL train step; --compute-dtype/--master-dtype pick the
      precision plan (the bf16 flagship leg of ``make lint``), and
      --certify prints the per-layer precision certificate
      (analysis.certify_precision_plan — the ROADMAP item 2 gate; the
      runtime leg is PADDLE_TPU_NUM_SANITIZER=1 on the chaos drills).

    Exit 0 only when no diagnostics fire (``make lint``'s contract)."""
    ap = argparse.ArgumentParser(
        prog="paddle-tpu lint",
        description="config-time graph lint + package self-lint "
        "(the reference config_parser's config_assert plane)",
    )
    ap.add_argument("--config", action="append", default=[],
                    help="v1 config file to graph-lint (repeatable; one "
                    "process lints the whole corpus; skips the self-lint)")
    ap.add_argument("--config_args", default="",
                    help="comma-separated key=value pairs for the config(s)")
    ap.add_argument("--extra", action="append", default=[],
                    help="extra .py files to self-lint (e.g. bench.py)")
    ap.add_argument("--journal", action="append", default=[],
                    help="master journal file to verify (repeatable; "
                    "rules J###; skips the self-lint)")
    ap.add_argument("--donation", action="store_true",
                    help="audit the shipped step builders' buffer donation "
                    "(rule T106; skips the self-lint)")
    ap.add_argument("--concurrency", action="store_true",
                    help="lock-discipline lint (rules C###) over the "
                    "package source (skips the self-lint)")
    ap.add_argument("--numerics", action="store_true",
                    help="precision-flow lint (rules N###) over the "
                    "compiled train-step jaxprs: package probes, or each "
                    "--config's real step (skips the self-lint)")
    ap.add_argument("--protocol", action="store_true",
                    help="protocol-conformance lint (rules P###) over the "
                    "distributed planes: RPC surface vs handlers vs wire "
                    "universe, journal record/replay/compaction coverage, "
                    "status-ledger exhaustiveness, lease/fence "
                    "monotonicity, timeout completeness (skips the "
                    "self-lint)")
    ap.add_argument("--compute-dtype", default=None,
                    help="numerics: compute dtype of the precision plan "
                    "(e.g. bfloat16; default f32)")
    ap.add_argument("--master-dtype", default=None,
                    help="numerics: master/param dtype of the plan "
                    "(default float32)")
    ap.add_argument("--certify", action="store_true",
                    help="numerics + --config: print the per-layer "
                    "precision certificate for the dtype plan")
    ap.add_argument("--min-severity", default=None,
                    choices=["info", "warning", "error"],
                    help="only report findings at or above this severity")
    args = ap.parse_args(argv)

    from paddle_tpu import analysis

    diags = []
    if args.journal:
        from paddle_tpu import master_journal as _mj

        for jpath in args.journal:
            for f in _mj.verify_journal(jpath):
                diags.append(analysis.Diagnostic(
                    rule=f["rule"],
                    severity=analysis.Severity[f["severity"].upper()],
                    message=f["message"],
                    source=jpath,
                ))
    if args.donation:
        diags.extend(_donation_audit_builders())
    if args.concurrency:
        from paddle_tpu.analysis.concurrency_lint import (
            lint_concurrency_package,
        )

        diags.extend(lint_concurrency_package(extra_paths=args.extra))
    if args.protocol:
        from paddle_tpu.analysis.protocol_lint import lint_protocol_package

        diags.extend(lint_protocol_package())
    if args.numerics:
        from paddle_tpu.analysis.numerics_lint import (
            certify_precision_plan,
            lint_numerics_config,
            lint_numerics_package,
        )

        if args.certify and not args.config:
            print("error: --certify needs --config (a certificate is "
                  "per-topology; the package probes have none)",
                  file=sys.stderr)
            return 2
        if args.config:
            from paddle_tpu.v1_compat import parse_config

            for cfg in args.config:
                if len(args.config) > 1 or args.certify:
                    print(f"numerics-lint {cfg} "
                          f"(compute={args.compute_dtype or 'float32'})")
                if args.certify:
                    # ONE trace: the certificate already carries every
                    # (pragma-filtered) N-rule finding for this plan, and
                    # a REJECT must fail the exit-code contract
                    parsed = parse_config(
                        os.path.abspath(cfg), args.config_args
                    )
                    from paddle_tpu.v1_compat import make_optimizer

                    try:
                        opt = make_optimizer(parsed.settings)
                    except Exception:  # exotic settings: the Adam probe
                        opt = None
                    cert = certify_precision_plan(parsed.topology, {
                        "compute_dtype": args.compute_dtype,
                        "master_dtype": args.master_dtype,
                    }, optimizer=opt)
                    print(cert.format())
                    diags.extend(cert.diagnostics)
                else:
                    diags.extend(lint_numerics_config(
                        cfg, args.config_args,
                        compute_dtype=args.compute_dtype,
                        master_dtype=args.master_dtype,
                    ))
        else:
            diags.extend(lint_numerics_package(
                compute_dtype=args.compute_dtype,
                master_dtype=args.master_dtype,
            ))
    if args.config and not args.numerics:
        from paddle_tpu.v1_compat import parse_config

        for cfg in args.config:
            if len(args.config) > 1:
                print(f"graph-lint {cfg}")
            try:
                parsed = parse_config(os.path.abspath(cfg), args.config_args)
            except analysis.DiagnosticError as e:
                # build-time findings (duplicate names, feed-slot errors)
                # report like any other lint result, not as a traceback —
                # re-homed onto this config so the merged report attributes
                # them to the right file
                import dataclasses as _dc

                diags.extend(
                    _dc.replace(d, source=cfg) for d in e.diagnostics
                )
                continue
            diags.extend(analysis.lint_parsed(parsed))
    if not (args.config or args.journal or args.donation
            or args.concurrency or args.numerics or args.protocol):
        diags = analysis.lint_package(extra_paths=args.extra)

    if args.min_severity:
        floor = analysis.Severity[args.min_severity.upper()]
        diags = [d for d in diags if d.severity >= floor]

    print(analysis.format_diagnostics(diags))
    return 1 if diags else 0


def cmd_explore(argv: List[str]) -> int:
    """Deterministic interleaving explorer over the distributed planes.

    Drives the REAL state machines (serving router, journaled master,
    HA lease file) in-process on a virtual clock with a simulated
    transport, searching event interleavings for protocol-invariant
    violations (double-serve, epoch-fence breach, recovery infidelity).

    * default: seeded-random exploration (``--schedules`` independent
      schedules; schedule i draws from ``Random(f"{seed}:{i}")``, so
      any run replays exactly).
    * --dfs-depth N: additionally sweep every interleaving up to depth
      N (bounded DFS, first ``--dfs-branch`` enabled events per state).
    * --plant NAME: plant a known bug (canary) to prove the harness
      detects, shrinks, and replays — e.g. ``double_serve``.
    * --replay SPEC.json: re-run a shrunk violation spec; exit 0 iff
      the violation reproduces (the regression-test contract).

    Exit code: 0 = clean (or replay reproduced), 1 = violation found
    (or replay failed to reproduce).  A found violation is ddmin-shrunk
    to a minimal replayable spec, printed, and written to ``--out``.
    """
    ap = argparse.ArgumentParser(prog="paddle-tpu explore",
                                 description=cmd_explore.__doc__)
    ap.add_argument("--model", default="router",
                    choices=["router", "master", "ha"],
                    help="which state machine to drive (default router)")
    ap.add_argument("--schedules", type=int, default=200,
                    help="number of seeded-random schedules (default 200)")
    ap.add_argument("--seed", type=int, default=0,
                    help="batch seed; schedule i uses Random(f'{seed}:{i}')")
    ap.add_argument("--max-events", type=int, default=14,
                    help="events per random schedule (default 14)")
    ap.add_argument("--dfs-depth", type=int, default=0,
                    help="also run bounded DFS to this depth (0 = skip)")
    ap.add_argument("--dfs-branch", type=int, default=5,
                    help="DFS branch limit per state (default 5)")
    ap.add_argument("--plant", default=None,
                    help="plant a known bug as a harness canary "
                    "(e.g. double_serve)")
    ap.add_argument("--replay", default=None, metavar="SPEC",
                    help="re-run a shrunk violation spec JSON file")
    ap.add_argument("--out", default=None, metavar="SPEC",
                    help="write the shrunk violation spec here")
    args = ap.parse_args(argv)

    import json
    import logging
    import tempfile

    from paddle_tpu.analysis.interleave import (
        dfs_explore, explore_schedules, make_model, replay_spec,
    )

    # fault injection makes the router log every simulated transport
    # failure — noise at batch scale, so keep only real errors
    logging.getLogger("paddle_tpu").setLevel(logging.ERROR)

    if args.replay:
        with open(args.replay, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
        out = replay_spec(spec)
        if out["reproduced"]:
            print(f"reproduced ({out['applied']} events applied):")
            for v in out["violations"]:
                print(f"  {v}")
            return 0
        print(f"spec did NOT reproduce ({out['applied']} events applied, "
              "no violation)", file=sys.stderr)
        return 1

    workdir = tempfile.mkdtemp(prefix="paddle-tpu-explore-")
    model = make_model(args.model, workdir, planted=args.plant)
    try:
        res = explore_schedules(model, schedules=args.schedules,
                                seed=args.seed, max_events=args.max_events)
        if not res["violation_found"] and args.dfs_depth > 0:
            dres = dfs_explore(model, depth=args.dfs_depth,
                               branch_limit=args.dfs_branch)
            print(f"dfs: {dres['paths_run']} paths to depth "
                  f"{args.dfs_depth}")
            if dres["violation_found"]:
                res = {"violation_found": True,
                       "schedules_run": res["schedules_run"],
                       "spec": dres["spec"]}
        if not res["violation_found"]:
            print(f"clean: {res['schedules_run']} schedules on model "
                  f"{args.model!r} (seed {args.seed}), no violation")
            return 0
        spec = res["spec"]
        print(f"VIOLATION on model {args.model!r} after "
              f"{res['schedules_run']} schedules, shrunk to "
              f"{len(spec['events'])} events:")
        for v in spec["violations"]:
            print(f"  {v}")
        print(json.dumps(spec, indent=2, sort_keys=True))
        if args.out:
            with open(args.out, "w", encoding="utf-8") as fh:
                json.dump(spec, fh, indent=2, sort_keys=True)
            print(f"spec written to {args.out} "
                  f"(replay: paddle-tpu explore --replay {args.out})")
        return 1
    finally:
        model.close()


def cmd_fuzz(argv: List[str]) -> int:
    """Coverage-guided chaos-composition fuzzer (robustness/fuzz.py).

    Samples seeded COMPOSITIONS of the existing fault vocabulary —
    arrival process x rate factor, serve-plane chaos (nan_request,
    serve_slow_client), network emulation (delay/drop/dup/corrupt/
    partition), training chaos (worker_hang), torn checkpoints — as
    declarative specs, runs each cocktail against the REAL serving/
    training/checkpoint planes in-process, and checks the invariant
    set (disjoint status ledger, bit-identical training params, journal
    lint, page/thread leaks, armed-chaos consultation, checkpoint
    restore past torn artifacts).

    * default: ``--count`` seeded compositions; composition i draws
      from ``Random(f"{seed}:{i}")``, so any run replays exactly.
    * --plant NAME: plant a known bug (canary) to prove the harness
      detects, shrinks, and replays — e.g. ``ledger_skew``.
    * --replay SPEC.json: re-run a shrunk violation spec; exit 0 iff
      the violation reproduces (the regression-test contract, shared
      with ``paddle-tpu explore``).

    Exit code: 0 = clean (or replay reproduced), 1 = violation found
    (or replay failed to reproduce).  A found violation is ddmin-shrunk
    to a minimal replayable spec, printed, and written to ``--out``.
    """
    ap = argparse.ArgumentParser(prog="paddle-tpu fuzz",
                                 description=cmd_fuzz.__doc__)
    ap.add_argument("--count", type=int, default=25,
                    help="number of seeded compositions (default 25)")
    ap.add_argument("--seed", type=int, default=0,
                    help="batch seed; composition i uses "
                    "Random(f'{seed}:{i}')")
    ap.add_argument("--requests", type=int, default=16,
                    help="serving requests offered per composition")
    ap.add_argument("--plant", default=None,
                    help="plant a known bug as a harness canary "
                    "(e.g. ledger_skew)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="skip ddmin shrinking of a found violation")
    ap.add_argument("--replay", default=None, metavar="SPEC",
                    help="re-run a shrunk violation spec JSON file")
    ap.add_argument("--out", default=None, metavar="SPEC",
                    help="write the shrunk violation spec here")
    args = ap.parse_args(argv)

    import json
    import logging
    import tempfile

    from paddle_tpu.robustness import fuzz as _fz

    # fault cocktails make every plane log its injected failures —
    # noise at batch scale, so keep only real errors
    logging.getLogger("paddle_tpu").setLevel(logging.ERROR)

    workdir = tempfile.mkdtemp(prefix="paddle-tpu-fuzz-")
    if args.replay:
        spec = _fz.load_spec(args.replay)
        out = _fz.replay_fuzz_spec(spec, workdir=workdir)
        if out["reproduced"]:
            print("reproduced:")
            for v in out["violations"]:
                print(f"  {v}")
            return 0
        print("spec did NOT reproduce (clean run, no violation)",
              file=sys.stderr)
        return 1

    res = _fz.fuzz_batch(
        count=args.count, seed=args.seed, workdir=workdir,
        planted=args.plant, shrink=not args.no_shrink,
        n_requests=args.requests, log=lambda m: _echo(f"fuzz: {m}"),
    )
    if not res["violation_found"]:
        print(f"clean: {res['compositions_run']} compositions "
              f"(seed {args.seed}), no violation")
        return 0
    spec = res["spec"]
    print(f"VIOLATION after {res['compositions_run']} compositions, "
          f"shrunk to {len(spec['items'])} item(s):")
    for v in spec["violations"]:
        print(f"  {v}")
    print(json.dumps(spec, indent=2, sort_keys=True))
    if args.out:
        _fz.save_spec(spec, args.out)
        print(f"spec written to {args.out} "
              f"(replay: paddle-tpu fuzz --replay {args.out})")
    return 1


_COMMANDS = {
    "train": cmd_train,
    "version": cmd_version,
    "dump_config": cmd_dump_config,
    "make_diagram": cmd_make_diagram,
    "merge_model": cmd_merge_model,
    "plotcurve": cmd_plotcurve,
    "lint": cmd_lint,
    "explore": cmd_explore,
    "fuzz": cmd_fuzz,
    "cache": cmd_cache,
    "serve": cmd_serve,
    "route": cmd_route,
    "scenario": cmd_scenario,
    "trace": cmd_trace,
    "worker": cmd_worker,
    "master": cmd_master,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: paddle-tpu <command> [<flags>]")
        print("commands:")
        print("    train             train/test/time a v1 config (--job=...)")
        print("    version           print version + device info")
        print("    dump_config       print the resolved topology of a config")
        print("    make_diagram      write a Graphviz diagram of a config")
        print("    merge_model       bundle config + parameters into one file")
        print("    plotcurve         plot training curves from a log")
        print("    lint              static analysis: graph-lint a config, or")
        print("                      self-lint the package source")
        print("    explore           interleaving explorer: drive the real")
        print("                      router/master/HA state machines on a")
        print("                      virtual clock, hunt protocol-invariant")
        print("                      violations, shrink + replay specs")
        print("    fuzz              chaos-composition fuzzer: seeded fault")
        print("                      cocktails (arrival x chaos x netem x")
        print("                      torn checkpoints) vs the invariant set;")
        print("                      shrink + replay violation specs")
        print("    cache             AOT executable cache: ls / warm / prune /")
        print("                      clear a persistent compile cache dir")
        print("    serve             continuous-batching serving plane over")
        print("                      the NMT flagship (request queue + paged")
        print("                      decode cache, SLO admission/shedding,")
        print("                      SIGTERM graceful drain); --register")
        print("                      joins a fleet router as one engine")
        print("    route             serving-fleet frontend: SLO admission +")
        print("                      least-predicted-wait affinity routing")
        print("                      over registered engines (lease plane,")
        print("                      idempotent ledger, rolling restart)")
        print("    scenario          production-gate scenario harness: mixed")
        print("                      traffic + chaos under load, SLO metrics")
        print("    trace             merge/validate span-timeline files: zip")
        print("                      per-process traces into one Perfetto")
        print("                      timeline (clock-skew aligned via RPC)")
        print("    master            run an HA master candidate (elastic")
        print("                      scale-out: registry + shard leases)")
        print("    worker            run one elastic trainer process against")
        print("                      a master discovery directory")
        return 0 if argv else 1
    cmd, rest = argv[0], argv[1:]
    if cmd not in _COMMANDS:
        print(f"unknown command {cmd!r}; try 'paddle-tpu --help'", file=sys.stderr)
        return 1
    return _COMMANDS[cmd](rest)


if __name__ == "__main__":
    sys.exit(main())
