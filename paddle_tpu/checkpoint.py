"""Checkpoint / resume subsystem.

Three planes, mirroring the reference's three checkpoint stories:

1. **v1 parameter dirs** — ``pass-%05d/`` with one binary file per parameter
   (header: int32 version, uint32 value_size, uint64 count; then raw float32)
   exactly like the reference trainer's per-pass dumps (reference:
   paddle/parameter/Parameter.cpp save/load ~250-340, trainer/ParamUtil.cpp).

2. **v2 tar** — ``Parameters.to_tar/from_tar`` (already on Parameters;
   reference python/paddle/v2/parameters.py).

3. **Full training-state checkpoints** — params + layer state + optimizer
   state + counters in one atomically-renamed step directory with CRC32 and
   a JSON meta file, optionally written by a background thread (async), with
   retention.  This is the TPU-native replacement for the Go pserver's
   shard+optimizer-state checkpoint with md5/CRC + etcd meta (reference:
   go/pserver/service.go:244-303, paddle/optimizer/serialization.h) — except
   there is no pserver: the whole jit-visible state pytree is the checkpoint.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import tempfile
import threading
import time
import zlib
from typing import Any, Dict, Optional

import jax
import numpy as np

__all__ = [
    "save_parameter_dir",
    "load_parameter_dir",
    "CheckpointManager",
]

_V1_VERSION = 0
_V1_VALUE_SIZE = 4  # float32


# ---------------------------------------------------------------------------
# Plane 1: v1 per-parameter binary files
# ---------------------------------------------------------------------------

def save_parameter_dir(parameters, dirname: str) -> None:
    """One file per parameter named by its flattened key, v1 header layout."""
    os.makedirs(dirname, exist_ok=True)
    for name in parameters.names():
        arr = np.asarray(parameters.get(name), dtype=np.float32)
        with open(os.path.join(dirname, name.replace("/", "__")), "wb") as f:
            f.write(struct.pack("<iIQ", _V1_VERSION, _V1_VALUE_SIZE, arr.size))
            f.write(arr.tobytes())


def load_parameter_dir(parameters, dirname: str) -> None:
    for name in parameters.names():
        path = os.path.join(dirname, name.replace("/", "__"))
        with open(path, "rb") as f:
            version, value_size, count = struct.unpack("<iIQ", f.read(16))
            if version != _V1_VERSION or value_size != _V1_VALUE_SIZE:
                raise ValueError(
                    f"{path}: unsupported header version={version} "
                    f"value_size={value_size}"
                )
            data = np.frombuffer(f.read(count * value_size), dtype=np.float32)
        cur = np.asarray(parameters.get(name))
        if data.size != cur.size:
            raise ValueError(
                f"{path}: size {data.size} != parameter {name} size {cur.size}"
            )
        parameters.set(name, data.reshape(cur.shape).copy())


# ---------------------------------------------------------------------------
# Plane 3: full-state checkpoints
# ---------------------------------------------------------------------------

def _crc_file(path: str, block: int = 1 << 20) -> int:
    """Streaming CRC32 — O(1) memory for multi-GB checkpoints."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(block)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _flatten(tree) -> Dict[str, np.ndarray]:
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[jax.tree_util.keystr(path)] = np.asarray(jax.device_get(leaf))
    return out


def _unflatten_into(template, arrays: Dict[str, np.ndarray]):
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = jax.tree_util.keystr(path)
        if key not in arrays:
            raise KeyError(f"checkpoint missing leaf {key}")
        arr = arrays[key]
        want = np.shape(leaf)
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"leaf {key}: checkpoint shape {arr.shape} != template {want}"
            )
        new_leaves.append(arr.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


MANIFEST_NAME = "MANIFEST.json"


def _shard_file(shard_id: int, num_shards: int) -> str:
    return f"shard-{shard_id:05d}-of-{num_shards:05d}"


class CheckpointManager:
    """Step-indexed checkpoints under ``directory/ckpt-%08d/`` with atomic
    rename, CRC verification, retention, and optional async writes.

    Two write layouts share one read path:

    * **single-writer** (:meth:`save`) — ``state.npz`` + ``meta.json``,
      committed by atomically renaming the whole step directory;
    * **sharded multi-writer** (:meth:`save_shard` + :meth:`commit`) — each
      elastic worker writes ``shard-%05d-of-%05d.npz`` (its slice of the
      sorted leaf names, round-robin) plus a CRC sidecar straight into the
      step directory, and the step becomes restorable only when a
      ``MANIFEST.json`` lands via atomic rename.  A crash that strands a
      manifest-less shard set, or a torn shard under a committed manifest
      (CRC mismatch), makes that step unrestorable and
      :meth:`restore_latest` walks back to the previous complete manifest —
      the multi-writer generalization of the Go pserver's CRC-checked shard
      checkpoints (go/pserver/service.go:244-303)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None

    # -- write ----------------------------------------------------------
    def save(
        self,
        step: int,
        tree: Any,
        extra: Optional[Dict[str, Any]] = None,
        async_: bool = False,
    ) -> None:
        # Materialize on host *before* handing off so the training loop can
        # donate/overwrite device buffers immediately (orbax-style).
        arrays = _flatten(tree)
        self.wait()  # serialize with any in-flight async write
        if async_:

            def run():
                try:
                    self._write(step, arrays, extra)
                except BaseException as exc:  # surfaced by the next wait()
                    self._pending_error = exc

            # Non-daemon: interpreter exit joins it, so a checkpoint started
            # at the end of a script is never silently truncated.
            t = threading.Thread(target=run, name="paddle-ckpt-write",
                                 daemon=False)
            t.start()
            self._pending = t
        else:
            self._write(step, arrays, extra)

    def _write(self, step: int, arrays: Dict[str, np.ndarray], extra) -> None:
        final = os.path.join(self.directory, f"ckpt-{step:08d}")
        tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=self.directory)
        try:
            data_path = os.path.join(tmp, "state.npz")
            np.savez(data_path, **arrays)
            crc = _crc_file(data_path)
            meta = {
                "step": step,
                "crc32": crc,
                "timestamp": time.time(),
                "n_leaves": len(arrays),
                "extra": extra or {},
            }
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        from paddle_tpu.robustness import chaos as _chaos

        if _chaos.fire("torn_checkpoint"):
            # simulate a crash mid-write: the step dir exists, the data file
            # is truncated (restore must detect it and fall back)
            _chaos.tear_file(os.path.join(final, "state.npz"))
        self._retain()

    # -- sharded multi-writer plane (elastic scale-out) ------------------
    def save_shard(
        self,
        step: int,
        shard_id: int,
        num_shards: int,
        tree: Any,
        async_: bool = False,
    ) -> None:
        """Write THIS process's shard of the state pytree: the
        ``shard_id``-th slice of the sorted flattened leaf names, taken
        round-robin over ``num_shards``.  Host-materializes before handing
        off (the training loop may donate the device buffers immediately);
        ``async_=True`` runs the disk write off the hot path on a
        background thread — failures surface from :meth:`wait` and from the
        next ``save``/``save_shard``.  The step only becomes restorable
        once every shard landed and :meth:`commit` published the
        manifest."""
        # select THIS shard's leaves by key first, then device_get only
        # those: materializing the whole tree on every worker would pay N
        # full device-to-host transfers per checkpoint — the cost sharding
        # exists to avoid
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        keyed = {jax.tree_util.keystr(path): leaf for path, leaf in leaves}
        keys = sorted(keyed)[shard_id::num_shards]
        mine = {k: np.asarray(jax.device_get(keyed[k])) for k in keys}
        self.wait()  # serialize with (and surface) any in-flight write
        if async_:

            def run():
                try:
                    self._write_shard(step, shard_id, num_shards, mine)
                except BaseException as exc:  # surfaced by the next wait()
                    self._pending_error = exc

            t = threading.Thread(target=run, name="paddle-ckpt-shard",
                                 daemon=False)
            t.start()
            self._pending = t
        else:
            self._write_shard(step, shard_id, num_shards, mine)

    def _write_shard(
        self, step: int, shard_id: int, num_shards: int, arrays: Dict[str, np.ndarray]
    ) -> None:
        d = os.path.join(self.directory, f"ckpt-{step:08d}")
        os.makedirs(d, exist_ok=True)
        base = _shard_file(shard_id, num_shards)
        fd, tmp = tempfile.mkstemp(prefix=f".tmp-{base}-", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            crc = _crc_file(tmp)
            os.replace(tmp, os.path.join(d, base + ".npz"))
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        side = {"crc32": crc, "n_leaves": len(arrays)}
        side_tmp = os.path.join(d, "." + base + ".json.tmp")
        with open(side_tmp, "w") as f:
            json.dump(side, f)
        os.replace(side_tmp, os.path.join(d, base + ".json"))
        from paddle_tpu.robustness import chaos as _chaos

        if _chaos.fire("torn_checkpoint"):
            # crash-mid-write drill: the shard file is truncated AFTER its
            # CRC was recorded — a committed manifest must fail restore and
            # fall back to the previous complete one
            _chaos.tear_file(os.path.join(d, base + ".npz"))

    def commit(
        self,
        step: int,
        num_shards: int,
        extra: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Publish a sharded step: verify every shard (and its CRC sidecar)
        landed, then atomically rename ``MANIFEST.json`` into place — the
        single commit point that makes the step restorable.  Idempotent
        (True if a manifest already exists) and safe to attempt from every
        worker: returns False — without committing — while any shard is
        missing (e.g. its writer died before the write finished)."""
        d = os.path.join(self.directory, f"ckpt-{step:08d}")
        man_path = os.path.join(d, MANIFEST_NAME)
        if os.path.exists(man_path):
            return True
        shards: Dict[str, int] = {}
        n_leaves = 0
        for i in range(num_shards):
            base = _shard_file(i, num_shards)
            side_path = os.path.join(d, base + ".json")
            if not os.path.exists(os.path.join(d, base + ".npz")):
                return False
            try:
                with open(side_path) as f:
                    side = json.load(f)
            except (OSError, ValueError):
                return False
            shards[base + ".npz"] = side["crc32"]
            n_leaves += side.get("n_leaves", 0)
        manifest = {
            "step": step,
            "num_shards": num_shards,
            "shards": shards,
            "n_leaves": n_leaves,
            "timestamp": time.time(),
            "extra": extra or {},
        }
        tmp = man_path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, man_path)
        self._retain()
        return True

    def _retain(self) -> None:
        """Keep the newest ``max_to_keep`` COMMITTED steps.  Only committed
        steps count toward the quota and only steps OLDER than the oldest
        kept committed one are deleted: an uncommitted shard set that is
        still being written by other workers is always newer than the kept
        window and must never be reaped, while a stranded torn/uncommitted
        newest step must never push the last restorable manifest out."""
        committed = [s for s in self.all_steps() if self._is_committed(s)]
        if len(committed) <= self.max_to_keep:
            return
        keep_from = committed[-self.max_to_keep]
        for s in self.all_steps():
            if s < keep_from:
                shutil.rmtree(
                    os.path.join(self.directory, f"ckpt-{s:08d}"),
                    ignore_errors=True,
                )

    def _is_committed(self, step: int) -> bool:
        d = os.path.join(self.directory, f"ckpt-{step:08d}")
        return os.path.exists(os.path.join(d, "meta.json")) or os.path.exists(
            os.path.join(d, MANIFEST_NAME)
        )

    def wait(self) -> None:
        """Join any in-flight async write; re-raises its failure so a broken
        checkpoint never goes unnoticed."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._pending_error is not None:
            exc, self._pending_error = self._pending_error, None
            raise exc

    # -- read -----------------------------------------------------------
    def all_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt-"):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def meta(self, step: int) -> Dict[str, Any]:
        """The step's meta/manifest dict (meta.json for single-writer
        steps, MANIFEST.json for sharded ones)."""
        d = os.path.join(self.directory, f"ckpt-{step:08d}")
        for name in ("meta.json", MANIFEST_NAME):
            path = os.path.join(d, name)
            if os.path.exists(path):
                with open(path) as f:
                    return json.load(f)
        raise IOError(f"checkpoint {d}: no meta.json or {MANIFEST_NAME}")

    def restore(self, step: int, template: Any):
        """Verify CRC, then rebuild the pytree into `template`'s structure.
        Returns (tree, extra).  Sharded steps (MANIFEST.json) merge every
        shard, verifying each against its manifest CRC; an uncommitted
        shard set (no manifest) is unrestorable by definition."""
        d = os.path.join(self.directory, f"ckpt-{step:08d}")
        man_path = os.path.join(d, MANIFEST_NAME)
        if os.path.exists(man_path):
            with open(man_path) as f:
                manifest = json.load(f)
            arrays: Dict[str, np.ndarray] = {}
            for fname, crc in manifest["shards"].items():
                path = os.path.join(d, fname)
                if _crc_file(path) != crc:
                    raise IOError(
                        f"checkpoint shard {path} corrupt: crc mismatch vs "
                        f"manifest {crc:#x}"
                    )
                with np.load(path) as z:
                    arrays.update({k: z[k] for k in z.files})
            return _unflatten_into(template, arrays), manifest.get("extra", {})
        meta = self.meta(step)
        data_path = os.path.join(d, "state.npz")
        if _crc_file(data_path) != meta["crc32"]:
            raise IOError(
                f"checkpoint {d} corrupt: crc mismatch vs meta {meta['crc32']:#x}"
            )
        with np.load(data_path) as z:
            arrays = {k: z[k] for k in z.files}
        return _unflatten_into(template, arrays), meta.get("extra", {})

    def restore_latest(self, template: Any):
        """Newest RESTORABLE checkpoint as ``(step, tree, extra)`` — or None
        when the directory holds none that loads.

        Unlike :meth:`restore` (strict: a caller naming a step deserves the
        error), this walks newest → oldest past torn/corrupt step dirs: a
        truncated ``state.npz`` (crash mid-write), a CRC mismatch (bit rot),
        or a missing ``meta.json`` must never brick a resume while an older
        retained checkpoint is intact — the Go pserver's checkpoint loader
        takes the same stance (service.go:244: a bad CRC fails over rather
        than wedging the shard)."""
        import logging

        log = logging.getLogger("paddle_tpu.checkpoint")
        for step in reversed(self.all_steps()):
            try:
                tree, extra = self.restore(step, template)
            except Exception as exc:  # noqa: BLE001 — any torn artifact
                log.warning(
                    "checkpoint ckpt-%08d unusable (%s: %s); falling back "
                    "to the previous retained checkpoint",
                    step, type(exc).__name__, exc,
                )
                continue
            return step, tree, extra
        return None
