"""CRC-framed append-only journal for the master's durable state plane.

The reference Go master journals every queue transition into etcd so a
standby that wins the next campaign RESUMES the job instead of restarting
it (go/master/etcd_client.go; the TF fault-tolerance model of
arXiv:1605.08695 §4.4).  etcd-free equivalent: one append-only file next to
the ``master_state.json`` snapshot.  The snapshot is the compaction target
(periodically rewritten), the journal is the fsync'd delta on top of it —
recovery = load snapshot, replay the journal records whose ``seq`` exceeds
the snapshot's.

Frame format (all integers big-endian)::

    MAGIC(4) | seq(8) | length(4) | crc32(4) | payload(length)

``crc32`` covers ``seq|length|payload``, so a torn header, a torn payload
and a bit-flipped record are all detected.  The payload is the dict
``{"t": <record type>, ...}`` in the master_wire restricted typed encoding
(``PTJ2`` frames) — the same safe codec the RPC plane the records arrived
on uses, so numpy gradient trees round-trip bit-exactly and a damaged or
foreign payload can never execute.  Pre-wire-codec generations (``PTJ1``
frames, payload pickled) remain READABLE for the one upgrade boot that
replays them; everything written from then on is ``PTJ2`` (the first
compaction rewrites the plane).

Durability discipline:

* every append is ``flush`` + ``fsync`` before the RPC that caused it is
  acknowledged — a worker that saw ``task_finished`` return True can rely
  on the result surviving a master kill -9;
* an incomplete final frame (crash mid-append) is TOLERATED on replay: the
  journal is a prefix-consistent history, so recovery applies the prefix
  and moves on;
* a CRC-corrupt COMPLETE frame is flagged (``corrupt``) — replay still
  stops at the prefix (never applies unverifiable bytes), but the journal
  lint reports it as an error so an operator sees silent media rot;
* an UNKNOWN record type is a hard error everywhere: a typo'd or
  version-skewed record must never be silently dropped from a recovery.

Generations: compaction writes a NEW journal file (``master_journal-
NNNNNN.log``), re-emits the retained per-pass results into it, then
atomically publishes a snapshot referencing it; the old generation is
deleted only after the snapshot rename lands.  A deposed leader that
somehow keeps appending writes to a generation no snapshot references —
the second fence behind the HA lease.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

from paddle_tpu import master_wire as _wire

__all__ = [
    "MAGIC",
    "MAGIC_V1",
    "RECORD_TYPES",
    "JournalError",
    "JournalWriter",
    "encode_frame",
    "read_records",
    "verify_journal",
    "journal_filename",
    "parse_generation",
]

MAGIC = b"PTJ2"      # payload = master_wire restricted typed encoding
MAGIC_V1 = b"PTJ1"   # legacy: payload pickled (read-only upgrade path)
_HEADER = struct.Struct(">QI")  # seq, payload length
_CRC = struct.Struct(">I")
_FRAME_OVERHEAD = len(MAGIC) + _HEADER.size + _CRC.size

# every record type the replay plane understands; replaying (or linting) a
# record outside this set is a HARD error — version skew and corruption
# must never be silently dropped from a recovery
RECORD_TYPES = frozenset({
    "lease",     # todo -> pending (task, epoch, worker)
    "finish",    # pending/todo -> done, + per-pass result payload
    "fail",      # pending -> todo|discarded via the failure_max discipline
    "ret",       # pending -> todo, no failure event (graceful give-back)
    "rotate",    # pass boundary: done -> todo, pass_id++
    "frotate",   # forced rotation: every live worker attested the pass
                 # was applied on a deposed leader (failover-regression
                 # heal) — whole queue recycles, result map poisoned
    "unres",     # requeue_unresulted: done -> todo (results lost)
    "join",      # worker registry join
    "leave",     # worker registry leave (graceful or pruned)
    "farrive",   # fence arrival (first arrival per worker, with meta)
    "frelease",  # fence release (frozen membership view)
})

# how many trailing passes of result maps compaction re-emits mirrors the
# Service's own retention (see Service._rotate_pass)


class JournalError(RuntimeError):
    """The journal cannot be (fully) trusted: unknown record type,
    non-monotonic sequence, or a caller asked for strict framing."""


def encode_frame(seq: int, record: Dict[str, Any]) -> bytes:
    payload = _wire.encode_payload(record)
    header = _HEADER.pack(seq, len(payload))
    crc = zlib.crc32(header + payload) & 0xFFFFFFFF
    return MAGIC + header + _CRC.pack(crc) + payload


class JournalWriter:
    """Appender for one journal generation.  ``fsync=False`` is for tests
    that grind thousands of records; production masters keep it on — the
    append is the durability point the RPC ack stands on."""

    def __init__(self, path: str, fsync: bool = True, fresh: bool = True,
                 exclusive: bool = False):
        self.path = path
        self.fsync = fsync
        # exclusive: refuse to open a generation file someone else already
        # created (FileExistsError) — compaction's collision fence
        mode = "xb" if exclusive else ("wb" if fresh else "ab")
        self._f = open(path, mode)

    def append(self, seq: int, record: Dict[str, Any],
               sync: bool = True) -> int:
        from paddle_tpu import obs as _obs

        # the fsync here is what every RPC ack's durability stands on —
        # exactly the hold a merged timeline must show when a drill asks
        # "where did the ack latency go"
        with _obs.span(
            "journal_append", cat="master", seq=seq, t=record.get("t"),
        ):
            frame = encode_frame(seq, record)
            self._f.write(frame)
            if sync:
                self.sync()
        return len(frame)

    def sync(self) -> None:
        """Flush + fsync everything appended so far.  ``sync=False``
        appends (compaction's bulk re-emission) stand on one trailing
        call here — same crash ordering, one fsync instead of N."""
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def _iter_frames(
    data: bytes, base_offset: int = 0
) -> Iterator[Tuple[int, int, Dict[str, Any]]]:
    """Yield ``(end_offset, seq, record)`` per valid frame; raise
    ``_Torn``/``_Corrupt`` (internal) at the first bad frame."""
    o = 0
    n = len(data)
    while o < n:
        if n - o < _FRAME_OVERHEAD:
            raise _Torn(base_offset + o)
        magic = data[o : o + 4]
        if magic not in (MAGIC, MAGIC_V1):
            raise _Corrupt(base_offset + o, "bad frame magic")
        seq, length = _HEADER.unpack_from(data, o + 4)
        payload_start = o + _FRAME_OVERHEAD
        if payload_start + length > n:
            # the frame claims more bytes than the file holds: a crash
            # mid-append (torn tail) — or a corrupt length field, which is
            # indistinguishable without trusting the corrupt bytes
            raise _Torn(base_offset + o)
        (crc,) = _CRC.unpack_from(data, o + 4 + _HEADER.size)
        blob = data[payload_start : payload_start + length]
        want = zlib.crc32(data[o + 4 : o + 4 + _HEADER.size] + blob) & 0xFFFFFFFF
        if crc != want:
            raise _Corrupt(base_offset + o, "crc mismatch")
        try:
            if magic == MAGIC_V1:
                record = pickle.loads(blob)  # wire: allow[A206] pre-wire-codec (PTJ1) journal generations pickled their payloads; this CRC-verified, operator-owned local file is replayed exactly once at the upgrade boot — the first compaction rewrites the plane as PTJ2
            else:
                record = _wire.decode_payload(blob)
        except Exception as exc:  # noqa: BLE001 — any undecodable payload
            raise _Corrupt(base_offset + o, f"undecodable payload: {exc!r}")
        # end offset is ABSOLUTE (base_offset + position in this read):
        # a tailer feeds it straight back as its next resume offset
        yield base_offset + payload_start + length, seq, record
        o = payload_start + length


class _Torn(Exception):
    def __init__(self, offset: int):
        self.offset = offset


class _Corrupt(Exception):
    def __init__(self, offset: int, why: str):
        self.offset = offset
        self.why = why


def read_records(
    path: str, offset: int = 0
) -> Tuple[List[Tuple[int, Dict[str, Any]]], Dict[str, Any]]:
    """Read every complete, CRC-verified frame from ``offset`` on.

    Returns ``(records, info)`` where records is ``[(seq, record), ...]``
    and info carries ``end_offset`` (byte position after the last good
    frame — a tailer resumes here), ``torn`` (incomplete final frame:
    expected after a crash mid-append, tolerated), and ``corrupt`` (a
    COMPLETE frame failed its CRC or didn't decode: media rot / foreign
    bytes; replay still stops at the good prefix, the lint flags it)."""
    with open(path, "rb") as f:
        f.seek(offset)
        data = f.read()
    records: List[Tuple[int, Dict[str, Any]]] = []
    info: Dict[str, Any] = {
        "end_offset": offset, "torn": False, "corrupt": False, "error": None,
    }
    try:
        for end, seq, rec in _iter_frames(data, offset):
            records.append((seq, rec))
            info["end_offset"] = end
    except _Torn as t:
        info["torn"] = True
        info["error"] = f"incomplete frame at byte {t.offset}"
    except _Corrupt as c:
        info["corrupt"] = True
        info["error"] = f"{c.why} at byte {c.offset}"
    return records, info


def verify_journal(path: str) -> List[Dict[str, str]]:
    """Journal lint: framing, CRC, record-type and sequence checks.

    Returns a list of ``{"rule", "severity", "message"}`` findings (empty =
    clean) — ``paddle-tpu lint --journal`` maps them onto the shared
    diagnostic model.  Rules:

    * J001 — framing/CRC corruption (complete frame failed verification)
    * J002 — unknown record type (hard error: version skew or corruption)
    * J003 — non-monotonic sequence numbers
    * J004 — torn final frame (warning: expected after a crash mid-append)
    """
    findings: List[Dict[str, str]] = []
    try:
        records, info = read_records(path)
    except OSError as exc:
        return [{"rule": "J001", "severity": "error",
                 "message": f"unreadable journal {path}: {exc}"}]
    if info["corrupt"]:
        findings.append({
            "rule": "J001", "severity": "error",
            "message": f"{path}: {info['error']} — replay stops at the "
                       f"good prefix ({len(records)} records)",
        })
    elif info["torn"]:
        findings.append({
            "rule": "J004", "severity": "warning",
            "message": f"{path}: {info['error']} (torn tail — a crash "
                       f"mid-append; the prefix is consistent)",
        })
    last_seq: Optional[int] = None
    for seq, rec in records:
        t = rec.get("t") if isinstance(rec, dict) else None
        if t not in RECORD_TYPES:
            findings.append({
                "rule": "J002", "severity": "error",
                "message": f"{path}: unknown record type {t!r} at seq "
                           f"{seq} — refusing to interpret (version skew?)",
            })
        if last_seq is not None and seq <= last_seq:
            findings.append({
                "rule": "J003", "severity": "error",
                "message": f"{path}: sequence went {last_seq} -> {seq} "
                           f"(journal records must be strictly increasing)",
            })
        last_seq = seq
    return findings


def journal_filename(generation: int) -> str:
    return f"master_journal-{generation:06d}.log"


def parse_generation(filename: str) -> int:
    """Generation number from a journal filename; 0 when unparseable."""
    base = os.path.basename(filename)
    if base.startswith("master_journal-") and base.endswith(".log"):
        try:
            return int(base[len("master_journal-"):-len(".log")])
        except ValueError:
            pass
    return 0
