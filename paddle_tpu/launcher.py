"""Multi-host cluster launcher — the TPU-native replacement for the
reference's fabric/ssh job pusher (paddle/scripts/cluster_train/paddle.py)
and its pserver/trainer process zoo.

On TPU there are no parameter-server processes to start: every host runs the
SAME SPMD program and jax.distributed forms the global mesh over ICI/DCN.
So the launcher's job collapses to (1) computing each worker's environment
(coordinator address, process id/count), (2) starting one python per host —
locally via subprocess, remotely by emitting/executing ssh commands — and
(3) `init_cluster()` inside the training script wiring jax.distributed.

Usage, in the training script::

    import paddle_tpu as paddle
    paddle.launcher.init_cluster()   # no-op single-host; env-driven multi

then either run it directly (single host) or::

    python -m paddle_tpu.launcher --hosts h1,h2,h3,h4 \
        --coordinator h1:8476 train.py --args...
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

ENV_COORD = "PADDLE_TPU_COORDINATOR"
ENV_NPROC = "PADDLE_TPU_NUM_PROCESSES"
ENV_PROC_ID = "PADDLE_TPU_PROCESS_ID"


def init_cluster(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    use_jax_distributed: Optional[bool] = None,
):
    """Join the cluster if a multi-process environment is configured;
    returns the formed :class:`~paddle_tpu.parallel.mesh.ProcessGroup`
    (truthy exactly when a multi-process group exists).  Call before any
    other jax use.  Single-host (no env): no-op group — the reference's
    `paddle.init(trainer_count=...)` local mode.

    On TPU pods (``PADDLE_TPU_DIST_BACKEND=jax``) this is a real
    ``jax.distributed`` runtime; elsewhere it is the subprocess/CPU shim —
    membership is recorded and cross-process reduction rides the master
    coordination plane (see parallel/mesh.py init_process_group)."""
    from paddle_tpu.parallel.mesh import init_process_group

    return init_process_group(
        coordinator, num_processes, process_id,
        use_jax_distributed=use_jax_distributed,
    )


def build_worker_env(
    coordinator: str, num_processes: int, process_id: int
) -> Dict[str, str]:
    """Environment fragment for one worker process.  The launcher host's
    PADDLE_TPU_DIST_BACKEND choice travels with the job — remote (ssh)
    workers only see the inlined fragment, and without it they would
    silently fall back to the coordination-service shim on a pod where the
    operator asked for the real jax.distributed runtime."""
    env = {
        ENV_COORD: coordinator,
        ENV_NPROC: str(num_processes),
        ENV_PROC_ID: str(process_id),
    }
    backend = os.environ.get("PADDLE_TPU_DIST_BACKEND")
    if backend:
        env["PADDLE_TPU_DIST_BACKEND"] = backend
    return env


def build_commands(
    hosts: Sequence[str],
    coordinator: str,
    script: str,
    script_args: Sequence[str] = (),
    python: str = sys.executable,
    workdir: Optional[str] = None,
    extra_env: Optional[Dict[int, Dict[str, str]]] = None,
) -> List[List[str]]:
    """One command per host: local hosts (localhost/127.0.0.1) run directly,
    remote hosts through ssh with the env inlined — the reference pushed
    jobs with fabric the same way (cluster_train/paddle.py job_start).

    ``extra_env``: per-process-id environment additions — how a chaos drill
    arms a fault point (e.g. ``PADDLE_TPU_CHAOS=kill_worker@2``) on worker
    k of N and nowhere else."""
    cmds: List[List[str]] = []
    for pid, host in enumerate(hosts):
        env = build_worker_env(coordinator, len(hosts), pid)
        env.update((extra_env or {}).get(pid, {}))
        assignments = [f"{k}={v}" for k, v in env.items()]
        base = [python, script, *script_args]
        if host in ("localhost", "127.0.0.1"):
            cmds.append(["env", *assignments, *base])
        else:
            remote = " ".join(
                ["cd", shlex.quote(workdir or "."), "&&", "env"]
                + assignments
                + [shlex.quote(c) for c in base]
            )
            cmds.append(["ssh", host, remote])
    return cmds


def launch(
    hosts: Sequence[str],
    coordinator: str,
    script: str,
    script_args: Sequence[str] = (),
    workdir: Optional[str] = None,
    poll_interval: float = 0.2,
    elastic: bool = False,
    extra_env: Optional[Dict[int, Dict[str, str]]] = None,
    exit_codes: Optional[List[int]] = None,
    sleep=time.sleep,
) -> int:
    """Start every worker and wait.

    Default (gang) mode: the first worker to exit NONZERO kills the rest (a
    dead coordinator would otherwise hang every other process inside
    jax.distributed.initialize — the reference fabric launcher tears the
    job down on first failure too); returns the first nonzero exit code.

    ``elastic=True`` (the lease-based membership mode): a dying worker is
    the EXPECTED failure case, not the job's — survivors keep running, the
    master requeues the dead worker's shard leases, and the launcher simply
    waits for everyone.  Returns 0 when at least one worker finished
    cleanly, else the first nonzero code.  ``exit_codes`` (when given) is
    extended with every worker's return code in process-id order, so a
    chaos harness can assert the kill actually landed."""
    procs = [
        subprocess.Popen(cmd)
        for cmd in build_commands(
            hosts, coordinator, script, script_args, workdir=workdir,
            extra_env=extra_env,
        )
    ]
    rc = 0
    try:
        if elastic:
            for p in procs:
                p.wait()
            codes = [p.returncode or 0 for p in procs]
            rc = 0 if any(c == 0 for c in codes) else next(
                (c for c in codes if c), 0
            )
        else:
            while rc == 0 and any(p.poll() is None for p in procs):
                rc = next(
                    (p.poll() for p in procs if p.poll() not in (None, 0)), 0
                )
                if rc == 0:
                    sleep(poll_interval)
            if rc:  # tear the job down on first failure
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                rc = rc or (p.returncode or 0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if exit_codes is not None:
            exit_codes.extend(p.returncode or 0 for p in procs)
    return rc


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.launcher",
        description="Launch one SPMD training process per host.",
    )
    ap.add_argument("--hosts", required=True, help="comma-separated host list")
    ap.add_argument(
        "--coordinator",
        required=True,
        help="host:port of process 0's jax.distributed coordinator",
    )
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--dry-run", action="store_true", help="print commands only")
    ap.add_argument(
        "--elastic", action="store_true",
        help="lease-based membership: a dying worker is tolerated (its "
        "shard leases requeue via the master) instead of tearing down the "
        "job",
    )
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    hosts = [h.strip() for h in args.hosts.split(",") if h.strip()]
    if args.dry_run:
        for cmd in build_commands(
            hosts, args.coordinator, args.script, args.script_args, workdir=args.workdir
        ):
            print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    return launch(
        hosts, args.coordinator, args.script, args.script_args,
        workdir=args.workdir, elastic=args.elastic,
    )


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
