"""Activation objects — the ``paddle.v2.activation`` surface (reference:
python/paddle/trainer_config_helpers/activations.py).  Layer functions accept
either these objects or plain strings."""

from __future__ import annotations


class BaseActivation:
    name = "identity"

    def __repr__(self) -> str:  # pragma: no cover
        return f"Activation({self.name})"


def _make(name_: str):
    cls = type(name_.title().replace("_", ""), (BaseActivation,), {"name": name_})
    return cls


Identity = _make("identity")
Linear = Identity
Sigmoid = _make("sigmoid")
Softmax = _make("softmax")
SequenceSoftmax = _make("sequence_softmax")
Relu = _make("relu")
BRelu = _make("brelu")
Tanh = _make("tanh")
STanh = _make("stanh")
SoftRelu = _make("softrelu")
Abs = _make("abs")
Square = _make("square")
Exp = _make("exponential")
Reciprocal = _make("reciprocal")
Sqrt = _make("sqrt")
Log = _make("log")


def act_name(act) -> str:
    """Normalize an activation argument (object, string, or None) and
    validate it against the registry so typos fail at model-build time."""
    from paddle_tpu.ops.activations import get_activation

    if act is None:
        return "identity"
    if isinstance(act, str):
        name = act
    elif isinstance(act, BaseActivation) or hasattr(act, "name"):
        name = act.name
    elif isinstance(act, type) and issubclass(act, BaseActivation):
        name = act.name
    else:
        raise TypeError(f"bad activation: {act!r}")
    get_activation(name)  # raises KeyError with the known-names list
    return name
