"""Test/dev-environment helpers.

The ambient environment pins jax to the single-chip `axon` TPU backend via a
sitecustomize that registers the PJRT plugin at interpreter start, so env
vars alone cannot switch platforms after startup — processes that need the
virtual multi-device CPU mesh must re-exec themselves once with the hook
disabled.  Shared by tests/conftest.py and __graft_entry__.py.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

REEXEC_SENTINEL = "PADDLE_TPU_CPU_MESH_REEXEC"


def ensure_cpu_mesh(argv: Optional[List[str]] = None, device_count: int = 8) -> None:
    """Re-exec the current process on a `device_count`-device virtual CPU
    mesh if the axon TPU hook is active.  `argv` overrides the re-exec
    command (default: preserve sys.argv)."""
    if not os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get(REEXEC_SENTINEL):
        os.environ.setdefault(
            "XLA_FLAGS", f"--xla_force_host_platform_device_count={device_count}"
        )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={device_count}"
    ).strip()
    env[REEXEC_SENTINEL] = "1"
    cmd = [sys.executable] + (argv if argv is not None else sys.argv)
    os.execve(sys.executable, cmd, env)
