"""Test/dev-environment helpers.

The ambient environment pins jax to the single-chip `axon` TPU backend via a
sitecustomize that registers the PJRT plugin at interpreter start, so env
vars alone cannot switch platforms after startup — processes that need the
virtual multi-device CPU mesh must re-exec themselves once with the hook
disabled.  Shared by tests/conftest.py and __graft_entry__.py.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

REEXEC_SENTINEL = "PADDLE_TPU_CPU_MESH_REEXEC"


def ensure_cpu_mesh(argv: Optional[List[str]] = None, device_count: int = 8) -> None:
    """Re-exec the current process on a `device_count`-device virtual CPU
    mesh if the axon TPU hook is active.  `argv` overrides the re-exec
    command (default: preserve sys.argv)."""
    if not os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get(REEXEC_SENTINEL):
        os.environ.setdefault(
            "XLA_FLAGS", f"--xla_force_host_platform_device_count={device_count}"
        )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={device_count}"
    ).strip()
    env[REEXEC_SENTINEL] = "1"
    cmd = [sys.executable] + (argv if argv is not None else sys.argv)
    os.execve(sys.executable, cmd, env)


def stage_reference_rnn_benchmark(
    dest: str, n: int = 64, seq_len: int = 100, vocab: int = 30000,
    seed: int = 0, min_seq_len: int = 0,
) -> None:
    """Stage the reference's rnn benchmark (benchmark/paddle/rnn) into
    ``dest`` with a synthesized ``imdb.train.pkl`` in the provider's exact
    pickle schema — ``(list_of_token_lists, labels)`` consumed by
    provider.py:process — plus a ``train.list`` of absolute paths.  Used
    by bench.py (full size) and the v1_compat test (tiny) so the schema
    lives in one place; zero-egress stand-in for the IMDB download that
    imdb.create_data would otherwise attempt.

    min_seq_len=0 keeps every review at exactly ``seq_len`` tokens (the
    fixed-shape bench); a positive value draws short-skewed review lengths
    in [min_seq_len, seq_len] (beta(2,3), IMDB-like) for the bucketing
    A/B."""
    import pickle
    import shutil

    import numpy as np

    src = "/root/reference/benchmark/paddle/rnn"
    for fn in ("rnn.py", "provider.py", "imdb.py"):
        shutil.copy(os.path.join(src, fn), dest)
    rng = np.random.RandomState(seed)
    if min_seq_len:
        lens = min_seq_len + np.floor(
            (seq_len - min_seq_len + 1) * rng.beta(2.0, 3.0, size=n)
        ).astype(int)
    else:
        lens = np.full(n, seq_len, int)
    x = [
        [int(t) for t in rng.randint(2, vocab, size=int(l))]
        for l in lens
    ]
    y = [int(v) for v in rng.randint(0, 2, size=n)]
    pkl = os.path.join(dest, "imdb.train.pkl")
    with open(pkl, "wb") as f:
        pickle.dump((x, y), f, protocol=2)
    with open(os.path.join(dest, "train.list"), "w") as f:
        f.write(pkl + "\n")
