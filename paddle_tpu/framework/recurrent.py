"""RecurrentOp — the op-framework RNN container (reference:
paddle/operators/recurrent_op.cc/h + rnn/ helpers: segments each inlink along
time, keeps a vector of per-step Scopes, and links memories
pre_state↔state).

TPU-native: there are no per-step scopes — the step net's trace becomes the
body of one ``jax.lax.scan`` over the time-major inlinks, memories are the
scan carry, and outlinks stack to [T, ...] arrays.  One compiled while-loop
instead of T interpreter invocations."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework.net import NetOp


class RecurrentOp:
    """inlinks: scope var → step var ([T, ...] sliced per step);
    memories: (pre_state, state, boot_var) triples;
    outlinks: step vars stacked back to [T, ...]."""

    type = "recurrent_op"

    def __init__(
        self,
        step_net: NetOp,
        inlinks: Dict[str, str],
        outlinks: Sequence[str],
        memories: Sequence[Tuple[str, str, str]] = (),
    ):
        self.step_net = step_net
        self.inlinks = dict(inlinks)
        self.outlinks = list(outlinks)
        self.memories = list(memories)
        pre_names = {pre for pre, _, _ in memories}
        self.static_inputs = [
            n
            for n in step_net.input_names()
            if n not in set(self.inlinks.values()) and n not in pre_names
        ]

    def input_names(self) -> List[str]:
        return (
            list(self.inlinks.keys())
            + [boot for _, _, boot in self.memories]
            + self.static_inputs
        )

    def output_names(self) -> List[str]:
        return list(self.outlinks)

    def trace(self, values: Dict[str, Any]) -> Dict[str, Any]:
        static_vals = {n: values[n] for n in self.static_inputs}
        boot = {
            state: values[boot_name]
            for _, state, boot_name in self.memories
        }
        xs = {step_var: values[v] for v, step_var in self.inlinks.items()}

        def body(carry, x_slices):
            step_values = dict(static_vals)
            step_values.update(x_slices)
            for pre, state, _ in self.memories:
                step_values[pre] = carry[state]
            step_values = self.step_net.trace(step_values)
            new_carry = {state: step_values[state] for _, state, _ in self.memories}
            outs = {o: step_values[o] for o in self.outlinks}
            return new_carry, outs

        _, stacked = jax.lax.scan(body, boot, xs)
        new_values = dict(values)
        for o in self.outlinks:
            new_values[o] = stacked[o]
        return new_values

    def run(self, scope) -> None:
        values = {
            n: jnp.asarray(scope.get_var(n).get()) for n in self.input_names()
        }
        out = jax.jit(self.trace)(values)
        for n in self.output_names():
            scope.new_var(n).set(np.asarray(out[n]))

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"RecurrentOp(inlinks={self.inlinks}, outlinks={self.outlinks}, "
            f"memories={self.memories})"
        )
