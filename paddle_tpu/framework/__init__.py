"""Op-level framework face — parity with the reference's embryonic
``paddle/framework`` + ``paddle/operators`` design (reference:
paddle/framework/operator.h, op_registry.h:338, scope.h:36, net_op.h,
backward.cc, python/paddle/v2/framework/).

The reference interprets a NetOp op-list one OperatorBase::Run at a time per
device.  Here an op graph *lowers to a single XLA computation*: each op is a
pure jax-traceable function; ``NetOp.lower()``/``Scope.run`` trace the whole
list into one jitted HLO program (the OpDesc→HLO north star), and
``Backward`` derives the gradient program with jax.vjp instead of per-op
symbolic grad ops.
"""

from paddle_tpu.framework.scope import Scope, Variable  # noqa: F401
from paddle_tpu.framework.op import (  # noqa: F401
    Operator,
    OpRegistry,
    create_op,
    register_op,
)
from paddle_tpu.framework.net import NetOp  # noqa: F401
from paddle_tpu.framework.backward import Backward, BackwardOp  # noqa: F401
from paddle_tpu.framework.recurrent import RecurrentOp  # noqa: F401
from paddle_tpu.framework import ops  # noqa: F401  (registers the op set)
from paddle_tpu.framework.gradient_checker import (  # noqa: F401
    check_gradients,
    numeric_gradient,
)
