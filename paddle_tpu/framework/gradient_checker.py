"""Numeric-vs-analytic gradient checking for ops (reference:
python/paddle/v2/framework/tests/gradient_checker.py; the same
finite-difference strategy as gserver's LayerGradUtil)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework.backward import Backward, grad_name
from paddle_tpu.framework.scope import Scope


def numeric_gradient(op, inputs: Dict[str, np.ndarray], wrt: str,
                     out_grads: Optional[Dict[str, np.ndarray]] = None,
                     delta: float = 1e-3) -> np.ndarray:
    """Central finite differences of sum(outputs · out_grads) w.r.t. `wrt`."""
    out_names = op.output_names()

    def objective(vals: Dict[str, np.ndarray]) -> float:
        traced = op.trace({k: jnp.asarray(v) for k, v in vals.items()})
        total = 0.0
        for n in out_names:
            o = np.asarray(traced[n], dtype=np.float64)
            g = (
                np.asarray(out_grads[n], dtype=np.float64)
                if out_grads is not None
                else np.ones_like(o)
            )
            total += float(np.sum(o * g))
        return total

    base = {k: np.array(v, dtype=np.float64) for k, v in inputs.items()}
    x = base[wrt]
    grad = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        plus = objective(base)
        flat[i] = orig - delta
        minus = objective(base)
        flat[i] = orig
        gflat[i] = (plus - minus) / (2 * delta)
    return grad


def check_gradients(op, inputs: Dict[str, np.ndarray],
                    wrt: Optional[Sequence[str]] = None,
                    out_grads: Optional[Dict[str, np.ndarray]] = None,
                    rtol: float = 1e-2, atol: float = 1e-3) -> None:
    """Assert the BackwardOp's analytic grads match finite differences."""
    scope = Scope()
    for k, v in inputs.items():
        scope.new_var(k).set(np.asarray(v, np.float32))
    op.run(scope)
    bwd = Backward(op)
    if out_grads is not None:
        for n, g in out_grads.items():
            scope.new_var(grad_name(n)).set(np.asarray(g, np.float32))
    bwd.run(scope)
    targets = list(wrt) if wrt is not None else bwd.grad_inputs
    for name in targets:
        analytic = np.asarray(scope.get_var(grad_name(name)).get())
        numeric = numeric_gradient(op, inputs, name, out_grads)
        np.testing.assert_allclose(
            analytic, numeric, rtol=rtol, atol=atol,
            err_msg=f"gradient mismatch for {name!r} of op {op!r}",
        )
