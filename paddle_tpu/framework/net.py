"""NetOp — sequential op container that LOWERS TO ONE XLA PROGRAM
(reference: paddle/operators/net_op.h — there it *interprets* the list,
op->Run per op; here ``lower()`` traces every op into a single jitted
function, the OpDesc→HLO lowering the north star names)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework.op import Operator, create_op


class NetOp:
    """add_op(...) ops in order; complete_add_op() computes the net's
    external inputs/outputs by dataflow (reference net_op.cc
    CompleteAddOp dedup of in/out)."""

    type = "plain_net"

    def __init__(self, ops: Optional[Sequence[Operator]] = None):
        self.ops: List[Operator] = list(ops or [])
        self._complete = False
        self.external_inputs: List[str] = []
        self.external_outputs: List[str] = []

    def add_op(self, op) -> "NetOp":
        if self._complete:
            raise RuntimeError("cannot add_op after complete_add_op")
        self.ops.append(op)
        return self

    def complete_add_op(self) -> "NetOp":
        produced: List[str] = []
        needed: List[str] = []
        for op in self.ops:
            for n in op.input_names():
                if n not in produced and n not in needed:
                    needed.append(n)
            for n in op.output_names():
                if n not in produced:
                    produced.append(n)
        self.external_inputs = needed
        self.external_outputs = produced
        self._complete = True
        return self

    # -- introspection ---------------------------------------------------
    def input_names(self) -> List[str]:
        if not self._complete:
            self.complete_add_op()
        return list(self.external_inputs)

    def output_names(self) -> List[str]:
        if not self._complete:
            self.complete_add_op()
        return list(self.external_outputs)

    def infer_shape(self, scope) -> None:
        for op in self.ops:
            op.infer_shape(scope)

    # -- lowering --------------------------------------------------------
    def trace(self, values: Dict[str, Any]) -> Dict[str, Any]:
        for op in self.ops:
            values = op.trace(values)
        return values

    def lower(self):
        """jit-compiled fn(*external_input_arrays) -> tuple(external_outputs).
        The whole net is ONE HLO computation — XLA fuses across op
        boundaries, unlike the reference's per-op Run interpreter."""
        in_names = self.input_names()
        out_names = self.output_names()

        @jax.jit
        def fn(*arrays):
            values = dict(zip(in_names, arrays))
            values = self.trace(values)
            return tuple(values[n] for n in out_names)

        return fn

    def run(self, scope) -> None:
        """Execute against a scope via the lowered program."""
        fn = self.lower()
        args = [jnp.asarray(scope.get_var(n).get()) for n in self.input_names()]
        outs = fn(*args)
        for n, o in zip(self.output_names(), outs):
            scope.new_var(n).set(np.asarray(o))

    def __repr__(self) -> str:  # pragma: no cover
        body = "\n  ".join(repr(op) for op in self.ops)
        return f"NetOp[\n  {body}\n]"


def fc_net(x: str, w: str, b: Optional[str], out: str, *, hidden: str = None) -> NetOp:
    """The fc composite op (reference paddle/operators/fc_op.cc builds
    mul + rowwise_add + sigmoid via NetOp)."""
    hidden = hidden or out + "@mul"
    net = NetOp()
    net.add_op(create_op("mul", X=x, Y=w, Out=hidden))
    if b is not None:
        added = out + "@add"
        net.add_op(create_op("rowwise_add", X=hidden, b=b, Out=added))
        hidden = added
    net.add_op(create_op("sigmoid", X=hidden, Y=out))
    net.complete_add_op()
    return net
