"""Scope/Variable — the hierarchical name→value store (reference:
paddle/framework/scope.h:36, variable.h).  Values are host numpy or jax
arrays; ops never mutate them in place — Run() writes fresh arrays, keeping
the store compatible with functional jax execution."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Variable:
    """A named slot.  `value` is the tensor (numpy/jax array) or None until
    set; get_dims mirrors the reference Tensor::dims."""

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[Any] = None

    def set(self, value) -> "Variable":
        self.value = value
        return self

    def get(self):
        return self.value

    def set_dims(self, dims) -> "Variable":
        """Pre-allocate by shape (reference tensor.mutable_data pattern)."""
        self.value = np.zeros(tuple(dims), dtype=np.float32)
        return self

    @property
    def shape(self):
        return None if self.value is None else tuple(np.shape(self.value))


class Scope:
    """Hierarchical variable scope (reference scope.h: parent chain lookup)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, Variable] = {}
        self._kids: List["Scope"] = []

    def new_var(self, name: str) -> Variable:
        if name in self.vars:
            return self.vars[name]
        v = Variable(name)
        self.vars[name] = v
        return v

    # reference naming
    var = new_var

    def find_var(self, name: str) -> Optional[Variable]:
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def get_var(self, name: str) -> Variable:
        v = self.find_var(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in scope chain")
        return v

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def local_names(self) -> List[str]:
        return sorted(self.vars)
