"""The op set (reference: paddle/operators/*.cc — add, mul, rowwise_add,
sigmoid, softmax, cross_entropy (onehot), mean, sgd, fill_zeros_like, scale,
plus the fc composite built in net.py).  Each kernel is the jax expression of
the reference's Eigen kernel (.h files)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.framework.op import register_op


def _same_shape(in_shapes, attrs):
    return [in_shapes[0]]


@register_op("add", ["X", "Y"], ["Out"], infer_shape=_same_shape)
def add(x, y):
    """add_op.cc: Out = X + Y"""
    return x + y


@register_op(
    "mul", ["X", "Y"], ["Out"],
    infer_shape=lambda s, a: [(s[0][0], s[1][1])],
)
def mul(x, y):
    """mul_op.cc: matrix product (maps straight onto the MXU)"""
    return jnp.matmul(x, y)


@register_op("rowwise_add", ["X", "b"], ["Out"], infer_shape=_same_shape)
def rowwise_add(x, b):
    """rowwise_add_op.cc: broadcast-add a row vector"""
    return x + b[None, :]


@register_op("sigmoid", ["X"], ["Y"], infer_shape=_same_shape)
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register_op("softmax", ["X"], ["Y"], infer_shape=_same_shape)
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register_op(
    "onehot_cross_entropy", ["X", "label"], ["Y"],
    infer_shape=lambda s, a: [(s[0][0],)],
)
def onehot_cross_entropy(x, label):
    """cross_entropy_op.cc: Y_i = -log(X_i[label_i])"""
    idx = label.astype(jnp.int32)
    picked = jnp.take_along_axis(x, idx[:, None], axis=1)[:, 0]
    return -jnp.log(jnp.maximum(picked, 1e-12))


@register_op("mean", ["X"], ["Out"], infer_shape=lambda s, a: [()])
def mean(x):
    return jnp.mean(x)


@register_op("scale", ["X"], ["Out"], attrs=("scale",), infer_shape=_same_shape)
def scale(x, scale=1.0):
    return x * scale


@register_op("fill_zeros_like", ["Src"], ["Dst"], infer_shape=_same_shape)
def fill_zeros_like(src):
    return jnp.zeros_like(src)


@register_op(
    "sgd", ["param", "grad"], ["param_out"],
    attrs=("learning_rate",), infer_shape=_same_shape,
)
def sgd(param, grad, learning_rate=0.01):
    """sgd_op.cc: param_out = param - lr * grad"""
    return param - learning_rate * grad
