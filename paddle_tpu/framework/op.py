"""Operator + registry (reference: paddle/framework/operator.h
OperatorBase::InferShape/Run, op_registry.h:338 REGISTER_OP/OpProto).

An op kernel here is one pure jax function ``fn(*inputs, **attrs) ->
output(s)``; the same kernel serves CPU and TPU because XLA owns the device
dispatch — there is no per-Place kernel map to replicate (reference
operator.h:328's CPU/GPU kernel registry collapses into jax)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class OpInfo:
    type: str
    fn: Callable  # (*input_arrays, **attrs) -> array | tuple of arrays
    inputs: Tuple[str, ...]  # formal input slot names (OpProto)
    outputs: Tuple[str, ...]  # formal output slot names
    infer_shape: Optional[Callable] = None  # (in_shapes, attrs) -> out_shapes
    attrs: Tuple[str, ...] = ()


class OpRegistry:
    """REGISTER_OP equivalent (reference op_registry.h:338-429)."""

    _ops: Dict[str, OpInfo] = {}

    @classmethod
    def register(cls, info: OpInfo) -> None:
        if info.type in cls._ops:
            raise ValueError(f"duplicate op type {info.type!r}")
        cls._ops[info.type] = info

    @classmethod
    def get(cls, type_name: str) -> OpInfo:
        try:
            return cls._ops[type_name]
        except KeyError:
            raise KeyError(
                f"unknown op type {type_name!r}; registered: {sorted(cls._ops)}"
            ) from None

    @classmethod
    def op_types(cls) -> List[str]:
        return sorted(cls._ops)


def register_op(
    type_name: str,
    inputs: Sequence[str],
    outputs: Sequence[str],
    attrs: Sequence[str] = (),
    infer_shape: Optional[Callable] = None,
):
    """Decorator: @register_op("add", ["X", "Y"], ["Out"])."""

    def deco(fn):
        OpRegistry.register(
            OpInfo(
                type=type_name,
                fn=fn,
                inputs=tuple(inputs),
                outputs=tuple(outputs),
                infer_shape=infer_shape,
                attrs=tuple(attrs),
            )
        )
        return fn

    return deco


class Operator:
    """A bound op instance: formal slots → scope variable names (the OpDesc,
    reference op_desc.proto), runnable against a Scope and traceable inside
    a jit."""

    def __init__(
        self,
        type_name: str,
        inputs: Dict[str, Union[str, Sequence[str]]],
        outputs: Dict[str, Union[str, Sequence[str]]],
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.info = OpRegistry.get(type_name)
        self.type = type_name
        self.inputs = {k: _as_names(v) for k, v in inputs.items()}
        self.outputs = {k: _as_names(v) for k, v in outputs.items()}
        self.attrs = dict(attrs or {})
        for slot in self.info.inputs:
            if slot not in self.inputs:
                raise ValueError(f"{type_name}: missing input slot {slot!r}")
        for slot in self.info.outputs:
            if slot not in self.outputs:
                raise ValueError(f"{type_name}: missing output slot {slot!r}")

    # -- introspection (reference OperatorBase::Input/Outputs) ----------
    def input_names(self) -> List[str]:
        return [n for slot in self.info.inputs for n in self.inputs[slot]]

    def output_names(self) -> List[str]:
        return [n for slot in self.info.outputs for n in self.outputs[slot]]

    # -- shape inference (reference InferShape) -------------------------
    def infer_shape(self, scope) -> None:
        if self.info.infer_shape is None:
            return
        in_shapes = [
            tuple(np.shape(scope.get_var(n).get())) for n in self.input_names()
        ]
        out_shapes = self.info.infer_shape(in_shapes, self.attrs)
        for name, shp in zip(self.output_names(), out_shapes):
            scope.new_var(name).set_dims(shp)

    # -- tracing / execution -------------------------------------------
    def trace(self, values: Dict[str, Any]) -> Dict[str, Any]:
        """Apply the kernel on a name→array dict (used inside jit tracing).
        Returns the dict updated with this op's outputs."""
        args = [values[n] for n in self.input_names()]
        result = self.info.fn(*args, **self.attrs)
        outs = result if isinstance(result, tuple) else (result,)
        names = self.output_names()
        if len(outs) != len(names):
            raise ValueError(
                f"{self.type}: kernel returned {len(outs)} outputs, "
                f"desc names {len(names)}"
            )
        new_values = dict(values)
        for n, o in zip(names, outs):
            new_values[n] = o
        return new_values

    def run(self, scope) -> None:
        """Execute against a scope (one jit call; for op-at-a-time parity
        tests — real programs lower a whole NetOp instead)."""
        values = {
            n: jnp.asarray(scope.get_var(n).get()) for n in self.input_names()
        }
        out = self.trace(values)
        for n in self.output_names():
            scope.new_var(n).set(np.asarray(out[n]))

    def __repr__(self) -> str:  # pragma: no cover
        ins = ", ".join(self.input_names())
        outs = ", ".join(self.output_names())
        return f"Op({self.type}: {ins} -> {outs})"


def _as_names(v) -> List[str]:
    return [v] if isinstance(v, str) else list(v)


def create_op(type_name: str, **kwargs) -> Operator:
    """Convenience mirroring v2/framework create_op_creation_methods:
    create_op("add", X="x", Y="y", Out="out", attr=...)."""
    info = OpRegistry.get(type_name)
    inputs = {k: kwargs[k] for k in info.inputs}
    outputs = {k: kwargs[k] for k in info.outputs}
    attrs = {k: kwargs[k] for k in info.attrs if k in kwargs}
    return Operator(type_name, inputs, outputs, attrs)
