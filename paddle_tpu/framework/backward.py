"""Backward — gradient program construction (reference:
paddle/framework/backward.cc:179 builds a reversed net of per-op grad ops
with X→X@GRAD renaming; grad_op_builder.cc).

TPU-native: the forward op/net is already one traceable function, so the
gradient program is jax.vjp of that trace — one fused backward HLO instead
of a reversed interpreter list.  The scope-facing contract is kept: running
the backward op reads each external output's ``name@GRAD`` and writes each
input's ``name@GRAD`` (the reference's naming scheme, backward.cc)."""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Set

import jax
import jax.numpy as jnp
import numpy as np

GRAD_SUFFIX = "@GRAD"


def grad_name(name: str) -> str:
    return name + GRAD_SUFFIX


class BackwardOp:
    """The gradient operator for a forward op/net."""

    type = "backward"

    def __init__(self, forward, no_grad_set: Optional[Set[str]] = None):
        self.forward = forward
        self.no_grad_set = set(no_grad_set or ())
        self.fwd_inputs = forward.input_names()
        self.fwd_outputs = forward.output_names()
        self.grad_inputs = [n for n in self.fwd_inputs if n not in self.no_grad_set]

    def input_names(self) -> List[str]:
        return self.fwd_inputs + [grad_name(n) for n in self.fwd_outputs]

    def output_names(self) -> List[str]:
        return [grad_name(n) for n in self.grad_inputs]

    def trace(self, values: Dict[str, Any]) -> Dict[str, Any]:
        grads = _vjp_trace(
            self.forward,
            {n: values[n] for n in self.fwd_inputs},
            {n: values[grad_name(n)] for n in self.fwd_outputs},
            self.grad_inputs,
        )
        new_values = dict(values)
        for n in self.grad_inputs:
            new_values[grad_name(n)] = grads[n]
        return new_values

    def run(self, scope) -> None:
        values = {}
        for n in self.fwd_inputs:
            values[n] = jnp.asarray(scope.get_var(n).get())
        for n in self.fwd_outputs:
            g = scope.find_var(grad_name(n))
            if g is None or g.get() is None:
                # default seed: ones like the forward output (callers usually
                # seed the loss grad explicitly)
                out_val = scope.find_var(n)
                values[grad_name(n)] = jnp.ones_like(
                    jnp.asarray(out_val.get())
                )
            else:
                values[grad_name(n)] = jnp.asarray(g.get())
        out = self.trace(values)
        for n in self.grad_inputs:
            scope.new_var(grad_name(n)).set(np.asarray(out[grad_name(n)]))


def _vjp_trace(forward, inputs: Dict[str, Any], out_grads: Dict[str, Any],
               wrt: List[str]) -> Dict[str, Any]:
    in_names = forward.input_names()
    out_names = forward.output_names()

    def fwd_fn(wrt_vals):
        values = dict(inputs)
        values.update(zip(wrt, wrt_vals))
        values = forward.trace(values)
        return tuple(values[n] for n in out_names)

    primals = [inputs[n] for n in wrt]
    _, vjp_fn = jax.vjp(fwd_fn, primals)
    cotangents = tuple(
        out_grads[n].astype(jnp.result_type(float)) for n in out_names
    )
    (grads,) = vjp_fn(cotangents)
    return dict(zip(wrt, grads))


def Backward(forward, no_grad_set: Optional[Iterable[str]] = None) -> BackwardOp:
    """reference backward.cc Backward(): returns the op computing
    d(outputs)/d(inputs) with @GRAD-named scope variables."""
    return BackwardOp(forward, set(no_grad_set or ()))
