"""paddle.v2.plot parity — training-curve plotting (reference:
python/paddle/v2/plot/plot.py Ploter/PlotData).

The data model is identical (named series of (step, value)); rendering uses
matplotlib when importable and not disabled via DISABLE_PLOT=True, else the
Ploter degrades to a silent recorder so headless training scripts run
unchanged."""

from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["Ploter", "PlotData"]


class PlotData:
    def __init__(self) -> None:
        self.step: List[int] = []
        self.value: List[float] = []

    def append(self, step: int, value: float) -> None:
        self.step.append(step)
        self.value.append(value)

    def reset(self) -> None:
        self.step = []
        self.value = []


class Ploter:
    """::

        ploter = Ploter("train", "test")
        ploter.append("train", step, cost)
        ploter.plot("curve.png")
    """

    def __init__(self, *titles: str):
        self.__args__ = titles
        self.__plot_data__: Dict[str, PlotData] = {t: PlotData() for t in titles}
        self._disabled = os.environ.get("DISABLE_PLOT") == "True"
        self._plt = None
        if not self._disabled:
            try:
                import sys

                import matplotlib

                if (
                    not os.environ.get("DISPLAY")
                    and "matplotlib.pyplot" not in sys.modules
                ):
                    # headless AND nothing rendered yet: choose Agg; never
                    # switch a backend a notebook/session already activated
                    matplotlib.use("Agg")
                import matplotlib.pyplot as plt

                self._plt = plt
            except ImportError:
                self._disabled = True

    def append(self, title: str, step: int, value: float) -> None:
        self.__plot_data__[title].append(step, float(value))

    def data(self, title: str) -> PlotData:
        return self.__plot_data__[title]

    def plot(self, path: Optional[str] = None) -> None:
        """Render all series; with `path` writes an image file (headless),
        without it shows the interactive figure when a display exists."""
        if self._disabled or self._plt is None:
            return
        plt = self._plt
        plt.figure()
        titles = []
        for title in self.__args__:
            d = self.__plot_data__[title]
            if len(d.step) > 0:
                plt.plot(d.step, d.value, label=title)
                titles.append(title)
        if titles:
            plt.legend()
        plt.xlabel("step")
        if path is not None:
            plt.savefig(path)
            plt.close()
        else:  # pragma: no cover - needs a display
            plt.show()

    def reset(self) -> None:
        for d in self.__plot_data__.values():
            d.reset()
