"""Distributed model save/load — the ``paddle.v2.model`` surface
(reference: python/paddle/v2/model.py).

``save_model`` coordinates with the elastic master so exactly ONE trainer of
a data-parallel fleet writes the checkpoint (reference: the Go master's
save-model arbitration over etcd, go/master/service.go RequestSaveModel;
here ``master.Service.request_save_model`` over the lease RPC plane).
Without a master it degrades to a plain parameter tar — the single-trainer
path.
"""

from __future__ import annotations

import os
import uuid
from typing import Optional

__all__ = ["save_model", "load_model"]

# one id per process, like the reference's module-level uuid trainer_id
trainer_id = str(uuid.uuid4())


def save_model(
    parameters, path: str, master=None, block_secs: float = 60.0
) -> Optional[str]:
    """Write ``parameters`` as a tar at ``path``.

    ``master`` (a ``paddle_tpu.master.Service``, ``Client``, or a
    ``(host, port)`` Server address) enables the distributed arbitration:
    the master grants the save to one trainer per window and the rest skip
    (returns None).  Returns the path written, or None when another trainer
    holds the grant."""
    if master is not None:
        from paddle_tpu.master import Client, Service

        client = (
            master
            if isinstance(master, Client)
            else Client(master, trainer_id=trainer_id)
        )
        if not client.request_save_model(block_secs):
            return None  # another trainer saves this window
        # per-trainer subdir exactly like the reference's etcd path shape —
        # keyed by the identity that WON the grant, not this module's id
        path = os.path.join(path, client.trainer_id, "model.tar")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        parameters.to_tar(f)
    return path


def load_model(parameters, path: str) -> None:
    with open(path, "rb") as f:
        parameters.from_tar(f)
