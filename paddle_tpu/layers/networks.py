"""Prebuilt network compositions — the ``trainer_config_helpers.networks``
surface (reference: python/paddle/trainer_config_helpers/networks.py:
simple_img_conv_pool, vgg_16_network, simple_lstm, lstmemory_group,
simple_gru, bidirectional_lstm, simple_attention, sequence_conv_pool)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu import activation as A
from paddle_tpu import pooling as P
from paddle_tpu.core.topology import LayerOutput, auto_name
from paddle_tpu.layers import (
    addto,
    concat,
    data,
    expand,
    fc,
    first_seq,
    gru_step,
    grumemory,
    img_conv,
    img_pool,
    last_seq,
    lstm_step,
    lstmemory,
    pooling,
    recurrent_group,
    scaling,
    seq_reshape,
)
from paddle_tpu.layers import StaticInput, memory
from paddle_tpu.layers import sequence  # noqa: F401
from paddle_tpu.core.topology import LayerConf


def simple_img_conv_pool(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    pool_size: int,
    pool_stride: Optional[int] = None,
    num_channel: Optional[int] = None,
    act=None,
    padding: int = 0,
    pool_type=None,
    name: Optional[str] = None,
) -> LayerOutput:
    conv = img_conv(
        input,
        filter_size=filter_size,
        num_filters=num_filters,
        num_channels=num_channel,
        padding=padding,
        act=act,
        name=(name + "_conv") if name else None,
    )
    return img_pool(
        conv,
        pool_size=pool_size,
        stride=pool_stride or pool_size,
        pool_type=pool_type,
        name=(name + "_pool") if name else None,
    )


def img_conv_group(
    input: LayerOutput,
    conv_num_filter,
    pool_size: int,
    num_channels: Optional[int] = None,
    conv_padding=1,
    conv_filter_size=3,
    conv_act=None,
    conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0,
    pool_stride: int = 1,
    pool_type=None,
    param_attr=None,
) -> LayerOutput:
    """Image convolution group — [conv (+bn +dropout)]×N then one pool
    (reference networks.py:333 img_conv_group, the VGG building block).
    Scalar conv_* arguments broadcast across the group like the reference."""
    from paddle_tpu.layers import batch_norm, dropout

    n = len(conv_num_filter)

    def bcast(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n

    paddings = bcast(conv_padding)
    fsizes = bcast(conv_filter_size)
    acts = bcast(conv_act)
    with_bn = bcast(conv_with_batchnorm)
    bn_drop = bcast(conv_batchnorm_drop_rate)

    tmp = input
    for i in range(n):
        tmp = img_conv(
            tmp,
            filter_size=fsizes[i],
            num_filters=conv_num_filter[i],
            num_channels=num_channels if i == 0 else None,
            padding=paddings[i],
            act=A.Identity() if with_bn[i] else acts[i],
            param_attr=param_attr,
        )
        if with_bn[i]:
            tmp = batch_norm(tmp, act=acts[i])
            if bn_drop[i] > 0:
                tmp = dropout(tmp, bn_drop[i])
    return img_pool(tmp, pool_size=pool_size, stride=pool_stride, pool_type=pool_type)


def small_vgg(input_image: LayerOutput, num_channels: int, num_classes: int):
    """reference networks.py:435 small_vgg — 4 bn-conv groups + pool +
    dropout + fc."""
    from paddle_tpu.layers import dropout

    def block(ipt, num_filter, times, dropouts, ch_in=None):
        return img_conv_group(
            ipt,
            num_channels=ch_in,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * times,
            conv_filter_size=3,
            conv_act=A.Relu(),
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type=P.Max(),
        )

    tmp = block(input_image, 64, 2, [0.3, 0], num_channels)
    tmp = block(tmp, 128, 2, [0.4, 0])
    tmp = block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = block(tmp, 512, 3, [0.4, 0.4, 0])
    from paddle_tpu.attr import ExtraAttr
    from paddle_tpu.layers import batch_norm

    tmp = img_pool(tmp, stride=2, pool_size=2, pool_type=P.Max())
    tmp = dropout(tmp, 0.5)
    tmp = fc(tmp, size=512, act=A.Linear(), layer_attr=ExtraAttr(drop_rate=0.5))
    tmp = batch_norm(tmp, act=A.Relu())
    return fc(tmp, size=num_classes, act=A.Softmax())


def vgg_16_network(input_image: LayerOutput, num_channels: int, num_classes: int = 1000):
    """reference vgg_16_network (networks.py)."""

    def block(ipt, num_filter, groups, ch_in=None):
        out = ipt
        for i in range(groups):
            out = img_conv(
                out,
                filter_size=3,
                num_filters=num_filter,
                num_channels=ch_in if i == 0 else None,
                padding=1,
                act=A.Relu(),
            )
        return img_pool(out, pool_size=2, stride=2)

    t = block(input_image, 64, 2, num_channels)
    t = block(t, 128, 2)
    t = block(t, 256, 3)
    t = block(t, 512, 3)
    t = block(t, 512, 3)
    t = fc(t, size=4096, act=A.Relu(), layer_attr=None)
    t = fc(t, size=4096, act=A.Relu())
    return fc(t, size=num_classes, act=A.Softmax())


def simple_lstm(
    input: LayerOutput,
    size: int,
    reverse: bool = False,
    act=None,
    gate_act=None,
    state_act=None,
    name: Optional[str] = None,
    mat_param_attr=None,
    bias_param_attr=None,
    inner_param_attr=None,
    lstm_cell_attr=None,
    mixed_layer_attr=None,
) -> LayerOutput:
    """fc(4*size) + fused lstmemory (reference simple_lstm networks.py).
    The v1 attr arguments accepted: lstm_cell_attr.drop_rate applies to the
    cell output; parameter-attr knobs beyond initial_std are ignored."""
    proj = fc(
        input,
        size=size * 4,
        act=A.Identity(),
        bias_attr=False,
        param_attr=mat_param_attr,
        layer_attr=mixed_layer_attr,
        name=(name + "_transform") if name else None,
    )
    return lstmemory(
        proj,
        size=size,
        reverse=reverse,
        act=act,
        gate_act=gate_act,
        state_act=state_act,
        layer_attr=lstm_cell_attr,
        name=name,
    )


def gru_unit(
    input: LayerOutput,
    memory_boot: Optional[LayerOutput] = None,
    size: Optional[int] = None,
    name: Optional[str] = None,
    gru_bias_attr=None,
    gru_param_attr=None,
    act=None,
    gate_act=None,
    gru_layer_attr=None,
    naive: bool = False,
) -> LayerOutput:
    """One GRU step over a 3H-projected input with its own output memory
    (reference gru_unit, networks.py:840) — recurrent_group building block."""
    size = size or input.size // 3
    name = name or auto_name("gru_unit")
    out_mem = memory(name=name, size=size, boot_layer=memory_boot)
    return gru_step(
        input=input,
        output_mem=out_mem,
        size=size,
        bias_attr=gru_bias_attr if gru_bias_attr is not None else True,
        param_attr=gru_param_attr,
        act=act,
        gate_act=gate_act,
        name=name,
        naive=naive,
    )


def gru_group(
    input: LayerOutput,
    memory_boot: Optional[LayerOutput] = None,
    size: Optional[int] = None,
    name: Optional[str] = None,
    reverse: bool = False,
    gru_bias_attr=None,
    gru_param_attr=None,
    act=None,
    gate_act=None,
    gru_layer_attr=None,
    naive: bool = False,
) -> LayerOutput:
    """GRU as a recurrent_group of gru_step (reference gru_group,
    networks.py:902): same math as grumemory, composable step."""
    size = size or input.size // 3
    name = name or auto_name("gru_group")

    # Cross-group sharing rides the per-key parameter table: the in-group
    # gru_step declares its weight keys under gru_param_attr.name (and the
    # bias under a named gru_bias_attr), so two groups naming the same
    # params share exactly those keys — the reference's per-parameter
    # global-table semantics (a named weight + default bias shares the
    # weight only).
    def step(x):
        return gru_unit(
            input=x, memory_boot=memory_boot, size=size,
            name=f"{name}_unit", gru_bias_attr=gru_bias_attr,
            gru_param_attr=gru_param_attr, act=act, gate_act=gate_act,
            naive=naive,
        )

    return recurrent_group(step=step, input=input, reverse=reverse, name=name)


def lstmemory_unit(
    input: LayerOutput,
    out_memory: Optional[LayerOutput] = None,
    name: Optional[str] = None,
    size: Optional[int] = None,
    param_attr=None,
    act=None,
    gate_act=None,
    state_act=None,
    input_proj_bias_attr=None,
    input_proj_layer_attr=None,
    lstm_bias_attr=None,
    lstm_layer_attr=None,
) -> LayerOutput:
    """One LSTM step (reference lstmemory_unit, networks.py:633): the
    recurrence runs through a mixed projection of the output memory (the
    step itself carries no W_h), cell state rides the `@cell` aux output."""
    from paddle_tpu.layers import full_matrix_projection, identity_projection, mixed

    size = size or input.size // 4
    name = name or auto_name("lstm_unit")
    if out_memory is None:
        out_mem = memory(name=name, size=size)
    else:
        out_mem = out_memory
    state_mem = memory(name=f"{name}@cell", size=size)
    m = mixed(
        size=size * 4,
        input=[
            identity_projection(input=input),
            full_matrix_projection(input=out_mem, param_attr=param_attr),
        ],
        bias_attr=(
            input_proj_bias_attr if input_proj_bias_attr is not None else False
        ),
        layer_attr=input_proj_layer_attr,
        act=A.Identity(),
        name=f"{name}_input_recurrent",
    )
    return lstm_step(
        input=m,
        output_mem=out_mem,
        state_mem=state_mem,
        size=size,
        bias_attr=lstm_bias_attr if lstm_bias_attr is not None else True,
        recurrent_weight=False,
        act=act,
        gate_act=gate_act,
        state_act=state_act,
        name=name,
    )


def lstmemory_group(
    input: LayerOutput,
    size: Optional[int] = None,
    name: Optional[str] = None,
    out_memory: Optional[LayerOutput] = None,
    reverse: bool = False,
    param_attr=None,
    act=None,
    gate_act=None,
    state_act=None,
    input_proj_bias_attr=None,
    input_proj_layer_attr=None,
    lstm_bias_attr=None,
    lstm_layer_attr=None,
) -> LayerOutput:
    """LSTM as a recurrent_group of lstmemory_unit (reference
    lstmemory_group, networks.py:744)."""
    size = size or input.size // 4
    name = name or auto_name("lstm_group")

    # Cross-group sharing rides the per-key parameter table (see gru_group):
    # the inner mixed projection declares param_attr.name and the lstm_step
    # a named lstm_bias_attr, so same-named groups share per parameter.
    def step(x):
        return lstmemory_unit(
            input=x, out_memory=out_memory, name=f"{name}_unit", size=size,
            param_attr=param_attr, act=act, gate_act=gate_act,
            state_act=state_act,
            input_proj_bias_attr=input_proj_bias_attr,
            input_proj_layer_attr=input_proj_layer_attr,
            lstm_bias_attr=lstm_bias_attr, lstm_layer_attr=lstm_layer_attr,
        )

    return recurrent_group(step=step, input=input, reverse=reverse, name=name)


def simple_gru(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    reverse: bool = False,
    mixed_param_attr=None,
    mixed_bias_param_attr=None,
    mixed_layer_attr=None,
    gru_bias_attr=None,
    gru_param_attr=None,
    act=None,
    gate_act=None,
    gru_layer_attr=None,
    naive: bool = False,
) -> LayerOutput:
    """reference simple_gru (networks.py:975): W·x_t projection + gru_group."""
    proj = fc(
        input,
        size=size * 3,
        act=A.Identity(),
        bias_attr=(
            mixed_bias_param_attr if mixed_bias_param_attr is not None else False
        ),
        param_attr=mixed_param_attr,
        layer_attr=mixed_layer_attr,
        name=(name + "_transform") if name else None,
    )
    return gru_group(
        proj, size=size, name=name, reverse=reverse,
        gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
        act=act, gate_act=gate_act, gru_layer_attr=gru_layer_attr,
        naive=naive,
    )


def simple_gru2(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    reverse: bool = False,
    mixed_param_attr=None,
    mixed_bias_attr=None,
    gru_param_attr=None,
    gru_bias_attr=None,
    act=None,
    gate_act=None,
    mixed_layer_attr=None,
    gru_cell_attr=None,
) -> LayerOutput:
    """reference simple_gru2 (networks.py:1061): same math through the FUSED
    grumemory layer (one lax.scan) — the faster form."""
    proj = fc(
        input,
        size=size * 3,
        act=A.Identity(),
        bias_attr=mixed_bias_attr if mixed_bias_attr is not None else False,
        param_attr=mixed_param_attr,
        layer_attr=mixed_layer_attr,
        name=(name + "_transform") if name else None,
    )
    return grumemory(
        proj, size=size, reverse=reverse, act=act, gate_act=gate_act,
        param_attr=gru_param_attr, bias_attr=(
            gru_bias_attr if gru_bias_attr is not None else True
        ),
        layer_attr=gru_cell_attr, name=name,
    )


def bidirectional_lstm(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    return_seq: bool = False,
    return_concat: Optional[bool] = None,
    **kwargs,
) -> LayerOutput:
    """reference bidirectional_lstm (networks.py): fwd + reversed LSTM;
    return_seq=True concats the two output sequences [B,T,2H], else (the
    reference default) concats last-of-forward with first-of-backward
    [B,2H].  fwd_*/bwd_* kwargs route per direction."""
    fwd_kw = {k[4:]: v for k, v in kwargs.items() if k.startswith("fwd_")}
    bwd_kw = {k[4:]: v for k, v in kwargs.items() if k.startswith("bwd_")}
    leftover = {
        k for k in kwargs if not (k.startswith("fwd_") or k.startswith("bwd_"))
        and k not in ("last_seq_attr", "first_seq_attr", "concat_attr", "concat_act")
    }
    assert not leftover, f"bidirectional_lstm got unexpected kwargs {leftover}"
    fwd = simple_lstm(
        input, size, reverse=False, name=(name + "_fw") if name else None,
        **fwd_kw,
    )
    bwd = simple_lstm(
        input, size, reverse=True, name=(name + "_bw") if name else None,
        **bwd_kw,
    )
    if return_concat is not None:  # legacy surface of this package
        return concat([fwd, bwd]) if return_concat else addto([fwd, bwd])
    if return_seq:
        return concat([fwd, bwd], name=name)
    return concat([last_seq(input=fwd), first_seq(input=bwd)], name=name)


def bidirectional_gru(
    input: LayerOutput,
    size: int,
    name=None,
    return_seq: bool = False,
    return_concat: Optional[bool] = None,
    **kwargs,
) -> LayerOutput:
    """reference bidirectional_gru (networks.py:1122): fwd + reversed GRU;
    return_seq=True concats the two output sequences, else concats
    last-of-forward with first-of-backward.  fwd_*/bwd_* kwargs route to the
    respective direction (reference prefix convention)."""
    fwd_kw = {k[4:]: v for k, v in kwargs.items() if k.startswith("fwd_")}
    bwd_kw = {k[4:]: v for k, v in kwargs.items() if k.startswith("bwd_")}
    leftover = {
        k for k in kwargs if not (k.startswith("fwd_") or k.startswith("bwd_"))
        and k not in ("last_seq_attr", "first_seq_attr", "concat_attr", "concat_act")
    }
    assert not leftover, f"bidirectional_gru got unexpected kwargs {leftover}"
    fwd = simple_gru2(
        input, size, reverse=False, name=(name + "_fw") if name else None,
        **fwd_kw,
    )
    bwd = simple_gru2(
        input, size, reverse=True, name=(name + "_bw") if name else None,
        **bwd_kw,
    )
    if return_concat is not None:  # legacy surface of this package
        return concat([fwd, bwd]) if return_concat else addto([fwd, bwd])
    if return_seq:
        return concat([fwd, bwd], name=name)
    return concat([last_seq(input=fwd), first_seq(input=bwd)], name=name)


def sequence_conv_pool(
    input: LayerOutput,
    context_len: int,
    hidden_size: int,
    pool_type=None,
    act=None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Text conv (context window projection + fc) then seq pooling
    (reference sequence_conv_pool / context_projection path)."""
    from paddle_tpu.layers import context_projection

    ctxp = context_projection(input, context_len=context_len)
    h = fc(ctxp, size=hidden_size, act=act or A.Tanh(),
           name=(name + "_conv") if name else None)
    return pooling(h, pool_type or P.Max(), name=(name + "_pool") if name else None)


def simple_attention(
    encoded_sequence: LayerOutput,
    encoded_proj: LayerOutput,
    decoder_state: LayerOutput,
    transform_bias_attr=False,
    name: Optional[str] = None,
) -> LayerOutput:
    """Bahdanau-style attention (reference simple_attention,
    networks.py:1400-1464): score = fc_tanh(enc_proj + expand(dec_state)),
    weights = sequence_softmax, context = weighted sum over time.

    Used INSIDE a recurrent_group step: encoded_sequence/encoded_proj are
    StaticInput sequences [B, S, D]; decoder_state is a memory [B, H]."""
    expanded = expand(decoder_state, encoded_proj)
    state_proj = fc(
        expanded,
        size=encoded_proj.size,
        act=A.Identity(),
        bias_attr=transform_bias_attr,
        name=(name + "_state_proj") if name else None,
    )
    attn_hidden = addto([encoded_proj, state_proj], act=A.Tanh(), bias_attr=False)
    scores = fc(
        attn_hidden,
        size=1,
        act=A.SequenceSoftmax(),
        bias_attr=False,
        name=(name + "_scores") if name else None,
    )
    scaled = scaling(scores, encoded_sequence)
    return pooling(scaled, P.Sum(), name=(name + "_context") if name else None)
