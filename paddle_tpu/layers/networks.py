"""Prebuilt network compositions — the ``trainer_config_helpers.networks``
surface (reference: python/paddle/trainer_config_helpers/networks.py:
simple_img_conv_pool, vgg_16_network, simple_lstm, lstmemory_group,
simple_gru, bidirectional_lstm, simple_attention, sequence_conv_pool)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu import activation as A
from paddle_tpu import pooling as P
from paddle_tpu.core.topology import LayerOutput, auto_name
from paddle_tpu.layers import (
    addto,
    concat,
    data,
    expand,
    fc,
    first_seq,
    grumemory,
    img_conv,
    img_pool,
    last_seq,
    lstmemory,
    pooling,
    recurrent_group,
    scaling,
    seq_reshape,
)
from paddle_tpu.layers import StaticInput, memory
from paddle_tpu.layers import sequence  # noqa: F401
from paddle_tpu.core.topology import LayerConf


def simple_img_conv_pool(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    pool_size: int,
    pool_stride: Optional[int] = None,
    num_channel: Optional[int] = None,
    act=None,
    padding: int = 0,
    pool_type=None,
    name: Optional[str] = None,
) -> LayerOutput:
    conv = img_conv(
        input,
        filter_size=filter_size,
        num_filters=num_filters,
        num_channels=num_channel,
        padding=padding,
        act=act,
        name=(name + "_conv") if name else None,
    )
    return img_pool(
        conv,
        pool_size=pool_size,
        stride=pool_stride or pool_size,
        pool_type=pool_type,
        name=(name + "_pool") if name else None,
    )


def img_conv_group(
    input: LayerOutput,
    conv_num_filter,
    pool_size: int,
    num_channels: Optional[int] = None,
    conv_padding=1,
    conv_filter_size=3,
    conv_act=None,
    conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0,
    pool_stride: int = 1,
    pool_type=None,
    param_attr=None,
) -> LayerOutput:
    """Image convolution group — [conv (+bn +dropout)]×N then one pool
    (reference networks.py:333 img_conv_group, the VGG building block).
    Scalar conv_* arguments broadcast across the group like the reference."""
    from paddle_tpu.layers import batch_norm, dropout

    n = len(conv_num_filter)

    def bcast(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n

    paddings = bcast(conv_padding)
    fsizes = bcast(conv_filter_size)
    acts = bcast(conv_act)
    with_bn = bcast(conv_with_batchnorm)
    bn_drop = bcast(conv_batchnorm_drop_rate)

    tmp = input
    for i in range(n):
        tmp = img_conv(
            tmp,
            filter_size=fsizes[i],
            num_filters=conv_num_filter[i],
            num_channels=num_channels if i == 0 else None,
            padding=paddings[i],
            act=A.Identity() if with_bn[i] else acts[i],
            param_attr=param_attr,
        )
        if with_bn[i]:
            tmp = batch_norm(tmp, act=acts[i])
            if bn_drop[i] > 0:
                tmp = dropout(tmp, bn_drop[i])
    return img_pool(tmp, pool_size=pool_size, stride=pool_stride, pool_type=pool_type)


def small_vgg(input_image: LayerOutput, num_channels: int, num_classes: int):
    """reference networks.py:435 small_vgg — 4 bn-conv groups + pool +
    dropout + fc."""
    from paddle_tpu.layers import dropout

    def block(ipt, num_filter, times, dropouts, ch_in=None):
        return img_conv_group(
            ipt,
            num_channels=ch_in,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * times,
            conv_filter_size=3,
            conv_act=A.Relu(),
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type=P.Max(),
        )

    tmp = block(input_image, 64, 2, [0.3, 0], num_channels)
    tmp = block(tmp, 128, 2, [0.4, 0])
    tmp = block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = block(tmp, 512, 3, [0.4, 0.4, 0])
    from paddle_tpu.attr import ExtraAttr
    from paddle_tpu.layers import batch_norm

    tmp = img_pool(tmp, stride=2, pool_size=2, pool_type=P.Max())
    tmp = dropout(tmp, 0.5)
    tmp = fc(tmp, size=512, act=A.Linear(), layer_attr=ExtraAttr(drop_rate=0.5))
    tmp = batch_norm(tmp, act=A.Relu())
    return fc(tmp, size=num_classes, act=A.Softmax())


def vgg_16_network(input_image: LayerOutput, num_channels: int, num_classes: int = 1000):
    """reference vgg_16_network (networks.py)."""

    def block(ipt, num_filter, groups, ch_in=None):
        out = ipt
        for i in range(groups):
            out = img_conv(
                out,
                filter_size=3,
                num_filters=num_filter,
                num_channels=ch_in if i == 0 else None,
                padding=1,
                act=A.Relu(),
            )
        return img_pool(out, pool_size=2, stride=2)

    t = block(input_image, 64, 2, num_channels)
    t = block(t, 128, 2)
    t = block(t, 256, 3)
    t = block(t, 512, 3)
    t = block(t, 512, 3)
    t = fc(t, size=4096, act=A.Relu(), layer_attr=None)
    t = fc(t, size=4096, act=A.Relu())
    return fc(t, size=num_classes, act=A.Softmax())


def simple_lstm(
    input: LayerOutput,
    size: int,
    reverse: bool = False,
    act=None,
    gate_act=None,
    state_act=None,
    name: Optional[str] = None,
    mat_param_attr=None,
    bias_param_attr=None,
    inner_param_attr=None,
    lstm_cell_attr=None,
    mixed_layer_attr=None,
) -> LayerOutput:
    """fc(4*size) + fused lstmemory (reference simple_lstm networks.py).
    The v1 attr arguments accepted: lstm_cell_attr.drop_rate applies to the
    cell output; parameter-attr knobs beyond initial_std are ignored."""
    proj = fc(
        input,
        size=size * 4,
        act=A.Identity(),
        bias_attr=False,
        param_attr=mat_param_attr,
        layer_attr=mixed_layer_attr,
        name=(name + "_transform") if name else None,
    )
    return lstmemory(
        proj,
        size=size,
        reverse=reverse,
        act=act,
        gate_act=gate_act,
        state_act=state_act,
        layer_attr=lstm_cell_attr,
        name=name,
    )


def simple_gru(
    input: LayerOutput,
    size: int,
    reverse: bool = False,
    act=None,
    gate_act=None,
    name: Optional[str] = None,
) -> LayerOutput:
    proj = fc(
        input,
        size=size * 3,
        act=A.Identity(),
        bias_attr=False,
        name=(name + "_transform") if name else None,
    )
    return grumemory(proj, size=size, reverse=reverse, act=act, gate_act=gate_act, name=name)


def bidirectional_lstm(
    input: LayerOutput,
    size: int,
    return_concat: bool = True,
    name: Optional[str] = None,
) -> LayerOutput:
    fwd = simple_lstm(input, size, reverse=False, name=(name + "_fw") if name else None)
    bwd = simple_lstm(input, size, reverse=True, name=(name + "_bw") if name else None)
    if return_concat:
        return concat([fwd, bwd])
    return addto([fwd, bwd])


def bidirectional_gru(
    input: LayerOutput, size: int, return_concat: bool = True, name=None
) -> LayerOutput:
    fwd = simple_gru(input, size, reverse=False, name=(name + "_fw") if name else None)
    bwd = simple_gru(input, size, reverse=True, name=(name + "_bw") if name else None)
    if return_concat:
        return concat([fwd, bwd])
    return addto([fwd, bwd])


def sequence_conv_pool(
    input: LayerOutput,
    context_len: int,
    hidden_size: int,
    pool_type=None,
    act=None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Text conv (context window projection + fc) then seq pooling
    (reference sequence_conv_pool / context_projection path)."""
    from paddle_tpu.layers import context_projection

    ctxp = context_projection(input, context_len=context_len)
    h = fc(ctxp, size=hidden_size, act=act or A.Tanh(),
           name=(name + "_conv") if name else None)
    return pooling(h, pool_type or P.Max(), name=(name + "_pool") if name else None)


def simple_attention(
    encoded_sequence: LayerOutput,
    encoded_proj: LayerOutput,
    decoder_state: LayerOutput,
    transform_bias_attr=False,
    name: Optional[str] = None,
) -> LayerOutput:
    """Bahdanau-style attention (reference simple_attention,
    networks.py:1400-1464): score = fc_tanh(enc_proj + expand(dec_state)),
    weights = sequence_softmax, context = weighted sum over time.

    Used INSIDE a recurrent_group step: encoded_sequence/encoded_proj are
    StaticInput sequences [B, S, D]; decoder_state is a memory [B, H]."""
    expanded = expand(decoder_state, encoded_proj)
    state_proj = fc(
        expanded,
        size=encoded_proj.size,
        act=A.Identity(),
        bias_attr=transform_bias_attr,
        name=(name + "_state_proj") if name else None,
    )
    attn_hidden = addto([encoded_proj, state_proj], act=A.Tanh(), bias_attr=False)
    scores = fc(
        attn_hidden,
        size=1,
        act=A.SequenceSoftmax(),
        bias_attr=False,
        name=(name + "_scores") if name else None,
    )
    scaled = scaling(scores, encoded_sequence)
    return pooling(scaled, P.Sum(), name=(name + "_context") if name else None)
