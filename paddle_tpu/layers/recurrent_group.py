"""recurrent_group — the TPU-native RecurrentGradientMachine (reference:
paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp:530 forward,
python/paddle/trainer_config_helpers/layers.py recurrent_group/memory, and
the SubModelConfig plumbing of config_parser.py:366-386).

Reference semantics: a user step function composed of ordinary layers runs
per timestep; ``memory(name=X)`` reads layer X's output from t-1; sequence
inputs are scanned; non-sequence ("static") inputs are visible every step.
The reference executes this by cloning frame networks per timestep and
re-batching variable-length sequences by length (createInFrameInfo,
.cpp:428).

TPU-native lowering: the step function is traced ONCE at model-build time
into a *sub-topology* (the SubModelConfig analogue).  At apply time the
sub-network becomes the body of one ``lax.scan`` over the padded time axis;
memories are scan carries with mask-carry-through for padding; the whole
group is part of the same jitted XLA program as the rest of the model.
No per-timestep re-batching, no frame cloning — static shapes end to end.
"""

from __future__ import annotations

import contextlib
import warnings
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerConf, LayerOutput, Topology, auto_name
from paddle_tpu.layers.base import ApplyContext, register_layer
from paddle_tpu.ops import acc_einsum


class StaticInput:
    """Marks an outer layer as visible-every-step instead of scanned
    (reference StaticInput, trainer_config_helpers/layers.py).  `size` is
    accepted for config compatibility (the reference validates it against
    input.size; here the topology already carries it)."""

    def __init__(self, input: LayerOutput, is_seq: bool = False,
                 size: int = 0):
        self.input = input
        self.is_seq = is_seq
        if size and size != input.size:
            raise ValueError(
                f"StaticInput size {size} != input layer size {input.size}"
            )


class SubsequenceInput:
    """Marks a NESTED outer layer whose subsequences are the scan unit
    (reference SubsequenceInput, trainer_config_helpers/layers.py:3590;
    engine: RecurrentGradientMachine.cpp:428-528 createInFrameInfo with
    hasSubseq).  The group scans the outer S axis; each step's placeholder is
    an ordinary [B, T, ...] sequence, so the step function can itself contain
    sequence layers or an inner recurrent_group (hierarchical RNN)."""

    def __init__(self, input: LayerOutput):
        self.input = input


# Build-time state for the step function trace: maps memory placeholders to
# their link targets so the group layer can wire carries.
class _GroupBuild:
    def __init__(self) -> None:
        self.memories: List[LayerConf] = []
        # placeholder name -> outer boot LayerOutput (must join group parents)
        self.boot_layers: Dict[str, LayerOutput] = {}


_current_build: Optional[_GroupBuild] = None

# Unroll factor for the group scan.  The body is a whole traced
# sub-network; measured on v5e (NMT attention decoder fwd+bwd) unroll=2
# was SLOWER than 1 (33.0 vs 27.9 ms/step) — the body is large enough that
# scan overhead is already amortized and unrolling only bloats the program.
# (The small fused cells in ops/rnn.py are different: they unroll 4x.)
_GROUP_UNROLL = 1


@contextlib.contextmanager
def _group_build():
    global _current_build
    prev = _current_build
    _current_build = _GroupBuild()
    try:
        yield _current_build
    finally:
        _current_build = prev


class _MemoryOutput(LayerOutput):
    """memory() handle: supports the reference's deferred-link form
    ``m = memory(name=None, size=...); ...; m.set_input(layer)``."""

    def set_input(self, layer: LayerOutput) -> None:
        assert self.conf.type == "memory"
        self.conf.attrs["link"] = layer.name


def memory(
    name: Optional[str],
    size: int,
    boot_layer: Optional[LayerOutput] = None,
    boot_with_const_id: Optional[int] = None,
    is_seq: bool = False,
    memory_name: Optional[str] = None,
) -> LayerOutput:
    """Previous-timestep output of the in-group layer called `name`
    (reference memory(), layers.py; RecurrentGradientMachine "memory frame"
    links).  boot_layer provides the t=0 value (non-seq [B, size]).
    name=None defers the link: call ``.set_input(layer)`` before the group
    closes (reference memory(name=None).set_input pattern).

    is_seq=True carries a WHOLE SEQUENCE between outer steps (reference
    sequence-memory frames, RecurrentGradientMachine.cpp:530-608): the step
    sees the linked layer's previous-step [B, T_mem, size] sequence (with
    its lengths), so sequence layers / an inner group can consume it.  The
    boot value is the boot_layer's sequence (or an empty zero-length
    sequence when unbooted); under the static-shape scan the linked layer's
    padded width must be step-invariant."""
    assert _current_build is not None, "memory() must be called inside a recurrent_group step"
    if is_seq and boot_with_const_id is not None:
        raise ValueError(
            "memory(is_seq=True) cannot boot with a constant id — a "
            "sequence memory boots from a sequence boot_layer or as an "
            "empty sequence"
        )
    conf = LayerConf(
        name=auto_name(f"memory_{name or memory_name or 'deferred'}"),
        type="memory",
        size=size,
        bias=False,
        attrs={
            "link": name,
            "boot": boot_layer.name if boot_layer is not None else None,
            "boot_const_id": boot_with_const_id,
            **({"is_seq": True} if is_seq else {}),
        },
    )
    _current_build.memories.append(conf)
    if boot_layer is not None:
        _current_build.boot_layers[conf.name] = boot_layer
    return _MemoryOutput(conf)


@register_layer("memory")
def memory_apply(conf, params, inputs, ctx):  # pragma: no cover
    raise RuntimeError("memory placeholders are fed by the recurrent_group scan")


@register_layer("step_input")
def step_input_apply(conf, params, inputs, ctx):  # pragma: no cover
    raise RuntimeError("step inputs are fed by the recurrent_group scan")


def recurrent_group(
    step,
    input: Union[LayerOutput, StaticInput, Sequence[Union[LayerOutput, StaticInput]]],
    reverse: bool = False,
    name: Optional[str] = None,
) -> LayerOutput:
    """Run `step` over the time axis of the sequence inputs.

    Returns the step's (first) output as a sequence layer.  See module
    docstring for the lowering.
    """
    ins = input if isinstance(input, (list, tuple)) else [input]
    scanned: List[LayerOutput] = []
    sub_scanned: List[bool] = []  # parallel: scan unit is a subsequence
    statics: List[StaticInput] = []
    for i in ins:
        if isinstance(i, StaticInput):
            statics.append(i)
        elif isinstance(i, SubsequenceInput):
            scanned.append(i.input)
            sub_scanned.append(True)
        else:
            scanned.append(i)
            sub_scanned.append(False)
    assert scanned, "recurrent_group needs at least one sequence input to scan"

    gname = name or auto_name("recurrent_group")

    # ---- trace the step function into a sub-topology ------------------
    step_args, scan_placeholders, static_placeholders = _make_placeholders(
        gname, scanned, sub_scanned, statics
    )

    with _trace_capture() as (gb, created):
        out = step(*step_args)
    step_outputs: List[LayerOutput] = out if isinstance(out, (list, tuple)) else [out]
    return _finalize_group(
        gname, scanned, sub_scanned, statics, scan_placeholders,
        static_placeholders, gb, created, step_outputs, reverse,
    )


@contextlib.contextmanager
def _trace_capture():
    """Group-trace context shared by the step-function face above and the
    raw RecurrentLayerGroupBegin/End face: opens a _GroupBuild for memory
    declarations and captures every LayerOutput built inside (chaining any
    outer layer sink), restoring both on exit — including the error path."""
    from paddle_tpu.core.topology import set_layer_sink

    created: Dict[str, LayerOutput] = {}

    def _capture(lo: LayerOutput) -> None:
        created[lo.conf.name] = lo
        if prev_sink is not None:
            prev_sink(lo)

    with _group_build() as gb:
        prev_sink = set_layer_sink(_capture)
        try:
            yield gb, created
        finally:
            set_layer_sink(prev_sink)


def _make_placeholders(gname, scanned, sub_scanned, statics):
    """Scan/static step-input placeholder confs for a group being built."""
    step_args: List[LayerOutput] = []
    scan_placeholders: List[LayerConf] = []
    static_placeholders: List[LayerConf] = []
    for k, lo in enumerate(scanned):
        conf = LayerConf(
            name=f"{gname}@in{k}", type="step_input", size=lo.size, bias=False,
            attrs={"step_seq": sub_scanned[k]},
        )
        scan_placeholders.append(conf)
        step_args.append(LayerOutput(conf))
    for k, st in enumerate(statics):
        conf = LayerConf(
            name=f"{gname}@static{k}",
            type="step_input",
            size=st.input.size,
            bias=False,
            attrs={"static_seq": st.is_seq},
        )
        static_placeholders.append(conf)
        step_args.append(LayerOutput(conf))
    return step_args, scan_placeholders, static_placeholders


def _finalize_group(
    gname, scanned, sub_scanned, statics, scan_placeholders,
    static_placeholders, gb, created, step_outputs, reverse,
) -> LayerOutput:
    """Assemble the recurrent_group LayerConf from a traced step body —
    shared by the step-function form above and the raw
    RecurrentLayerGroupBegin/End config face (v1_compat.raw_face)."""
    unset = [m.name for m in gb.memories if m.attrs["link"] is None]
    if unset:
        raise ValueError(
            f"memories {unset} in recurrent_group {gname!r} have no link: "
            "pass name= or call .set_input(layer) inside the step"
        )
    # Memory link targets must be part of the sub-topology even when not on
    # the path to the step output (reference: a memory may link a layer
    # built purely for the recurrence, e.g. last_seq over the inner rnn in
    # sequence_nest_rnn.conf) — add those as extra sub-topology roots.
    sub_topo = Topology(list(step_outputs))
    link_bases = list(dict.fromkeys(  # order-preserving dedup: deterministic
        m.attrs["link"].split("@")[0] for m in gb.memories
    ))
    extra_roots = [
        created[base]
        for base in link_bases
        if base not in sub_topo.layers and base in created
    ]
    if extra_roots:
        sub_topo = Topology(list(step_outputs) + extra_roots)
    # links may address auxiliary outputs like "<layer>@cell" (lstm_step)
    missing_links = [
        m
        for m in gb.memories
        if m.attrs["link"].split("@")[0] not in sub_topo.layers
    ]
    if missing_links:
        raise ValueError(
            f"memory links {[m.attrs['link'] for m in missing_links]} not found "
            f"in recurrent_group {gname!r} step outputs' graph"
        )

    # Boot layers are OUTER layers: include them as group parents so their
    # values exist in ctx.outputs at apply time.
    outer_inputs: List[LayerOutput] = (
        list(scanned) + [s.input for s in statics] + list(gb.boot_layers.values())
    )

    conf = LayerConf(
        name=gname,
        type="recurrent_group",
        size=step_outputs[0].size,
        inputs=tuple(o.name for o in outer_inputs),
        bias=False,
        attrs={
            "_sub_topology": sub_topo,
            "_memories": tuple(gb.memories),
            "_scan_placeholders": tuple(c.name for c in scan_placeholders),
            "_sub_scanned": tuple(sub_scanned),
            "_static_placeholders": tuple(
                (c.name, c.attrs.get("static_seq", False))
                for c in static_placeholders
            ),
            "_output": step_outputs[0].name,
            "n_scanned": len(scanned),
            "reverse": reverse,
        },
    )
    return LayerOutput(conf, outer_inputs)


# ---------------------------------------------------------------------------
# layer implementation
# ---------------------------------------------------------------------------


def _rg_init(conf, in_confs, rng):
    from paddle_tpu.core.compiler import CompiledNetwork

    sub = CompiledNetwork(conf.attrs["_sub_topology"])
    return sub.init_params(rng)


def _rg_init_state(conf, in_confs):
    from paddle_tpu.core.compiler import CompiledNetwork

    sub = CompiledNetwork(conf.attrs["_sub_topology"])
    return sub.init_state()


@register_layer(
    "recurrent_group", init=_rg_init, init_state=_rg_init_state, auto_activation=False
)
def recurrent_group_apply(conf, params, inputs, ctx: ApplyContext) -> SeqTensor:
    from paddle_tpu.core.compiler import CompiledNetwork

    a = conf.attrs
    sub_topo: Topology = a["_sub_topology"]
    # Inherit the enclosing network's compute dtype so scan carries keep a
    # consistent dtype under mixed precision.
    subnet = CompiledNetwork(sub_topo, compute_dtype=ctx.dtype)
    memories: Sequence[LayerConf] = a["_memories"]
    scan_names: Sequence[str] = a["_scan_placeholders"]
    static_info = a["_static_placeholders"]
    out_name: str = a["_output"]
    n_scan = a["n_scanned"]
    reverse = a["reverse"]

    sub_scanned = a.get("_sub_scanned", (False,) * n_scan)
    scanned = inputs[:n_scan]
    statics = inputs[n_scan : n_scan + len(static_info)]  # rest are boot layers
    lengths = scanned[0].lengths
    assert lengths is not None, "recurrent_group inputs must be sequences"
    t_max = scanned[0].max_len  # outer scan extent: T (plain) or S (nested)
    b = scanned[0].batch_size

    # Outer-axis-major scanned inputs, as SeqTensor pytrees so lax.scan
    # slices data AND per-subsequence lengths together: a nested input
    # [B, S, T, D] + sub_lengths [B, S] scans to an ordinary [B, T, D]
    # sequence per step (the TPU-native hasSubseq path —
    # RecurrentGradientMachine.cpp:446 re-batches frames instead).
    xs = []
    for s_in, is_sub in zip(scanned, sub_scanned):
        if is_sub:
            assert s_in.is_nested, (
                f"{conf.name}: SubsequenceInput requires a nested slot"
            )
            data = jnp.swapaxes(s_in.data, 0, 1)  # [S, B, T, ...]
            sub_len = jnp.swapaxes(s_in.sub_lengths, 0, 1)  # [S, B]
            if reverse:
                data = jnp.flip(data, axis=0)
                sub_len = jnp.flip(sub_len, axis=0)
            xs.append(SeqTensor(data, sub_len))
        else:
            x = jnp.swapaxes(s_in.data, 0, 1)  # [T, B, D]
            if reverse:
                x = jnp.flip(x, axis=0)
            xs.append(SeqTensor(x))
    tpos = jnp.arange(t_max, dtype=jnp.int32)[:, None]  # [T, 1]
    if reverse:
        valid = tpos >= (t_max - lengths[None, :])
    else:
        valid = tpos < lengths[None, :]
    mask_seq = valid[..., None].astype(jnp.float32)  # [T, B, 1]

    static_batch = {
        pname: (st if is_seq else SeqTensor(st.data))
        for (pname, is_seq), st in zip(static_info, statics)
    }
    sub_state0 = ctx.state.get(conf.name, {})

    # Sequence-valued memories (reference sequence-memory frames,
    # RecurrentGradientMachine.cpp:530-608) carry a whole padded sequence:
    # their static width must equal the linked layer's per-step padded
    # width, found by abstract evaluation of the step body (fixed-point
    # iteration: a link whose width depends on the memory's own width — e.g.
    # an elementwise transform — converges in one extra round).
    seq_widths = _seq_memory_widths(
        conf, subnet, params, memories, scan_names, static_batch, xs,
        ctx, sub_state0, b,
    )

    # initial memory carries
    init_carry = {}
    for m in memories:
        boot = m.attrs.get("boot")
        boot_const = m.attrs.get("boot_const_id")
        if m.attrs.get("is_seq"):
            w = seq_widths[m.name]
            if boot is not None:
                bt = ctx.outputs[boot]
                if bt.is_seq:
                    if bt.data.shape[1] > w:
                        # the boot layer's PADDED width exceeds the link's
                        # converged fixed-point width: any boot sequence
                        # longer than w loses its tail here.  Lengths are
                        # traced values, so whether real timesteps (vs mere
                        # padding, e.g. bucketed feeder pads) are dropped
                        # is unknowable at trace time — warn with both
                        # widths instead of clipping silently (the lengths
                        # clamp below keeps ≤w boots exactly correct).
                        warnings.warn(
                            f"seq memory '{m.name}': boot layer '{boot}' is "
                            f"padded to {bt.data.shape[1]} steps but the "
                            f"linked layer's fixed-point width is {w}; boot "
                            f"sequences longer than {w} steps will be "
                            "truncated before the first outer step",
                            stacklevel=2,
                        )
                    d = bt.data[:, :w]
                    if d.shape[1] < w:
                        pad = [(0, 0), (0, w - d.shape[1])] + [(0, 0)] * (
                            d.ndim - 2
                        )
                        d = jnp.pad(d, pad)
                    init_carry[m.name] = SeqTensor(
                        d, jnp.minimum(bt.lengths, w).astype(jnp.int32)
                    )
                else:  # non-seq boot -> a length-1 sequence
                    d = jnp.pad(
                        bt.data[:, None], [(0, 0), (0, w - 1), (0, 0)]
                    )
                    init_carry[m.name] = SeqTensor(
                        d, jnp.ones((b,), jnp.int32)
                    )
            else:  # unbooted: EMPTY sequence (zero lengths), not zeros-as-data
                init_carry[m.name] = SeqTensor(
                    jnp.zeros((b, w, m.size), ctx.dtype),
                    jnp.zeros((b,), jnp.int32),
                )
        elif boot is not None:
            init_carry[m.name] = ctx.outputs[boot].data
        elif boot_const is not None:
            # id-type memory booted with a constant id (reference
            # boot_with_const_id — used for generated-input memories);
            # these DO follow the scanned ids' integer dtype
            init_carry[m.name] = jnp.full(
                (b, m.size), boot_const, scanned[0].data.dtype
            )
        else:
            # memories carry float layer state: zeros at the COMPUTE dtype,
            # never the first scanned input's (an id sequence scanned first
            # made the carry int32 while the linked fc emits floats —
            # sequence_nest_rnn_multi_input.conf)
            init_carry[m.name] = jnp.zeros((b, m.size), ctx.dtype)

    step_rng = ctx.layer_rng(conf.name)
    t_iota = jnp.arange(t_max, dtype=jnp.uint32)

    # Epilogue hoisting: the maximal rowwise SUFFIX of the step graph that
    # no memory depends on runs ONCE on the stacked [T*B] sequence instead
    # of per scan step.  The canonical win is a per-step vocab projection
    # (seq2seq dec_out: 50 latency-bound [B,512]x[512,30000] GEMMs + a
    # [512,30000] grad accumulator carried through every backward step
    # become one batched GEMM) — the generalization of keeping input
    # projections outside the cell scans, and the TPU analogue of the
    # reference evaluating output frames via SequenceToBatch re-batching.
    # Disabled for nested inputs and sequence-valued memories, whose step
    # outputs are not plain [B, D] rows.
    # both hoists assume plain [B, D] per-step rows and non-seq carries
    rows_hoistable = not any(sub_scanned) and not any(
        m.attrs.get("is_seq") for m in memories
    )
    epilogue = None
    frontier = (out_name,)
    if rows_hoistable:
        static_seq = {p for (p, is_seq) in static_info if is_seq}
        epilogue, frontier = _split_epilogue(
            sub_topo, memories, out_name, static_seq
        )
    static_names = {p for (p, _s) in static_info}
    if epilogue is not None:
        # validate by a ONE-step abstract eval (shapes only) that every
        # frontier value really is a plain [B, D] row — a loop layer can
        # emit a sequence (expand over a static, sub-seq transforms) whose
        # stacked form must not be time-flattened
        probe = dict(static_batch)
        for pname, x in zip(scan_names, xs):
            probe[pname] = jax.tree_util.tree_map(lambda v: v[0], x)
        for m in memories:
            # mirror the real carries (dtype matters: id memories are int)
            probe[m.name] = SeqTensor(init_carry[m.name])
        outs_shape = jax.eval_shape(
            lambda p, pb: subnet.apply(
                p, pb, state=sub_state0, train=ctx.train, rng=None,
                only=set(sub_topo.order) - epilogue,
            )[0],
            params,
            probe,
        )
        scan_name_set = set(scan_names)
        for n in frontier:
            if n in static_names or n in scan_name_set:
                continue  # preset straight from the outer values below
            st = outs_shape[n]
            if (
                st.lengths is not None
                or st.sub_lengths is not None
                or st.data.ndim != 2
            ):
                epilogue, frontier = None, (out_name,)
                break
    # Prologue hoisting (the prefix complement): rowwise layers fed only by
    # scanned/static placeholders — in-step input projections like
    # gru_unit/lstmemory_group's mixed 3H/4H GEMMs — compute once on the
    # time-flattened inputs; the body reads their per-step slices.
    _pro_producer = _producer_resolver(sub_topo.layers)
    prologue = set()
    if rows_hoistable:
        prologue = _split_prologue(
            sub_topo, scan_names, static_info, epilogue or set()
        )
    pro_outs = {}
    pro_sliced = ()
    if prologue:
        pre_preset = {}
        for pname, x in zip(scan_names, xs):
            d = x.data  # [T, B, ...] (already flipped for reverse groups)
            pre_preset[pname] = SeqTensor(
                d.reshape((t_max * b,) + d.shape[2:])
            )
        for (pname, is_seq) in static_info:
            if not is_seq:
                pre_preset[pname] = SeqTensor(
                    _tile_rows(static_batch[pname].data, t_max)
                )
        pro_outs, _ = subnet.apply(
            params, {}, state=sub_state0, train=ctx.train, rng=None,
            only=prologue, preset=pre_preset,
        )
        # every computed output (incl. "@side" keys) whose base layer was
        # hoisted becomes a per-step scan input for the body
        pro_sliced = tuple(
            n for n in pro_outs if _pro_producer(n) in prologue
        )

    body_only = set(sub_topo.order) - (epilogue or set()) - prologue
    loop_only = (
        None if epilogue is None and not prologue else body_only
    )
    # static frontier inputs are step-invariant (tiled into the epilogue
    # preset directly); prologue-produced frontier values are already
    # available time-flattened — the scan emits neither
    frontier_scan = tuple(
        n for n in frontier
        if epilogue is None
        or (
            n not in static_names
            and n not in scan_names
            and _pro_producer(n) not in prologue
        )
    )
    pro_stacked = tuple(
        pro_outs[n].data.reshape((t_max, b) + pro_outs[n].data.shape[1:])
        for n in pro_sliced
    )

    # Fused attention-GRU lowering: when the whole remaining loop body IS
    # the v1 attention-decoder idiom (layers/attention.py
    # match_attention_gru_step), replace the generic per-layer scan with
    # the fused custom-VJP core (ops/rnn.py _attgru_core) — state
    # projection + GRU gates share one GEMM per step, the target-side
    # input projection runs once on the whole sequence, and the backward
    # defers every weight gradient to post-scan einsums.  v1 configs hit
    # this with no edits; any structural mismatch falls through to the
    # generic scan below.
    fused_hs = None
    from paddle_tpu.utils.flags import get_flag

    if (
        rows_hoistable
        and len(memories) == 1
        and not sub_state0
        and get_flag("fused_attention_gru")
    ):
        fused_hs = _try_fused_attention_gru(
            conf, subnet, params, memories[0], scan_names, static_info,
            static_batch, scanned, xs, mask_seq, init_carry, ctx,
            set(body_only), frontier_scan,
        )

    def body_core(carry_all, scan_in):
        carry, sub_state = carry_all
        n_x = len(xs)
        xt = scan_in[:n_x]
        pro_t = scan_in[n_x:-2]
        m_t = scan_in[-2]
        t_idx = scan_in[-1]
        sub_batch = dict(static_batch)
        for pname, x in zip(scan_names, xt):
            sub_batch[pname] = x  # SeqTensor: a sequence when SubsequenceInput
        for m in memories:
            if m.attrs.get("is_seq"):
                sub_batch[m.name] = carry[m.name]  # whole-sequence SeqTensor
            else:
                sub_batch[m.name] = SeqTensor(carry[m.name])
        # fold the timestep in so dropout/sampling decorrelate across steps
        rng_t = None if step_rng is None else jax.random.fold_in(step_rng, t_idx)
        outs, new_sub_state = subnet.apply(
            params, sub_batch, state=sub_state, train=ctx.train, rng=rng_t,
            only=loop_only,
            preset={
                n: SeqTensor(p) for n, p in zip(pro_sliced, pro_t)
            } or None,
        )
        new_carry = {}
        for m in memories:
            upd = outs[m.attrs["link"]]
            if m.attrs.get("is_seq"):
                old = carry[m.name]
                assert upd.lengths is not None, (
                    f"{conf.name}: seq memory {m.name} links "
                    f"{m.attrs['link']!r}, which is not a sequence"
                )
                new_carry[m.name] = SeqTensor(
                    jnp.where(
                        m_t[..., None] > 0,
                        upd.data,
                        old.data.astype(upd.data.dtype),
                    ),
                    jnp.where(m_t[:, 0] > 0, upd.lengths, old.lengths),
                )
            else:
                new_carry[m.name] = jnp.where(
                    m_t > 0, upd.data, carry[m.name].astype(upd.data.dtype)
                )
        # Return the whole SeqTensor so a seq-valued step output stacks its
        # per-step lengths too (the nested-output case).
        return (new_carry, new_sub_state), tuple(
            outs[n] for n in frontier_scan
        )

    # Mask-aware scan early-exit: when a batch's true max length sits below
    # the padded ladder rung (the bucket-shape contract pads T up to 16·2^k
    # — core.batch.canonicalize_batch / DataFeeder(ladder=...)), the
    # trailing scan steps are pure padding for EVERY row.  Wrapping the body
    # in lax.cond on a per-step any-row-live bit turns those dead steps into
    # a carry pass-through: the compiled shape stays the rung's (one
    # executable per bucket), the executed trip count shrinks to the bucket
    # bound.  Reverse groups flip their inputs, so their dead steps sit at
    # the START of the scan — the per-step bit covers both ends.
    scan_xs = tuple(xs) + pro_stacked + (mask_seq, t_iota)
    body = body_core
    if fused_hs is None and get_flag("scan_early_exit"):
        active_seq = jnp.any(valid, axis=1)  # [T] any row live at step t
        # dead steps must emit the live branch's exact output structure;
        # abstract-eval the body once (shapes only, no FLOPs) to know it
        slice0 = jax.tree_util.tree_map(lambda v: v[0], scan_xs)
        ys_struct = jax.eval_shape(
            lambda c, s: body_core(c, s)[1], (init_carry, sub_state0), slice0
        )

        def body(carry_all, scan_in):
            def live(c):
                return body_core(c, scan_in[:-1])

            def dead(c):
                zeros = jax.tree_util.tree_map(
                    lambda st: jnp.zeros(st.shape, st.dtype), ys_struct
                )
                return c, zeros

            return jax.lax.cond(scan_in[-1], live, dead, carry_all)

        scan_xs = scan_xs + (active_seq,)

    # Memory/step placeholders ride the compiler's data path per step.
    if fused_hs is not None:
        ys_stacked = (SeqTensor(fused_hs),)
    else:
        (_, sub_state_out), ys_stacked = jax.lax.scan(  # num: allow[N401] generic-group backward: weight cotangents accumulate at compute dtype across <=T ladder steps (PR-2 parity contract); f32 master updates + the bf16 convergence tests gate the loss
            body,
            (init_carry, sub_state0),
            scan_xs,
            unroll=_GROUP_UNROLL,
        )
        if sub_state0:
            ctx.new_state[conf.name] = sub_state_out

    group_logits = None
    if epilogue is not None:
        # run the hoisted suffix once over the whole stacked sequence,
        # time flattened into the batch (rowwise layers only, so [T*B]
        # rows are independent)
        preset = {}
        for n, st in zip(frontier_scan, ys_stacked):
            d = st.data  # [T, B, ...]
            preset[n] = SeqTensor(d.reshape((t_max * b,) + d.shape[2:]))
        for n in frontier:
            if n in preset:
                continue
            if _pro_producer(n) in prologue:
                preset[n] = pro_outs[n]  # already time-flattened
            elif n in scan_names:
                # the scan input itself: already held time-major in xs
                d = xs[scan_names.index(n)].data
                preset[n] = SeqTensor(
                    d.reshape((t_max * b,) + d.shape[2:])
                )
            else:  # step-invariant static: broadcast per step, don't stack
                preset[n] = SeqTensor(
                    _tile_rows(static_batch[n].data, t_max)
                )
        epi_outs, _ = subnet.apply(
            params, {}, state=sub_state0, train=ctx.train, rng=None,
            only=epilogue, preset=preset,
        )
        eo = epi_outs[out_name]
        ys = SeqTensor(
            eo.data.reshape((t_max, b) + eo.data.shape[1:])
        )
        lg = epi_outs.get(out_name + "@logits")
        if lg is not None:
            group_logits = lg.data.reshape(
                (t_max, b) + lg.data.shape[1:]
            )
    else:
        ys = ys_stacked[0]
    if ys.lengths is not None:
        # step emitted sequences -> nested [B, S, T, ...] output
        data, sub_len = ys.data, ys.lengths
        if reverse:
            data = jnp.flip(data, axis=0)
            sub_len = jnp.flip(sub_len, axis=0)
        data = jnp.swapaxes(data, 0, 1)  # [B, S, T, ...]
        out = SeqTensor(data, lengths, jnp.swapaxes(sub_len, 0, 1))
        return out.with_data(out.masked_data())
    ys = ys.data
    if reverse:
        ys = jnp.flip(ys, axis=0)
    ys = jnp.swapaxes(ys, 0, 1)  # [B, T, D]
    ys = ys * mask_like(ys, lengths)
    if group_logits is not None:
        # expose the hoisted softmax's pre-activation at the GROUP level so
        # a downstream cross_entropy fuses into log-softmax CE and the
        # [B, T, vocab] probabilities dead-code-eliminate entirely
        if reverse:
            group_logits = jnp.flip(group_logits, axis=0)
        ctx.outputs[conf.name + "@logits"] = SeqTensor(
            jnp.swapaxes(group_logits, 0, 1), lengths
        )
    return SeqTensor(ys, lengths)


def _try_fused_attention_gru(
    conf, subnet, params, mem, scan_names, static_info, static_batch,
    scanned, xs, mask_seq, init_carry, ctx, body_only, frontier_scan,
):
    """Lower a matched attention-GRU decoder step onto ops/rnn._attgru_core.

    Returns the [T, B, H] hidden sequence (time-major, matching what the
    generic scan would emit for the gru frontier value), or None when the
    step doesn't match / a runtime precondition fails — the caller then
    runs the generic scan.  Numerics are pinned identical to the unfused
    lowering by tests/test_attention_gru_fused.py."""
    from paddle_tpu.core.compiler import _cast_floats
    from paddle_tpu.layers.attention import match_attention_gru_step
    from paddle_tpu.ops.rnn import _attgru_core
    from paddle_tpu.utils.flags import get_flag

    sub_topo: Topology = conf.attrs["_sub_topology"]
    static_seq = {p for (p, is_seq) in static_info if is_seq}
    match = match_attention_gru_step(
        sub_topo.layers, mem, set(scan_names), static_seq
    )
    if match is None:
        return None
    # the fused core must replace the loop body EXACTLY: the scan's only
    # emitted value is the gru state, and every loop-resident layer is part
    # of the matched pattern (no extra step outputs, no side computation)
    if tuple(frontier_scan) != (match.gru,):
        return None
    loop_layers = {
        n for n in body_only
        if sub_topo.layers[n].type not in ("data", "step_input", "memory")
    }
    if loop_layers != set(match.matched):
        return None
    # runtime preconditions on the actual tensors
    enc_t = static_batch[match.enc_name]
    ep_t = static_batch[match.ep_name]
    if enc_t.data.ndim != 3 or ep_t.data.ndim != 3:
        return None
    # the unfused path masks the score softmax by enc_proj's lengths and
    # the context sum by enc's — only equivalent to the core's single mask
    # when they are the same lengths array (they are: enc_proj is a rowwise
    # projection of enc, which propagates the identical lengths object)
    if enc_t.lengths is not ep_t.lengths and not (
        enc_t.lengths is None and ep_t.lengths is None
    ):
        return None
    scan_idx = {n: i for i, n in enumerate(scan_names)}
    for _slot, pname in match.scan_slots:
        x = xs[scan_idx[pname]]
        s_in = scanned[scan_idx[pname]]
        if (
            x.lengths is not None  # SubsequenceInput slice: not a plain row
            or x.data.ndim != 3
            or getattr(s_in, "sparse_ids", False)
            or not jnp.issubdtype(x.data.dtype, jnp.floating)
        ):
            return None

    mixed = ctx.dtype != jnp.dtype(jnp.float32)

    def layer_p(name):
        p = subnet.layer_params(params, name)
        return _cast_floats(p, ctx.dtype) if mixed else p

    p_sp = layer_p(match.state_proj)
    p_sc = layer_p(match.scores)
    p_in = layer_p(match.in_proj)
    p_gru = layer_p(match.gru)
    if "w_h" not in p_gru or "w_c" not in p_gru:
        return None

    # fused state weight: one [H, P+2H] GEMM covers the attention state
    # projection AND the GRU update/reset gates
    w1 = jnp.concatenate([p_sp["w0"], p_gru["w_h"]], axis=1)
    v = p_sc["w0"][:, 0]
    w_ctx = p_in[f"w{match.ctx_slot}"]
    w_c = p_gru["w_c"]

    # target-side gate projections for the WHOLE sequence, outside the scan
    # (the generic path re-ran this [B,*]x[*,3H] GEMM every step because it
    # shares an fc with the in-loop context term)
    xg = None
    for slot, pname in match.scan_slots:
        x = xs[scan_idx[pname]].data  # [T, B, D], already flipped if reverse
        term = acc_einsum("tbd,dg->tbg", x, p_in[f"w{slot}"])
        xg = term if xg is None else xg + term
    for p in (p_in, p_gru):
        if "b" in p:
            xg = xg + p["b"]  # num: allow[N401] gate-bias grad sums over T at compute dtype; every weight grad in the fused core accumulates f32 post-scan
    ep = ep_t.data
    if "b" in p_sp:
        ep = ep + p_sp["b"]  # state-proj bias is step-invariant: fold here

    emask = enc_t.mask(bool) if enc_t.lengths is not None else None
    hs, _h_last = _attgru_core(
        (match.gate_act, match.act, match.att_act,
         bool(get_flag("scan_early_exit"))),
        xg, enc_t.data, ep, emask, w1, v, w_ctx, w_c,
        init_carry[mem.name], mask_seq > 0,
    )
    return hs


# Layer types whose rows are independent (time can fold into batch): every
# mixed projection kind is per-row (full_matrix/trans_full_matrix/table/
# identity/identity_offset/slice/scaling/dotmul — layers/mixed.py), and
# conv/context projections enter mixed as identity terms of ordinary
# layers, which would simply not hoist.
_HOIST_ROWWISE = frozenset(
    {"fc", "addto", "slope_intercept", "mixed", "embedding"}
)


def _producer_resolver(layers):
    """Map an input reference to its producing layer name: raw names pass
    through; "layer@side" side-output keys (lstm_step's "unit@cell")
    resolve to the base layer — but ONLY when the base actually names a
    layer, because scan/static placeholders legitimately contain '@'
    ("group@in0") and must not be mangled."""

    def producer(i):
        if i in layers:
            return i
        b = i.split("@")[0]
        return b if b in layers else i

    return producer


def _hoist_eligible(c, impl):
    return (
        c.type in _HOIST_ROWWISE
        and c.drop_rate == 0.0
        and impl.init_state is None
        and c.act != "sequence_softmax"
        and not c.attr("error_clip", 0.0)
    )


def _split_prologue(sub_topo, scan_names, static_info, epilogue):
    """The PREFIX complement of epilogue hoisting: rowwise layers whose
    transitive inputs are only scanned/static placeholders (never a
    memory) compute identically at every scan step offset — the classic
    in-step input projection (gru_unit/lstmemory_group's mixed 3H/4H
    projections; reference SequenceToBatch feeds pre-projected frames).
    They run ONCE on the time-flattened inputs before the scan; the body
    receives their per-step slices as extra scan inputs.  Returns the set
    of hoisted names (possibly empty)."""
    from paddle_tpu.layers.base import get_layer_impl

    layers = sub_topo.layers
    producer = _producer_resolver(layers)
    scanned = set(scan_names)
    static_ok = {p for (p, is_seq) in static_info if not is_seq}
    prologue = set()
    for name in sub_topo.order:
        c = layers[name]
        if c.type in ("data", "step_input", "memory") or name in epilogue:
            continue
        if not _hoist_eligible(c, get_layer_impl(c.type)):
            continue
        deps = [producer(i) for i in c.inputs]
        if not all(
            d in scanned or d in static_ok or d in prologue for d in deps
        ):
            continue
        if not any(d in scanned or d in prologue for d in deps):
            continue  # step-invariant (static-only): nothing to batch over
        prologue.add(name)
    return prologue


def _split_epilogue(sub_topo, memories, out_name, static_seq):
    """Partition the step graph for epilogue hoisting.

    Returns (epilogue_names, frontier_names): `epilogue` is the maximal
    suffix reaching `out_name` whose layers are rowwise (independent per
    [B] row, so time can fold into batch), stateless, dropout-free, and
    not ancestors of any memory link; `frontier` is every non-epilogue
    name the epilogue reads (loop layers, memory/step placeholders) —
    the scan body emits exactly these.  (None, (out_name,)) when nothing
    hoists."""
    from paddle_tpu.layers.base import get_layer_impl

    layers = sub_topo.layers
    producer = _producer_resolver(layers)
    loop_needed = set()
    stack = [producer(m.attrs["link"]) for m in memories]
    while stack:
        n = stack.pop()
        if n in loop_needed:
            continue
        loop_needed.add(n)
        if n in layers:  # memory placeholders live outside the sub topology
            stack.extend(producer(i) for i in layers[n].inputs)

    consumers: Dict[str, set] = {}
    for n in sub_topo.order:
        for i in layers[n].inputs:
            consumers.setdefault(producer(i), set()).add(n)

    epilogue = set()
    for name in reversed(sub_topo.order):
        cons = consumers.get(name, set())
        wanted = name == out_name or bool(cons)
        if not wanted or name in loop_needed:
            continue
        if not all(c in epilogue for c in cons):
            # SOME consumer stays in the loop (or is off the out cone), so
            # this output must be computed there; hoisting it too would
            # leave the loop-resident consumer reading a value the scan
            # body never produced (diamond graphs)
            continue
        c = layers[name]
        if c.type in ("data", "step_input", "memory"):
            continue  # placeholder: becomes frontier
        if not _hoist_eligible(c, get_layer_impl(c.type)):
            # ineligible: stays in the loop; consumers already in the
            # epilogue read it from the frontier
            loop_needed.add(name)
            continue
        epilogue.add(name)
    if out_name not in epilogue:
        return None, (out_name,)
    order_ix = {n: i for i, n in enumerate(sub_topo.order)}
    frontier = []
    for e in sorted(epilogue, key=order_ix.__getitem__):
        for i in layers[e].inputs:
            if producer(i) not in epilogue and i not in frontier:
                if i in static_seq:
                    # a sequence-valued static feeding the suffix: its
                    # per-step value is not a plain [B, D] row — bail
                    return None, (out_name,)
                frontier.append(i)
    return epilogue, tuple(frontier)


def _seq_memory_widths(
    conf, subnet, params, memories, scan_names, static_batch, xs,
    ctx, sub_state0, b,
) -> Dict[str, int]:
    """Static padded width of each sequence-valued memory = the linked
    layer's per-step padded width, found by abstract evaluation
    (jax.eval_shape) of the step body — no FLOPs, shapes only.  Iterates to
    a fixed point because a link's width can depend on the memory's own
    width (elementwise transforms of the memory); widths that keep changing
    (e.g. a concat that grows every step) cannot be a static scan carry and
    raise."""
    seq_mems = [m for m in memories if m.attrs.get("is_seq")]
    if not seq_mems:
        return {}
    # first-step slices of the scanned inputs, exactly as lax.scan hands
    # them to the body ([T,B,...] -> [B,...], nested sub-lengths included)
    x0 = [jax.tree_util.tree_map(lambda v: v[0], x) for x in xs]

    # initial guess: boot width, else the inner width of a nested scanned
    # input (the usual link target in hierarchical steps — a bad guess can
    # make the probe fail outright, e.g. addto(memory, subsequence) with
    # mismatched widths, before the fixed point is ever reached)
    nested_w = next(
        (x.data.shape[1] for x in x0 if getattr(x, "lengths", None) is not None),
        1,
    )
    widths: Dict[str, int] = {}
    for m in seq_mems:
        boot = m.attrs.get("boot")
        if boot is not None and ctx.outputs[boot].is_seq:
            widths[m.name] = ctx.outputs[boot].max_len
        else:
            widths[m.name] = nested_w

    def run_shapes(pb):
        return jax.eval_shape(
            lambda p, bb: subnet.apply(
                p, bb, state=sub_state0, train=ctx.train, rng=None
            )[0],
            params,
            pb,
        )

    for _ in range(3):
        pb = dict(static_batch)
        for pname, x in zip(scan_names, x0):
            pb[pname] = x
        for m in memories:
            if m.attrs.get("is_seq"):
                pb[m.name] = SeqTensor(
                    jnp.zeros((b, widths[m.name], m.size), jnp.float32),
                    jnp.zeros((b,), jnp.int32),
                )
            else:
                pb[m.name] = SeqTensor(jnp.zeros((b, m.size), jnp.float32))
        outs = run_shapes(pb)
        new_widths: Dict[str, int] = {}
        stable = True
        for m in seq_mems:
            out = outs[m.attrs["link"]]
            if out.lengths is None:
                raise ValueError(
                    f"{conf.name}: memory(is_seq=True) {m.name} links "
                    f"{m.attrs['link']!r}, which is not a sequence layer"
                )
            new_widths[m.name] = out.data.shape[1]
            stable = stable and new_widths[m.name] == widths[m.name]
        if stable:
            return widths
        widths = new_widths
    raise ValueError(
        f"{conf.name}: sequence-memory padded width did not reach a fixed "
        f"point (last {widths}); a step whose linked sequence grows every "
        "iteration cannot be carried through a static-shape scan"
    )


def _tile_rows(d: jnp.ndarray, t: int) -> jnp.ndarray:
    """Step-invariant [B, ...] value expanded to the time-flattened
    [t*B, ...] preset rows of the hoisted prologue/epilogue.  broadcast_to +
    reshape instead of jnp.tile: XLA keeps the T× expansion a broadcast
    fused into the consumer rather than a materialized copy (a wide static
    — e.g. an encoder summary feeding the hoisted suffix — would otherwise
    cost T× its footprint in HBM)."""
    return jnp.broadcast_to(d[None], (t,) + d.shape).reshape(
        (t * d.shape[0],) + d.shape[1:]
    )


def mask_like(ys: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """[B, T] validity mask broadcast-shaped to ys's rank (ys may carry any
    number of trailing axes — features, beam × token for an in-group
    generator, ...)."""
    t = jnp.arange(ys.shape[1], dtype=jnp.int32)
    m = (t[None, :] < lengths[:, None]).astype(ys.dtype)
    return m.reshape(m.shape + (1,) * (ys.ndim - 2))
