"""Sequence layers: pooling over time, first/last instance, expand, lstmemory,
gru, simple recurrent, seqreshape, seqconcat, sampling_id, eos detection.

Reference counterparts: paddle/gserver/layers/{SequencePoolLayer,
SequenceLastInstanceLayer,ExpandLayer,LstmLayer,GatedRecurrentLayer,
RecurrentLayer,SequenceReshapeLayer,SequenceConcatLayer,SamplingIdLayer,
EosIdCheckLayer}.cpp.

All operate on padded [B, T, ...] SeqTensors with length masks instead of the
reference's CSR `sequenceStartPositions` (Argument.h:84).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer
from paddle_tpu.ops import acc_matmul
from paddle_tpu.ops import rnn as rnn_ops


# ---------------------------------------------------------------------------
# sequence pooling — SequencePoolLayer (max/average/sum/sqrt_n over time)
# ---------------------------------------------------------------------------


def _masked_pool(data, mask, counts, kind):
    """Pool `data` over its axis-1 under `mask` (same leading dims)."""
    m = mask[..., None]
    if kind == "max":
        out = jnp.max(jnp.where(m > 0, data, -jnp.inf), axis=1)
        return jnp.where(jnp.isfinite(out), out, 0.0)  # all-padding rows -> 0
    s = jnp.sum(data * m, axis=1)
    if kind == "sum":
        return s
    n = jnp.maximum(counts.astype(data.dtype), 1.0)[..., None]
    return s / jnp.sqrt(n) if kind == "sqrt_n" else s / n


def _stride_windows(data, lengths, stride):
    """Chunk [B, T, ...] into ceil(T/stride) windows of `stride` steps:
    returns (flat [B*W, stride, D], per-window valid counts [B*W], W,
    out_lengths [B]) — the reference SequencePoolLayer stride path, which
    emits a SHORTER sequence of per-window values."""
    b, t = data.shape[:2]
    w = -(-t // stride)
    pad = w * stride - t
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)) + ((0, 0),) * (data.ndim - 2))
    flat = data.reshape((b * w, stride) + data.shape[2:])
    win_len = jnp.clip(
        lengths[:, None] - jnp.arange(w, dtype=lengths.dtype)[None, :] * stride,
        0,
        stride,
    )  # [B, W]
    out_lengths = (lengths + stride - 1) // stride
    return flat, win_len.reshape(b * w), w, out_lengths


@register_layer("seqpool")
def seqpool_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq, f"{conf.name}: seqpool input must be a sequence"
    kind = conf.attr("pool_type", "max")
    to_seq = conf.attr("agg_level", 0) == 1  # AggregateLevel.TO_SEQUENCE
    stride = conf.attr("stride", -1)
    assert stride <= 0 or not x.is_nested, (
        f"{conf.name}: stride pooling is undefined for nested sequences"
    )
    if conf.attr("output_max_index", False):
        # MaxPooling(output_max_index=True): per-feature argmax timestep
        # (reference MaxPoolingLayer index output)
        assert not x.is_nested and stride <= 0
        masked = jnp.where(
            x.mask(x.data.dtype)[..., None] > 0, x.data, -jnp.inf
        )
        return SeqTensor(jnp.argmax(masked, axis=1).astype(jnp.int32))
    if stride > 0 and not x.is_nested:
        assert not to_seq, f"{conf.name}: stride pooling is TO_NO_SEQUENCE only"
        b = x.data.shape[0]
        flat, counts, w, out_len = _stride_windows(x.data, x.lengths, stride)
        mask = (
            jnp.arange(stride, dtype=jnp.int32)[None, :] < counts[:, None]
        ).astype(x.data.dtype)
        pooled = _masked_pool(flat, mask, counts, kind).reshape(b, w, -1)
        out = SeqTensor(pooled, out_len)
        return out.with_data(out.masked_data())
    if x.is_nested:
        if to_seq:
            # pool each subsequence -> a plain sequence of pooled vectors
            # (reference SequencePoolLayer with trans_type="seq" reading
            # subSequenceStartPositions)
            b, s, t = x.data.shape[:3]
            inner = (
                jnp.arange(t, dtype=jnp.int32)[None, None, :]
                < x.sub_lengths[:, :, None]
            ).astype(x.data.dtype)
            flat = _masked_pool(
                x.data.reshape((b * s, t) + x.data.shape[3:]),
                inner.reshape(b * s, t),
                x.sub_lengths.reshape(b * s),
                kind,
            )
            out = flat.reshape((b, s) + flat.shape[1:])
            out = out * x.mask(out.dtype)[..., None]
            return SeqTensor(out, x.lengths)
        # pool the whole outer sequence -> one vector per sample
        b, s, t = x.data.shape[:3]
        data = x.data.reshape((b, s * t) + x.data.shape[3:])
        mask = x.sub_mask(x.data.dtype).reshape(b, s * t)
        counts = jnp.sum(x.sub_mask(jnp.int32), axis=(1, 2))
        return SeqTensor(_masked_pool(data, mask, counts, kind))
    assert not to_seq, f"{conf.name}: TO_SEQUENCE pooling needs nested input"
    return SeqTensor(_masked_pool(x.data, x.mask(x.data.dtype), x.lengths, kind))


# ---------------------------------------------------------------------------
# last / first instance — SequenceLastInstanceLayer (select_first flag)
# ---------------------------------------------------------------------------


def _select_ins(data, lengths, first):
    """First/last valid element along axis 1 of [N, T, D]."""
    if first:
        return data[:, 0]
    idx = jnp.maximum(lengths - 1, 0)
    return jnp.take_along_axis(data, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]


@register_layer("seqlastins")
def seqlastins_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    first = conf.attr("select_first", False)
    to_seq = conf.attr("agg_level", 0) == 1
    stride = conf.attr("stride", -1)
    assert stride <= 0 or not x.is_nested, (
        f"{conf.name}: stride selection is undefined for nested sequences"
    )
    if stride > 0:
        assert not to_seq, f"{conf.name}: stride selection is TO_NO_SEQUENCE only"
        b = x.data.shape[0]
        flat, counts, w, out_len = _stride_windows(x.data, x.lengths, stride)
        sel = _select_ins(
            flat.reshape(flat.shape[0], stride, -1), jnp.maximum(counts, 1), first
        ).reshape(b, w, -1)
        out = SeqTensor(sel, out_len)
        return out.with_data(out.masked_data())
    if x.is_nested:
        b, s, t = x.data.shape[:3]
        flat = _select_ins(
            x.data.reshape(b * s, t, -1), x.sub_lengths.reshape(b * s), first
        ).reshape(b, s, -1)  # first/last of EACH subsequence: [B, S, D]
        if to_seq:
            return SeqTensor(flat * x.mask(flat.dtype)[..., None], x.lengths)
        # first/last of the whole nested sample: pick the first/last valid
        # subsequence's first/last element
        return SeqTensor(_select_ins(flat, x.lengths, first))
    assert not to_seq, f"{conf.name}: TO_SEQUENCE selection needs nested input"
    return SeqTensor(_select_ins(x.data, x.lengths, first))


# ---------------------------------------------------------------------------
# expand — ExpandLayer: broadcast per-sample value across a sequence's steps
# ---------------------------------------------------------------------------


@register_layer("expand")
def expand_apply(conf, params, inputs, ctx):
    x, pattern = inputs
    assert pattern.is_seq
    from_seq = conf.attr("expand_level", 0) == 1  # ExpandLevel.FROM_SEQUENCE
    assert from_seq == x.is_seq, (
        f"{conf.name}: expand_level "
        f"{'FROM_SEQUENCE' if from_seq else 'FROM_NO_SEQUENCE'} does not "
        f"match a {'sequence' if x.is_seq else 'non-sequence'} input"
    )
    b = x.data.shape[0]
    d = x.data.shape[-1]
    if pattern.is_nested:
        s, t = pattern.max_len, pattern.max_sub_len
        if from_seq:
            # ExpandLevel.FROM_SEQUENCE: [B, S, D] seq -> nested, each
            # subsequence repeats its element across timesteps.  The feeder
            # buckets the nested S axis and plain T axes independently, so
            # logically aligned slots may differ in padded extent — align to
            # the pattern's S (valid entries are bounded by both lengths).
            assert not x.is_nested
            xd = x.data
            if xd.shape[1] < s:
                xd = jnp.pad(xd, ((0, 0), (0, s - xd.shape[1]), (0, 0)))
            elif xd.shape[1] > s:
                xd = xd[:, :s]
            out = jnp.broadcast_to(xd[:, :, None, :], (b, s, t, d))
        else:
            # FROM_NO_SEQUENCE: [B, D] -> every timestep of every subsequence
            out = jnp.broadcast_to(x.data[:, None, None, :], (b, s, t, d))
        return SeqTensor(out, pattern.lengths, pattern.sub_lengths)
    assert not from_seq, f"{conf.name}: FROM_SEQUENCE needs a nested pattern"
    t = pattern.max_len
    out = jnp.broadcast_to(x.data[:, None, :], (b, t, d))
    return SeqTensor(out, pattern.lengths)


# ---------------------------------------------------------------------------
# seqreshape — SequenceReshapeLayer: change feature width, T' = T*D/D'
# ---------------------------------------------------------------------------


@register_layer("seqreshape")
def seqreshape_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    b, t, d = x.data.shape
    d2 = conf.size
    out = x.masked_data().reshape(b, t * d // d2, d2)
    new_len = (x.lengths * d) // d2
    return SeqTensor(out, new_len)


# ---------------------------------------------------------------------------
# seqconcat — SequenceConcatLayer: concat two sequences along time
# ---------------------------------------------------------------------------


@register_layer("seqconcat")
def seqconcat_apply(conf, params, inputs, ctx):
    a, b = inputs
    assert a.is_seq and b.is_seq
    ta = a.max_len
    # Place b's valid steps right after a's valid steps, per row.
    total = ta + b.max_len
    out_len = a.lengths + b.lengths
    pos = jnp.arange(total, dtype=jnp.int32)[None, :]  # [1, Ttot]
    from_a = pos < a.lengths[:, None]
    b_idx = jnp.clip(pos - a.lengths[:, None], 0, b.max_len - 1)
    a_idx = jnp.clip(pos, 0, ta - 1)
    ga = jnp.take_along_axis(a.data, a_idx[..., None], axis=1)
    gb = jnp.take_along_axis(b.data, b_idx[..., None], axis=1)
    out = jnp.where(from_a[..., None], ga, gb)
    mask = pos < out_len[:, None]
    out = out * mask[..., None].astype(out.dtype)
    return SeqTensor(out, out_len)


# ---------------------------------------------------------------------------
# lstmemory — LstmLayer.cpp: input already projected to 4H by preceding layer
# ---------------------------------------------------------------------------


def lstmemory_init(conf, in_confs, rng):
    h = conf.size
    r1, r2 = jax.random.split(rng)
    p = {"w_h": init.normal(r1, (h, 4 * h), conf.attr("param_std"))}
    if conf.bias:
        # Reference packs gate bias + 3 peephole vectors into one 7H bias
        # (LstmLayer.cpp bias_ layout); we keep them named.
        p["b"] = init.zeros((4 * h,))
        p["w_ci"] = init.normal(jax.random.fold_in(r2, 0), (h,), 1.0)
        p["w_cf"] = init.normal(jax.random.fold_in(r2, 1), (h,), 1.0)
        p["w_co"] = init.normal(jax.random.fold_in(r2, 2), (h,), 1.0)
    return p


@register_layer("lstmemory", init=lstmemory_init, auto_activation=False)
def lstmemory_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq, "lstmemory input must be a sequence"
    hs, _ = rnn_ops.lstm_scan(
        x.data,
        params["w_h"],
        params.get("b"),
        params.get("w_ci"),
        params.get("w_cf"),
        params.get("w_co"),
        x.lengths,
        gate_act=conf.attr("gate_act", "sigmoid"),
        act=conf.attr("active_type", conf.act or "tanh"),
        state_act=conf.attr("state_act", "tanh"),
        reverse=conf.attr("reverse", False),
    )
    return SeqTensor(hs, x.lengths)


# ---------------------------------------------------------------------------
# gru — GatedRecurrentLayer.cpp: input projected to 3H
# ---------------------------------------------------------------------------


def gru_init(conf, in_confs, rng):
    h = conf.size
    r1, r2 = jax.random.split(rng)
    p = {
        "w_h": init.normal(r1, (h, 2 * h)),
        "w_c": init.normal(r2, (h, h)),
    }
    if conf.bias:
        p["b"] = init.zeros((3 * h,))
    return p


@register_layer("gru", init=gru_init, auto_activation=False)
def gru_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq, "gru input must be a sequence"
    hs, _ = rnn_ops.gru_scan(
        x.data,
        params["w_h"],
        params["w_c"],
        params.get("b"),
        x.lengths,
        gate_act=conf.attr("gate_act", "sigmoid"),
        act=conf.attr("active_type", conf.act or "tanh"),
        reverse=conf.attr("reverse", False),
    )
    return SeqTensor(hs, x.lengths)


# ---------------------------------------------------------------------------
# recurrent — RecurrentLayer.cpp: h_t = act(x_t + W h₋)
# ---------------------------------------------------------------------------


def recurrent_init(conf, in_confs, rng):
    h = conf.size
    p = {"w_h": init.normal(rng, (h, h), conf.attr("param_std"))}
    if conf.bias:
        p["b"] = init.zeros((h,))
    return p


@register_layer("recurrent", init=recurrent_init, auto_activation=False)
def recurrent_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    hs, _ = rnn_ops.simple_rnn_scan(
        x.data,
        params["w_h"],
        params.get("b"),
        x.lengths,
        act=conf.act or "tanh",
        reverse=conf.attr("reverse", False),
    )
    return SeqTensor(hs, x.lengths)


# ---------------------------------------------------------------------------
# gru_step / lstm_step — GruStepLayer.cpp / LstmStepLayer.cpp: one-timestep
# cells used inside recurrent_group decoders
# ---------------------------------------------------------------------------


def gru_step_init(conf, in_confs, rng):
    h = conf.size
    std = conf.attr("param_std")
    if conf.attr("tied_weights", False):
        p = {"w": init.normal(rng, (h, h), std)}
    else:
        r1, r2 = jax.random.split(rng)
        p = {
            "w_h": init.normal(r1, (h, 2 * h), std),
            "w_c": init.normal(r2, (h, h), std),
        }
    if conf.bias:
        p["b"] = init.zeros((3 * h,))
    return p


@register_layer("gru_step", init=gru_step_init, auto_activation=False)
def gru_step_apply(conf, params, inputs, ctx):
    """Reference GRU recurrence — GruStepLayer.cpp AND gru_step_naive_layer
    share the SAME math (both lower to GruCompute → hl_gru_ops.cuh
    gru_resetOutput/gru_finalOutput):
        u = σ(x_u + h₋·U_u),  r = σ(x_r + h₋·U_r)
        c = act(x_c + (r⊙h₋)·W_c)          # resetOutput = prevOut*r first
        h = (1-u)⊙h₋ + u⊙c                  # prevOut - u·prevOut + u·c
    naive=True differs only in parameter ASSEMBLY (three separate
    full_matrix_projections instead of the fused 3H gate weight); with a
    NAMED param_attr the reference ties all three projections to ONE H×H
    matrix — that case sets tied_weights and uses a single `w`."""
    from paddle_tpu.ops.activations import get_activation

    x, h_p = inputs[0].data, inputs[1].data  # [B, 3H], [B, H]
    h = conf.size
    f_gate = get_activation(conf.attr("gate_act", "sigmoid"))
    f_act = get_activation(conf.attr("active_type", "tanh"))
    if "b" in params:
        x = x + params["b"]
    x_u, x_r, x_c = jnp.split(x, 3, axis=-1)
    if conf.attr("tied_weights", False):
        w = params["w"]
        hw = acc_matmul(h_p, w)
        u_t = f_gate(x_u + hw)
        r_t = f_gate(x_r + hw)
        w_c = w
    else:
        ur = acc_matmul(h_p, params["w_h"])
        u_t = f_gate(x_u + ur[:, :h])
        r_t = f_gate(x_r + ur[:, h:])
        w_c = params["w_c"]
    c_t = f_act(x_c + acc_matmul(r_t * h_p, w_c))
    h_t = (1.0 - u_t) * h_p + u_t * c_t
    return SeqTensor(h_t)


def lstm_step_init(conf, in_confs, rng):
    h = conf.size
    p = {}
    if conf.attr("recurrent_weight", True):
        p["w_h"] = init.normal(rng, (h, 4 * h))
    if conf.bias:
        p["b"] = init.zeros((4 * h,))
    return p


@register_layer("lstm_step", init=lstm_step_init, auto_activation=False)
def lstm_step_apply(conf, params, inputs, ctx):
    """inputs: (gates [B,4H], prev_h [B,H], prev_c [B,H]); output h; the cell
    state is exposed as `<name>@cell` for memory links (the reference reaches
    it via get_output_layer on the step's second output)."""
    from paddle_tpu.ops.activations import get_activation

    x, h_p, c_p = (t.data for t in inputs)
    f_gate = get_activation(conf.attr("gate_act", "sigmoid"))
    f_act = get_activation(conf.attr("active_type", "tanh"))
    f_state = get_activation(conf.attr("state_act", "tanh"))
    a = x + acc_matmul(h_p, params["w_h"]) if "w_h" in params else x
    if "b" in params:
        a = a + params["b"]
    a_i, a_f, a_g, a_o = jnp.split(a, 4, axis=-1)
    i_t = f_gate(a_i)
    f_t = f_gate(a_f)
    c_t = f_t * c_p + i_t * f_act(a_g)
    o_t = f_gate(a_o)
    h_t = o_t * f_state(c_t)
    ctx.outputs[conf.name + "@cell"] = SeqTensor(c_t)
    return SeqTensor(h_t)


# ---------------------------------------------------------------------------
# sampling_id — SamplingIdLayer.cpp: sample an id from each row's distribution
# ---------------------------------------------------------------------------


@register_layer("sampling_id", auto_activation=False)
def sampling_id_apply(conf, params, inputs, ctx):
    x = inputs[0]
    rng = ctx.layer_rng(conf.name)
    if rng is None:
        out = jnp.argmax(x.data, axis=-1)
    else:
        out = jax.random.categorical(rng, jnp.log(jnp.maximum(x.data, 1e-10)), axis=-1)
    return SeqTensor(out.astype(jnp.int32), x.lengths)


# ---------------------------------------------------------------------------
# eos_id — EosIdCheckLayer.cpp: 1 where id == eos
# ---------------------------------------------------------------------------


@register_layer("eos_id", auto_activation=False)
def eos_id_apply(conf, params, inputs, ctx):
    x = inputs[0]
    eos = conf.attrs["eos_id"]
    ids = x.data.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return SeqTensor((ids == eos).astype(jnp.float32), x.lengths)


# ---------------------------------------------------------------------------
# context_projection — ContextProjection (paddle/function/ContextProjectionOp,
# gserver/layers/ContextProjection.cpp): per-timestep window concat
# ---------------------------------------------------------------------------


@register_layer("context_projection")
def context_projection_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    clen = conf.attrs["context_len"]
    start = conf.attrs["context_start"]
    data = x.masked_data()  # zeros beyond length so windows read zeros
    b, t, d = data.shape
    lo = max(-start, 0)
    hi = max(start + clen - 1, 0)
    padded = jnp.pad(data, ((0, 0), (lo, hi), (0, 0)))
    slices = [
        jax.lax.dynamic_slice_in_dim(padded, lo + start + k, t, axis=1)
        for k in range(clen)
    ]
    return SeqTensor(jnp.concatenate(slices, axis=-1), x.lengths)


# ---------------------------------------------------------------------------
# row_conv — RowConvLayer.cpp: causal look-ahead convolution over time
# ---------------------------------------------------------------------------


def row_conv_init(conf, in_confs, rng):
    k = conf.attrs["context_len"]
    return {"w": init.normal(rng, (k, conf.size), 1.0 / max(k, 1))}


@register_layer("row_conv", init=row_conv_init)
def row_conv_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    data = x.masked_data()
    b, t, d = data.shape
    k = conf.attrs["context_len"]
    padded = jnp.pad(data, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(
        jax.lax.dynamic_slice_in_dim(padded, j, t, axis=1) * params["w"][j]
        for j in range(k)
    )
    return SeqTensor(out, x.lengths)


# ---------------------------------------------------------------------------
# conv_shift — ConvShiftLayer.cpp: circular convolution of each row pair
# ---------------------------------------------------------------------------


@register_layer("conv_shift")
def conv_shift_apply(conf, params, inputs, ctx):
    a, b = inputs  # a: [B, D], b: [B, K] (K odd)
    k = b.data.shape[-1]
    d = a.data.shape[-1]
    half = k // 2
    idx = (jnp.arange(d)[:, None] + jnp.arange(-half, half + 1)[None, :]) % d
    gathered = a.data[:, idx]  # [B, D, K]
    out = jnp.einsum("bdk,bk->bd", gathered, b.data)
    return SeqTensor(out, a.lengths)


# ---------------------------------------------------------------------------
# subseq/get_output-style helpers
# ---------------------------------------------------------------------------


@register_layer("slice_time")
def slice_time_apply(conf, params, inputs, ctx):
    """Take timestep `offset` of a sequence as a non-seq row (used by memory
    boot and attention wiring)."""
    x = inputs[0]
    off = conf.attr("offset", 0)
    return SeqTensor(x.data[:, off])
