"""Sequence layers: pooling over time, first/last instance, expand, lstmemory,
gru, simple recurrent, seqreshape, seqconcat, sampling_id, eos detection.

Reference counterparts: paddle/gserver/layers/{SequencePoolLayer,
SequenceLastInstanceLayer,ExpandLayer,LstmLayer,GatedRecurrentLayer,
RecurrentLayer,SequenceReshapeLayer,SequenceConcatLayer,SamplingIdLayer,
EosIdCheckLayer}.cpp.

All operate on padded [B, T, ...] SeqTensors with length masks instead of the
reference's CSR `sequenceStartPositions` (Argument.h:84).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer
from paddle_tpu.ops import rnn as rnn_ops


# ---------------------------------------------------------------------------
# sequence pooling — SequencePoolLayer (max/average/sum/sqrt_n over time)
# ---------------------------------------------------------------------------


@register_layer("seqpool")
def seqpool_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq, f"{conf.name}: seqpool input must be a sequence"
    kind = conf.attr("pool_type", "max")
    m = x.mask(x.data.dtype)[..., None]  # [B, T, 1]
    if kind == "max":
        data = jnp.where(m > 0, x.data, -jnp.inf)
        out = jnp.max(data, axis=1)
        # all-padding rows (len 0) -> 0
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    else:
        s = jnp.sum(x.data * m, axis=1)
        if kind == "sum":
            out = s
        else:
            n = jnp.maximum(x.lengths.astype(x.data.dtype), 1.0)[:, None]
            if kind == "sqrt_n":
                out = s / jnp.sqrt(n)
            else:  # average
                out = s / n
    return SeqTensor(out)


# ---------------------------------------------------------------------------
# last / first instance — SequenceLastInstanceLayer (select_first flag)
# ---------------------------------------------------------------------------


@register_layer("seqlastins")
def seqlastins_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    if conf.attr("select_first", False):
        out = x.data[:, 0]
    else:
        idx = jnp.maximum(x.lengths - 1, 0)
        out = jnp.take_along_axis(
            x.data, idx[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
    return SeqTensor(out)


# ---------------------------------------------------------------------------
# expand — ExpandLayer: broadcast per-sample value across a sequence's steps
# ---------------------------------------------------------------------------


@register_layer("expand")
def expand_apply(conf, params, inputs, ctx):
    x, pattern = inputs  # x: [B, D] non-seq; pattern: [B, T, ...] seq
    assert pattern.is_seq
    t = pattern.max_len
    out = jnp.broadcast_to(
        x.data[:, None, :], (x.data.shape[0], t, x.data.shape[-1])
    )
    return SeqTensor(out, pattern.lengths)


# ---------------------------------------------------------------------------
# seqreshape — SequenceReshapeLayer: change feature width, T' = T*D/D'
# ---------------------------------------------------------------------------


@register_layer("seqreshape")
def seqreshape_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    b, t, d = x.data.shape
    d2 = conf.size
    out = x.masked_data().reshape(b, t * d // d2, d2)
    new_len = (x.lengths * d) // d2
    return SeqTensor(out, new_len)


# ---------------------------------------------------------------------------
# seqconcat — SequenceConcatLayer: concat two sequences along time
# ---------------------------------------------------------------------------


@register_layer("seqconcat")
def seqconcat_apply(conf, params, inputs, ctx):
    a, b = inputs
    assert a.is_seq and b.is_seq
    ta = a.max_len
    # Place b's valid steps right after a's valid steps, per row.
    total = ta + b.max_len
    out_len = a.lengths + b.lengths
    pos = jnp.arange(total, dtype=jnp.int32)[None, :]  # [1, Ttot]
    from_a = pos < a.lengths[:, None]
    b_idx = jnp.clip(pos - a.lengths[:, None], 0, b.max_len - 1)
    a_idx = jnp.clip(pos, 0, ta - 1)
    ga = jnp.take_along_axis(a.data, a_idx[..., None], axis=1)
    gb = jnp.take_along_axis(b.data, b_idx[..., None], axis=1)
    out = jnp.where(from_a[..., None], ga, gb)
    mask = pos < out_len[:, None]
    out = out * mask[..., None].astype(out.dtype)
    return SeqTensor(out, out_len)


# ---------------------------------------------------------------------------
# lstmemory — LstmLayer.cpp: input already projected to 4H by preceding layer
# ---------------------------------------------------------------------------


def lstmemory_init(conf, in_confs, rng):
    h = conf.size
    r1, r2 = jax.random.split(rng)
    p = {"w_h": init.normal(r1, (h, 4 * h))}
    if conf.bias:
        # Reference packs gate bias + 3 peephole vectors into one 7H bias
        # (LstmLayer.cpp bias_ layout); we keep them named.
        p["b"] = init.zeros((4 * h,))
        p["w_ci"] = init.normal(jax.random.fold_in(r2, 0), (h,), 1.0)
        p["w_cf"] = init.normal(jax.random.fold_in(r2, 1), (h,), 1.0)
        p["w_co"] = init.normal(jax.random.fold_in(r2, 2), (h,), 1.0)
    return p


@register_layer("lstmemory", init=lstmemory_init, auto_activation=False)
def lstmemory_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq, "lstmemory input must be a sequence"
    hs, _ = rnn_ops.lstm_scan(
        x.data,
        params["w_h"],
        params.get("b"),
        params.get("w_ci"),
        params.get("w_cf"),
        params.get("w_co"),
        x.lengths,
        gate_act=conf.attr("gate_act", "sigmoid"),
        act=conf.attr("active_type", conf.act or "tanh"),
        state_act=conf.attr("state_act", "tanh"),
        reverse=conf.attr("reverse", False),
    )
    return SeqTensor(hs, x.lengths)


# ---------------------------------------------------------------------------
# gru — GatedRecurrentLayer.cpp: input projected to 3H
# ---------------------------------------------------------------------------


def gru_init(conf, in_confs, rng):
    h = conf.size
    r1, r2 = jax.random.split(rng)
    p = {
        "w_h": init.normal(r1, (h, 2 * h)),
        "w_c": init.normal(r2, (h, h)),
    }
    if conf.bias:
        p["b"] = init.zeros((3 * h,))
    return p


@register_layer("gru", init=gru_init, auto_activation=False)
def gru_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq, "gru input must be a sequence"
    hs, _ = rnn_ops.gru_scan(
        x.data,
        params["w_h"],
        params["w_c"],
        params.get("b"),
        x.lengths,
        gate_act=conf.attr("gate_act", "sigmoid"),
        act=conf.attr("active_type", conf.act or "tanh"),
        reverse=conf.attr("reverse", False),
    )
    return SeqTensor(hs, x.lengths)


# ---------------------------------------------------------------------------
# recurrent — RecurrentLayer.cpp: h_t = act(x_t + W h₋)
# ---------------------------------------------------------------------------


def recurrent_init(conf, in_confs, rng):
    h = conf.size
    p = {"w_h": init.normal(rng, (h, h))}
    if conf.bias:
        p["b"] = init.zeros((h,))
    return p


@register_layer("recurrent", init=recurrent_init, auto_activation=False)
def recurrent_apply(conf, params, inputs, ctx):
    x = inputs[0]
    assert x.is_seq
    hs, _ = rnn_ops.simple_rnn_scan(
        x.data,
        params["w_h"],
        params.get("b"),
        x.lengths,
        act=conf.act or "tanh",
        reverse=conf.attr("reverse", False),
    )
    return SeqTensor(hs, x.lengths)


# ---------------------------------------------------------------------------
# sampling_id — SamplingIdLayer.cpp: sample an id from each row's distribution
# ---------------------------------------------------------------------------


@register_layer("sampling_id", auto_activation=False)
def sampling_id_apply(conf, params, inputs, ctx):
    x = inputs[0]
    rng = ctx.layer_rng(conf.name)
    if rng is None:
        out = jnp.argmax(x.data, axis=-1)
    else:
        out = jax.random.categorical(rng, jnp.log(jnp.maximum(x.data, 1e-10)), axis=-1)
    return SeqTensor(out.astype(jnp.int32), x.lengths)


# ---------------------------------------------------------------------------
# eos_id — EosIdCheckLayer.cpp: 1 where id == eos
# ---------------------------------------------------------------------------


@register_layer("eos_id", auto_activation=False)
def eos_id_apply(conf, params, inputs, ctx):
    x = inputs[0]
    eos = conf.attrs["eos_id"]
    ids = x.data.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return SeqTensor((ids == eos).astype(jnp.float32), x.lengths)


# ---------------------------------------------------------------------------
# subseq/get_output-style helpers
# ---------------------------------------------------------------------------


@register_layer("slice_time")
def slice_time_apply(conf, params, inputs, ctx):
    """Take timestep `offset` of a sequence as a non-seq row (used by memory
    boot and attention wiring)."""
    x = inputs[0]
    off = conf.attr("offset", 0)
    return SeqTensor(x.data[:, off])
