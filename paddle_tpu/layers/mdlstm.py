"""Multi-dimensional (2D) LSTM — reference MDLstmLayer
(paddle/gserver/layers/MDLstmLayer.cpp:180-240): an LSTM whose recurrence
runs over BOTH image axes, with one forget gate per dimension
(Graves' multi-dimensional RNN).

TPU-native lowering: a lax.scan over rows whose body is a lax.scan over
columns; each cell sees its left neighbor (inner carry) and top neighbor
(outer carry, a whole row of states).  Gates come pre-projected from the
input layer as 5*size channels (i, f_row, f_col, o, g), like lstmemory's
4*size convention.  The reference packs one n×(3+numDims)n recurrent matrix;
here the left/top recurrences get separate matrices (w_row, w_col) — same
capacity, simpler layout.  Direction flags flip the scan over either axis
(the reference's 2^numDims directions are built from multiple layers)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer
from paddle_tpu.ops.activations import get_activation


def mdlstm_init(conf, in_confs, rng):
    n = conf.attrs["channels"]
    r1, r2 = jax.random.split(rng)
    p = {
        "w_row": init.normal(r1, (n, 5 * n)),  # from the top neighbor
        "w_col": init.normal(r2, (n, 5 * n)),  # from the left neighbor
    }
    if conf.bias:
        p["b"] = init.zeros((5 * n,))
    return p


@register_layer("mdlstmemory", init=mdlstm_init, auto_activation=False)
def mdlstm_apply(conf, params, inputs, ctx):
    a = conf.attrs
    n = a["channels"]  # hidden width; conf.size is the flattened extent
    h_img, w_img, c_in = a["in_h"], a["in_w"], a["in_c"]
    assert c_in == 5 * n, (
        f"{conf.name}: input must be pre-projected to 5*size gates "
        f"(got {c_in} channels for size {n})"
    )
    from paddle_tpu.layers.conv import to_nhwc

    x = to_nhwc(inputs[0].data, h_img, w_img, c_in)
    b = x.shape[0]
    if a.get("reverse_h"):
        x = jnp.flip(x, axis=1)
    if a.get("reverse_w"):
        x = jnp.flip(x, axis=2)

    f_gate = get_activation(conf.attr("gate_act", "sigmoid"))
    f_act = get_activation(conf.attr("active_type", "tanh"))
    f_state = get_activation(conf.attr("state_act", "tanh"))
    w_row, w_col = params["w_row"], params["w_col"]
    bias = params.get("b")

    def cell(gates, h_left, c_left, h_top, c_top):
        g = gates + h_left @ w_col + h_top @ w_row
        if bias is not None:
            g = g + bias
        gi, gfr, gfc, go, gg = jnp.split(g, 5, axis=-1)
        c = f_gate(gfc) * c_left + f_gate(gfr) * c_top + f_gate(gi) * f_act(gg)
        h = f_gate(go) * f_state(c)
        return h, c

    def row_body(row_carry, x_row):
        h_top_row, c_top_row = row_carry  # [B, W, n]

        def col_body(col_carry, col_in):
            h_left, c_left = col_carry
            gates, h_top, c_top = col_in
            h, c = cell(gates, h_left, c_left, h_top, c_top)
            return (h, c), (h, c)

        zeros = jnp.zeros((b, n), x_row.dtype)
        (_, _), (h_row, c_row) = jax.lax.scan(
            col_body,
            (zeros, zeros),
            (
                jnp.swapaxes(x_row, 0, 1),  # [W, B, 5n]
                jnp.swapaxes(h_top_row, 0, 1),
                jnp.swapaxes(c_top_row, 0, 1),
            ),
        )
        h_row = jnp.swapaxes(h_row, 0, 1)  # [B, W, n]
        c_row = jnp.swapaxes(c_row, 0, 1)
        return (h_row, c_row), h_row

    zeros_row = jnp.zeros((b, w_img, n), x.dtype)
    _, hs = jax.lax.scan(
        row_body, (zeros_row, zeros_row), jnp.swapaxes(x, 0, 1)  # [H, B, W, 5n]
    )
    hs = jnp.swapaxes(hs, 0, 1)  # [B, H, W, n]
    if a.get("reverse_h"):
        hs = jnp.flip(hs, axis=1)
    if a.get("reverse_w"):
        hs = jnp.flip(hs, axis=2)
    return SeqTensor(hs)
