"""Core layer implementations: data, fc, embedding, addto, concat, scaling,
slope_intercept, interpolation, sum_to_one_norm, row_l2_norm, maxid, multiplex.

Reference counterparts live in paddle/gserver/layers/ (FullyConnectedLayer.cpp,
TableProjection.cpp, AddtoLayer.cpp, ConcatenateLayer.cpp, ScalingLayer.cpp,
SlopeInterceptLayer.cpp, InterpolationLayer.cpp, NormLayer.cpp, MaxIdLayer.cpp,
MultiplexLayer.cpp).  Here each is a pure jnp trace; matmuls map onto the MXU
and elementwise ops fuse into them.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerConf
from paddle_tpu.layers.base import ApplyContext, register_layer
from paddle_tpu.ops import acc_matmul


def _flat2d(x: jnp.ndarray) -> jnp.ndarray:
    """Collapse trailing dims: [B, ...] -> [B, prod(...)] (the reference keeps
    everything logically flat between layers, Matrix rows = batch)."""
    if x.ndim == 2:
        return x
    return x.reshape(x.shape[0], -1)


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------


@register_layer("data")
def data_apply(conf, params, inputs, ctx):  # pragma: no cover - handled by compiler
    raise RuntimeError("data layers are fed directly by the compiler")


# ---------------------------------------------------------------------------
# fc — FullyConnectedLayer.cpp; one weight per input, shared bias
# ---------------------------------------------------------------------------


def fc_init(conf: LayerConf, in_confs: List[LayerConf], rng) -> Dict[str, Any]:
    params: Dict[str, Any] = {}
    stds = conf.attr("param_stds")
    for i, ic in enumerate(in_confs):
        std = stds[i] if stds is not None else conf.attr("param_std")
        params[f"w{i}"] = init.normal(
            jax.random.fold_in(rng, i), (ic.size, conf.size), std
        )
    if conf.bias:
        params["b"] = init.zeros((conf.size,))
    return params


@register_layer("fc", init=fc_init)
def fc_apply(conf, params, inputs: List[SeqTensor], ctx: ApplyContext) -> SeqTensor:
    acc = None
    lengths = None
    sub_lengths = None
    from paddle_tpu.layers.base import gather_sum_rows, is_sparse_ids

    for i, t in enumerate(inputs):
        x = t.data
        w = params[f"w{i}"]
        if is_sparse_ids(t, int(w.shape[0])):
            # big-vocab sparse_binary slot in padded-id form: the multi-hot
            # matmul is a gather-sum of touched rows
            if t.is_seq:
                lengths, sub_lengths = t.lengths, t.sub_lengths
            y = gather_sum_rows(w, x)
            acc = y if acc is None else acc + y
            continue
        if t.is_nested:
            lengths, sub_lengths = t.lengths, t.sub_lengths  # [B,S,T,D] as-is
        elif t.is_seq:
            lengths = t.lengths
            if x.ndim > 3:
                x = x.reshape(x.shape[0], x.shape[1], -1)
        else:
            x = _flat2d(x)
        y = acc_matmul(x, w)  # f32-accumulating under mixed precision
        acc = y if acc is None else acc + y
    if "b" in params:
        acc = acc + params["b"]  # num: allow[N401] bias-grad batch reduce rides the compute dtype; the heavy weight-grad contractions accumulate f32 via acc_matmul and masters stay f32
    return SeqTensor(acc, lengths, sub_lengths)


# ---------------------------------------------------------------------------
# embedding — TableProjection / table_projection (embedding_layer in DSL)
# ---------------------------------------------------------------------------


def embedding_init(conf, in_confs, rng):
    vocab = in_confs[0].size
    std = conf.attr("param_std")
    return {"w": init.normal(rng, (vocab, conf.size), std)}


@register_layer("embedding", init=embedding_init)
def embedding_apply(conf, params, inputs, ctx):
    ids = inputs[0]
    idx = ids.data.astype(jnp.int32)
    # Squeeze a trailing singleton FEATURE axis ([B,1] / [B,T,1] id columns)
    # — but a nested slot's axes are all structural ([B,S,T] with T possibly
    # padded to 1), so no squeeze there.
    if idx.ndim >= 2 and idx.shape[-1] == 1 and not ids.is_nested:
        idx = idx[..., 0]
    from paddle_tpu.layers.base import take_rows_or_zero

    # out-of-range ids (e.g. the providers' 0xffffffff OOV sentinel)
    # contribute a zero row, reference KeMatrixAddRows semantics
    out = take_rows_or_zero(params["w"], idx)
    return SeqTensor(out, ids.lengths, ids.sub_lengths)


# ---------------------------------------------------------------------------
# addto — AddtoLayer.cpp: elementwise sum of equally-sized inputs (+ bias)
# ---------------------------------------------------------------------------


def addto_init(conf, in_confs, rng):
    return {"b": init.zeros((conf.size,))} if conf.bias else {}


@register_layer("addto", init=addto_init)
def addto_apply(conf, params, inputs, ctx):
    acc = inputs[0].data
    for t in inputs[1:]:
        acc = acc + t.data
    if "b" in params:
        acc = acc + params["b"]
    return inputs[0].with_data(acc)


# ---------------------------------------------------------------------------
# concat — ConcatenateLayer.cpp: feature-axis concat
# ---------------------------------------------------------------------------


@register_layer("concat")
def concat_apply(conf, params, inputs, ctx):
    datas = []
    lengths = None
    sub_lengths = None
    for t in inputs:
        x = t.data
        if t.is_seq:
            lengths = t.lengths
            sub_lengths = t.sub_lengths
        elif x.ndim > 2:
            x = _flat2d(x)
        datas.append(x)
    return SeqTensor(jnp.concatenate(datas, axis=-1), lengths, sub_lengths)


# ---------------------------------------------------------------------------
# scaling — ScalingLayer.cpp: y = weight_scalar_per_row * x
# ---------------------------------------------------------------------------


@register_layer("scaling")
def scaling_apply(conf, params, inputs, ctx):
    w, x = inputs  # w: [B,1], x: [B,D]
    return x.with_data(x.data * w.data)


# ---------------------------------------------------------------------------
# slope_intercept — SlopeInterceptLayer.cpp: y = slope * x + intercept
# ---------------------------------------------------------------------------


@register_layer("slope_intercept")
def slope_intercept_apply(conf, params, inputs, ctx):
    x = inputs[0]
    slope = conf.attr("slope", 1.0)
    intercept = conf.attr("intercept", 0.0)
    return x.with_data(slope * x.data + intercept)


# ---------------------------------------------------------------------------
# interpolation — InterpolationLayer.cpp: y = w*x1 + (1-w)*x2
# ---------------------------------------------------------------------------


@register_layer("interpolation")
def interpolation_apply(conf, params, inputs, ctx):
    w, x1, x2 = inputs  # w: [B,1]
    lam = w.data
    return x1.with_data(lam * x1.data + (1.0 - lam) * x2.data)


# ---------------------------------------------------------------------------
# sum_to_one_norm / row_l2_norm — NormLayer.cpp
# ---------------------------------------------------------------------------


@register_layer("sum_to_one_norm")
def sum_to_one_norm_apply(conf, params, inputs, ctx):
    x = inputs[0]
    s = jnp.sum(x.data, axis=-1, keepdims=True)
    return x.with_data(x.data / jnp.where(s == 0, 1.0, s))


@register_layer("row_l2_norm")
def row_l2_norm_apply(conf, params, inputs, ctx):
    x = inputs[0]
    n = jnp.linalg.norm(x.data, axis=-1, keepdims=True)
    return x.with_data(x.data / jnp.maximum(n, 1e-12))


# ---------------------------------------------------------------------------
# maxid — MaxIdLayer.cpp: argmax over features
# ---------------------------------------------------------------------------


@register_layer("maxid")
def maxid_apply(conf, params, inputs, ctx):
    x = inputs[0]
    return SeqTensor(
        jnp.argmax(x.data, axis=-1).astype(jnp.int32), x.lengths
    )


# ---------------------------------------------------------------------------
# multiplex — MultiplexLayer.cpp: per-row select among inputs by index input
# ---------------------------------------------------------------------------


@register_layer("multiplex")
def multiplex_apply(conf, params, inputs, ctx):
    sel = inputs[0].data.astype(jnp.int32).reshape(-1)  # [B]
    stacked = jnp.stack([t.data for t in inputs[1:]], axis=0)  # [K, B, D]
    return SeqTensor(stacked[sel, jnp.arange(sel.shape[0])], inputs[1].lengths)


# ---------------------------------------------------------------------------
# trans — TransLayer.cpp: matrix transpose of the feature block
# ---------------------------------------------------------------------------


@register_layer("trans")
def trans_apply(conf, params, inputs, ctx):
    x = inputs[0]
    h = conf.attr("height")
    if h is None:
        # whole-minibatch transpose (reference TransLayer.cpp: y = x^T over
        # the [batch, size] matrix; the batch axis becomes the feature axis)
        return SeqTensor(jnp.swapaxes(x.data.reshape(x.data.shape[0], -1), 0, 1))
    b = x.data.shape[0]
    m = x.data.reshape(b, h, -1)
    return SeqTensor(jnp.swapaxes(m, 1, 2).reshape(b, -1), x.lengths)


# ---------------------------------------------------------------------------
# repeat — FeatureMapExpandLayer-era repeat_layer: tile the feature vector
# ---------------------------------------------------------------------------


@register_layer("repeat")
def repeat_apply(conf, params, inputs, ctx):
    x = inputs[0]
    n = conf.attr("num_repeats")
    if conf.attr("as_row_vector", True):
        # [x1..xd, x1..xd, ...]
        out = jnp.concatenate([x.data] * n, axis=-1)
    else:
        # [x1,x1,..., xd,xd,...]
        out = jnp.repeat(x.data, n, axis=-1)
    return x.with_data(out)


# ---------------------------------------------------------------------------
# resize — ResizeLayer.cpp: reshape rows to a new width
# ---------------------------------------------------------------------------


@register_layer("resize")
def resize_apply(conf, params, inputs, ctx):
    x = inputs[0]
    return SeqTensor(x.data.reshape(-1, conf.size), x.lengths)


# ---------------------------------------------------------------------------
# clip — ClipLayer.cpp
# ---------------------------------------------------------------------------


@register_layer("clip")
def clip_apply(conf, params, inputs, ctx):
    x = inputs[0]
    return x.with_data(
        jnp.clip(x.data, conf.attr("min", -1.0), conf.attr("max", 1.0))
    )


# ---------------------------------------------------------------------------
# dotmul — DotMulOperator/DotMulProjection: elementwise product
# ---------------------------------------------------------------------------


def dotmul_init(conf, in_confs, rng):
    # dotmul projection owns a [1, D] scale vector.
    if conf.attr("projection", False):
        return {"w": init.normal(rng, (conf.size,), 1.0 / max(conf.size, 1))}
    return {}


@register_layer("dotmul", init=dotmul_init)
def dotmul_apply(conf, params, inputs, ctx):
    if "w" in params:
        x = inputs[0]
        return x.with_data(x.data * params["w"])
    a, b = inputs
    return a.with_data(a.data * b.data)


# ---------------------------------------------------------------------------
# out_prod — OuterProdLayer.cpp: per-row outer product flattened
# ---------------------------------------------------------------------------


@register_layer("out_prod")
def out_prod_apply(conf, params, inputs, ctx):
    a, b = inputs
    out = jnp.einsum("bi,bj->bij", a.data, b.data)
    return SeqTensor(out.reshape(out.shape[0], -1), a.lengths)


# ---------------------------------------------------------------------------
# cos — CosSimLayer.cpp: row-wise cosine similarity * scale
# ---------------------------------------------------------------------------


@register_layer("cos")
def cos_apply(conf, params, inputs, ctx):
    a, b = inputs
    scale = conf.attr("scale", 1.0)
    n = conf.attr("cos_n", 1)
    if n > 1:
        # reference cos_sim size=N: b holds N vectors of a's width; one
        # cosine per vector (CosSimLayer over the reshaped [B, N, M])
        bm = b.data.reshape(b.data.shape[0], n, -1)
        num = jnp.sum(a.data[:, None, :] * bm, axis=-1)
        den = jnp.linalg.norm(a.data, axis=-1, keepdims=True) * jnp.linalg.norm(
            bm, axis=-1
        )
        return SeqTensor(scale * num / jnp.maximum(den, 1e-12), a.lengths)
    num = jnp.sum(a.data * b.data, axis=-1, keepdims=True)
    den = jnp.linalg.norm(a.data, axis=-1, keepdims=True) * jnp.linalg.norm(
        b.data, axis=-1, keepdims=True
    )
    return SeqTensor(scale * num / jnp.maximum(den, 1e-12), a.lengths)


# ---------------------------------------------------------------------------
# tensor — TensorLayer.cpp: y_k = x1 W_k x2^T (bilinear)
# ---------------------------------------------------------------------------


def tensor_init(conf, in_confs, rng):
    d1, d2 = in_confs[0].size, in_confs[1].size
    p = {"w": init.normal(rng, (conf.size, d1, d2), init.default_std(d1))}
    if conf.bias:
        p["b"] = init.zeros((conf.size,))
    return p


@register_layer("tensor", init=tensor_init)
def tensor_apply(conf, params, inputs, ctx):
    a, b = inputs
    out = jnp.einsum("bi,kij,bj->bk", a.data, params["w"], b.data)
    if "b" in params:
        out = out + params["b"]
    return SeqTensor(out, a.lengths)
