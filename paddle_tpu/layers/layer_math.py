"""Layer arithmetic — the ``paddle.trainer_config_helpers.math`` surface
(reference python/paddle/trainer_config_helpers/math.py): elementwise math
functions over LayerOutputs plus operator overloading, so v1 configs can
write ``layer_math.exp(logvar) * 0.5`` or ``mu + sigma``.

Everything lowers to existing layers: unary functions are identity-addto
layers with the matching activation; scalar affine ops are slope_intercept;
layer+layer is addto; layer*layer is a dotmul mixed term — the same
lowering the reference's math.py performs onto mixed/slope_intercept."""

from __future__ import annotations

from paddle_tpu.core.topology import LayerOutput

__all__ = [
    "exp", "log", "sqrt", "square", "abs", "reciprocal", "sigmoid", "tanh",
    "relu", "add", "sub", "mul",
]


def _act(input: LayerOutput, act_name: str) -> LayerOutput:
    from paddle_tpu import activation as A
    from paddle_tpu.layers import addto

    cls = {
        "exponential": A.Exp, "log": A.Log, "sqrt": A.Sqrt,
        "square": A.Square, "abs": A.Abs, "reciprocal": A.Reciprocal,
        "sigmoid": A.Sigmoid, "tanh": A.Tanh, "relu": A.Relu,
    }[act_name]
    return addto([input], act=cls(), bias_attr=False)


def exp(input: LayerOutput) -> LayerOutput:
    return _act(input, "exponential")


def log(input: LayerOutput) -> LayerOutput:
    return _act(input, "log")


def sqrt(input: LayerOutput) -> LayerOutput:
    return _act(input, "sqrt")


def square(input: LayerOutput) -> LayerOutput:
    return _act(input, "square")


def abs(input: LayerOutput) -> LayerOutput:  # noqa: A001 - reference name
    return _act(input, "abs")


def reciprocal(input: LayerOutput) -> LayerOutput:
    return _act(input, "reciprocal")


def sigmoid(input: LayerOutput) -> LayerOutput:
    return _act(input, "sigmoid")


def tanh(input: LayerOutput) -> LayerOutput:
    return _act(input, "tanh")


def relu(input: LayerOutput) -> LayerOutput:
    return _act(input, "relu")


def add(a, b):
    from paddle_tpu.layers import addto, slope_intercept

    if isinstance(a, LayerOutput) and isinstance(b, LayerOutput):
        return addto([a, b], bias_attr=False)
    if isinstance(a, LayerOutput):
        return slope_intercept(a, slope=1.0, intercept=float(b))
    return slope_intercept(b, slope=1.0, intercept=float(a))


def sub(a, b):
    from paddle_tpu.layers import addto, slope_intercept

    if isinstance(a, LayerOutput) and isinstance(b, LayerOutput):
        return addto([a, slope_intercept(b, slope=-1.0)], bias_attr=False)
    if isinstance(a, LayerOutput):
        return slope_intercept(a, slope=1.0, intercept=-float(b))
    return slope_intercept(b, slope=-1.0, intercept=float(a))


def mul(a, b):
    from paddle_tpu.layers import dotmul_operator, slope_intercept

    if isinstance(a, LayerOutput) and isinstance(b, LayerOutput):
        return dotmul_operator(a, b)
    if isinstance(a, LayerOutput):
        return slope_intercept(a, slope=float(b))
    return slope_intercept(b, slope=float(a))


# -- operator overloading on LayerOutput (reference math.py registers the
#    same dunders) ----------------------------------------------------------
LayerOutput.__add__ = add
LayerOutput.__radd__ = lambda self, other: add(other, self)
LayerOutput.__sub__ = sub
LayerOutput.__rsub__ = lambda self, other: sub(other, self)
LayerOutput.__mul__ = mul
LayerOutput.__rmul__ = lambda self, other: mul(other, self)
