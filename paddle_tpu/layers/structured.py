"""Structured-prediction layers: linear-chain CRF (loss + Viterbi decoding)
and CTC loss.

Reference: paddle/gserver/layers/{CRFLayer,CRFDecodingLayer,LinearChainCRF,
CTCLayer,LinearChainCTC,WarpCTCLayer}.cpp.

TPU-native design: the reference runs per-sequence dynamic programming on the
CPU (LinearChainCRF.cpp walks each sequence; WarpCTC is a CUDA kernel).  Here
each DP is a single ``lax.scan`` over the padded time axis for the whole
batch at once — one XLA while-loop with [B, N] (or [B, S]) carries, masked
per-sample by length, so variable-length batches cost max-length steps with
full vectorization and autodiff provides the gradients (no hand-written
backward DP).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer

NEG = -1e30  # effective -inf that stays finite under arithmetic


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------
#
# Parameterization matches the reference (LinearChainCRF.h): a weight matrix
# of shape [N+2, N] — row 0 start scores `a`, row 1 end scores `b`, rows 2..
# the transition matrix W[from, to].


def crf_init(conf, in_confs, rng):
    n = conf.attrs["num_classes"]
    return {"w": init.normal(rng, (n + 2, n), 0.1)}


def _crf_unpack(w):
    return w[0], w[1], w[2:]  # a[N], b[N], trans[N, N]


def _crf_log_z(x, lengths, a, b, trans):
    """log partition per sequence.  x: [B, T, N] emissions."""
    b_, t_, n = x.shape
    alpha0 = a[None, :] + x[:, 0]  # [B, N]

    def step(alpha, inp):
        xt, valid = inp  # [B, N], [B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) + xt
        alpha = jnp.where(valid[:, None], nxt, alpha)
        return alpha, None

    ts = jnp.arange(1, t_)
    valid = ts[:, None] < lengths[None, :]  # [T-1, B]
    alpha, _ = lax.scan(step, alpha0, (jnp.moveaxis(x[:, 1:], 1, 0), valid))
    return jax.nn.logsumexp(alpha + b[None, :], axis=-1)  # [B]


def _crf_path_score(x, labels, lengths, a, b, trans):
    """score of the gold path per sequence.  labels: [B, T] int."""
    b_, t_, n = x.shape
    tpos = jnp.arange(t_)[None, :]  # [1, T]
    mask = (tpos < lengths[:, None]).astype(x.dtype)  # [B, T]
    emit = jnp.take_along_axis(x, labels[..., None], axis=-1)[..., 0]  # [B, T]
    score = jnp.sum(emit * mask, axis=1)
    score = score + a[labels[:, 0]]
    last = jnp.take_along_axis(labels, (lengths - 1)[:, None], axis=1)[:, 0]
    score = score + b[last]
    trans_scores = trans[labels[:, :-1], labels[:, 1:]]  # [B, T-1]
    score = score + jnp.sum(trans_scores * mask[:, 1:], axis=1)
    return score


@register_layer("crf", init=crf_init, auto_activation=False, full_precision=True)
def crf_apply(conf, params, inputs, ctx):
    """-log P(label | emissions) per sequence → [B, 1]."""
    x_t, y_t = inputs
    assert x_t.is_seq, "crf needs sequence emissions"
    a, b, trans = _crf_unpack(params["w"])
    x = x_t.data
    labels = y_t.data.astype(jnp.int32)
    if labels.ndim == 3:
        labels = labels[..., 0]
    lengths = x_t.lengths
    nll = _crf_log_z(x, lengths, a, b, trans) - _crf_path_score(
        x, labels, lengths, a, b, trans
    )
    return SeqTensor(nll[:, None])


@register_layer("crf_decoding", init=crf_init, auto_activation=False, full_precision=True)
def crf_decoding_apply(conf, params, inputs, ctx):
    """Viterbi decode → [B, T] best label ids (padded with 0); when a label
    input is present, returns [B, T] 0/1 mismatch indicators instead
    (reference CRFDecodingLayer.cpp)."""
    x_t = inputs[0]
    assert x_t.is_seq
    a, b, trans = _crf_unpack(params["w"])
    x = x_t.data
    lengths = x_t.lengths
    b_, t_, n = x.shape

    alpha0 = a[None, :] + x[:, 0]

    def step(alpha, inp):
        xt, valid = inp
        cand = alpha[:, :, None] + trans[None]  # [B, from, to]
        bp = jnp.argmax(cand, axis=1).astype(jnp.int32)  # [B, to]
        nxt = jnp.max(cand, axis=1) + xt
        alpha_new = jnp.where(valid[:, None], nxt, alpha)
        return alpha_new, jnp.where(valid[:, None], bp, -1)

    ts = jnp.arange(1, t_)
    valid = ts[:, None] < lengths[None, :]
    alpha, bps = lax.scan(step, alpha0, (jnp.moveaxis(x[:, 1:], 1, 0), valid))
    # bps: [T-1, B, N]; backpointer for step t lives at bps[t-1].
    y_last = jnp.argmax(alpha + b[None, :], axis=-1).astype(jnp.int32)  # [B]

    # Backtrack t = T-2 .. 0.  bps[t] maps (label at t+1) -> (label at t).
    # The carry holds the decoded label at position t+1; it is (re)seeded
    # with y_last exactly when t+1 == len-1 (each sample's last position).
    def back(carry, inp):
        bp_t, t = inp
        carry = jnp.where((t + 1) == (lengths - 1), y_last, carry)
        y_t = jnp.take_along_axis(bp_t, carry[:, None], axis=1)[:, 0]
        emit_valid = t <= lengths - 2
        y_t = jnp.where(emit_valid, y_t, 0).astype(jnp.int32)
        carry = jnp.where(emit_valid, y_t, carry)
        return carry, y_t

    rev = lambda z: jnp.flip(z, axis=0)
    if t_ > 1:
        _, ys = lax.scan(back, y_last, (rev(bps), rev(jnp.arange(t_ - 1))))
        ys = jnp.moveaxis(rev(ys), 0, 1)  # [B, T-1]: labels at positions 0..T-2
        path = jnp.concatenate(
            [ys, jnp.zeros((b_, 1), jnp.int32)], axis=1
        )
    else:
        path = jnp.zeros((b_, 1), jnp.int32)
    path = path.at[jnp.arange(b_), lengths - 1].set(y_last)
    tpos = jnp.arange(t_)[None, :]
    path = jnp.where(tpos < lengths[:, None], path, 0).astype(jnp.int32)
    if len(inputs) > 1:
        gold = inputs[1].data.astype(jnp.int32)
        if gold.ndim == 3:
            gold = gold[..., 0]
        err = (path != gold) & (tpos < lengths[:, None])
        return SeqTensor(err.astype(jnp.float32), lengths)
    return SeqTensor(path, lengths)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------


@register_layer("ctc", auto_activation=False, full_precision=True)
def ctc_apply(conf, params, inputs, ctx):
    """CTC negative log likelihood per sequence → [B, 1].

    inputs[0]: [B, T, C] pre-softmax logits (the reference applies softmax
    inside, CTCLayer.cpp forwards through softmax); inputs[1]: label id
    sequence with its own lengths.  Blank index is configurable
    (``blank``); the `warp_ctc` registration fixes blank=0.
    """
    logits_t, labels_t = inputs
    assert logits_t.is_seq and labels_t.is_seq
    blank = conf.attrs.get("blank", conf.size - 1)
    norm_by_times = conf.attrs.get("norm_by_times", False)

    logp = jax.nn.log_softmax(logits_t.data, axis=-1)  # [B, T, C]
    in_len = logits_t.lengths
    labels = labels_t.data.astype(jnp.int32)
    if labels.ndim == 3:
        labels = labels[..., 0]
    lab_len = labels_t.lengths

    b_, t_, c_ = logp.shape
    l_ = labels.shape[1]
    s_ = 2 * l_ + 1

    # Extended label sequence z': blank, z1, blank, z2, ..., blank
    ext = jnp.full((b_, s_), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    spos = jnp.arange(s_)[None, :]
    s_eff = 2 * lab_len + 1  # [B]
    ext_valid = spos < s_eff[:, None]

    # can_skip[s]: alpha may come from s-2 (z'_s not blank and != z'_{s-2})
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s_]
    can_skip = (ext != blank) & (ext != ext_prev2)

    alpha0 = jnp.full((b_, s_), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    first_lab = jnp.where(lab_len > 0, labels[:, 0], blank)
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(lab_len > 0,
                  jnp.take_along_axis(logp[:, 0], first_lab[:, None], -1)[:, 0],
                  NEG)
    )

    def shift(a, k):
        return jnp.pad(a, ((0, 0), (k, 0)), constant_values=NEG)[:, :s_]

    def step(alpha, inp):
        em, valid = inp  # [B, S], [B]
        stay = alpha
        s1 = shift(alpha, 1)
        s2 = jnp.where(can_skip, shift(alpha, 2), NEG)
        nxt = jnp.logaddexp(jnp.logaddexp(stay, s1), s2) + em
        nxt = jnp.where(ext_valid, nxt, NEG)
        return jnp.where(valid[:, None], nxt, alpha), None

    ts = jnp.arange(1, t_)
    valid = ts[:, None] < in_len[None, :]
    # [B, T-1, S] emission log-probs of the extended labels, time-major for scan
    ems = jnp.take_along_axis(
        logp[:, 1:], jnp.broadcast_to(ext[:, None, :], (b_, t_ - 1, s_)), axis=-1
    )
    alpha, _ = lax.scan(step, alpha0, (jnp.moveaxis(ems, 1, 0), valid))

    last = jnp.take_along_axis(alpha, (s_eff - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(s_eff - 2, 0)[:, None], axis=1
    )[:, 0]
    # empty label sequence: only the all-blank path exists (s_eff == 1)
    last2 = jnp.where(s_eff >= 2, last2, NEG)
    ll = jnp.logaddexp(last, last2)
    nll = -ll
    if norm_by_times:
        nll = nll / in_len.astype(nll.dtype)
    return SeqTensor(nll[:, None])
