"""Cost layers — reference: paddle/gserver/layers/CostLayer.cpp (cross-entropy
family, SumOfSquaresCostLayer, HuberCost, RankingCost, SmoothL1Cost, SumCost).

Every cost layer emits a per-sample cost column [B, 1]; the train step takes
the batch mean (the reference sums per-sample costs then divides by batch,
trainer/TrainerInternal.cpp:131 Argument::sum).  Sequence costs mask padding
and sum over valid timesteps.  jax.grad over the mean replaces each cost
layer's hand-written backward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer

_EPS = 1e-10
# two-sided probability clip for the BCE family: must be representable in
# float32 — 1.0 - 1e-10 rounds to exactly 1.0 (f32 has ~7 digits), which
# made log(1-p) = -inf for saturated probabilities
_BCE_EPS = 1e-6


def _fused_ce_from_logits(x: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """-log softmax(x)[ids] WITHOUT materializing the [N, V] log-prob
    matrix: cost = logsumexp(x) - x[ids].

    jax.nn.log_softmax writes a full f32 [N, V] block (524 MB for 4096x32k)
    just so take_along_axis can read ONE element per row — at big vocab the
    HBM traffic of that round trip dominates the whole cost layer (~5 ms of
    a 24 ms transformer-base step).  The two-reduction form reads the bf16
    logits once, accumulates in f32 (promoted per-element inside the fused
    reduction — XLA never materializes the cast), and writes [N] scalars.
    The backward autodiffs to softmax(x)·g − one_hot·g, recomputed inside
    one bwd fusion at the logits dtype."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(acc)  # fuses into each reduction below; never stored whole
    m = jax.lax.stop_gradient(jnp.max(xf, axis=-1, keepdims=True))
    lse = m[..., 0] + jnp.log(jnp.sum(jnp.exp(xf - m), axis=-1))
    picked = jnp.take_along_axis(x, ids[..., None], axis=-1)[..., 0]
    return lse - picked.astype(acc)


def _per_sample(cost: jnp.ndarray, tensor: SeqTensor) -> SeqTensor:
    """Reduce a per-timestep cost [B, T] to per-*token-summed* [B, 1] with
    masking, or pass through [B] -> [B, 1]."""
    if tensor.is_seq and cost.ndim == 2:
        cost = jnp.sum(cost * tensor.mask(cost.dtype), axis=1)
    return SeqTensor(cost[:, None])


def _label_ids(label: SeqTensor) -> jnp.ndarray:
    ids = label.data.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return ids


# ---------------------------------------------------------------------------


@register_layer("cross_entropy", auto_activation=False, full_precision=True)
def cross_entropy_apply(conf, params, inputs, ctx):
    """-log p[label]; input is a probability distribution (softmax output),
    reference MultiClassCrossEntropy (CostLayer.cpp).  When the producing
    layer's activation was softmax, the compiler exposes its pre-activation
    as `<name>@logits` and we fuse into log-softmax CE instead (stable, one
    less kernel)."""
    prob, label = inputs[0], inputs[1]
    ids = _label_ids(label)
    logits = ctx.outputs.get(conf.inputs[0] + "@logits")
    if logits is not None:
        return _per_sample(_fused_ce_from_logits(logits.data, ids), prob)
    p = jnp.take_along_axis(prob.data, ids[..., None], axis=-1)[..., 0]
    cost = -jnp.log(jnp.maximum(p, _EPS))
    return _per_sample(cost, prob)


@register_layer("softmax_with_cost", auto_activation=False, full_precision=True)
def softmax_with_cost_apply(conf, params, inputs, ctx):
    """Fused log-softmax cross-entropy from *logits* — numerically stable
    TPU-native fast path the DSL uses for classification_cost when the input
    activation is softmax (fuses the reference's softmax + cross_entropy
    pair into one lax reduction)."""
    logits, label = inputs[0], inputs[1]
    ids = _label_ids(label)
    return _per_sample(_fused_ce_from_logits(logits.data, ids), logits)


@register_layer("soft_binary_class_cross_entropy", auto_activation=False, full_precision=True)
def soft_bce_apply(conf, params, inputs, ctx):
    """Per-dim BCE with soft targets (SoftBinaryClassCrossEntropy)."""
    prob, label = inputs[0], inputs[1]
    p = jnp.clip(prob.data, _BCE_EPS, 1.0 - _BCE_EPS)
    t = label.data
    cost = -jnp.sum(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p), axis=-1)
    return _per_sample(cost, prob)


@register_layer("multi_binary_label_cross_entropy", auto_activation=False, full_precision=True)
def multi_binary_label_ce_apply(conf, params, inputs, ctx):
    """BCE where the label is a multi-hot vector (MultiBinaryLabelCrossEntropy).
    The label slot arrives densified to multi-hot [B, D] by the feeder; an
    integer ID label one-hots (the reference's sparse id-matrix form)."""
    prob, label = inputs[0], inputs[1]
    p = jnp.clip(prob.data, _BCE_EPS, 1.0 - _BCE_EPS)
    t = _label_as_dense(label, prob.data.shape[-1])
    cost = -jnp.sum(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p), axis=-1)
    return _per_sample(cost, prob)


def _label_as_dense(label: SeqTensor, width: int) -> jnp.ndarray:
    """A cost's label operand as a dense [.., width] block: already-dense
    labels pass through; integer ID labels one-hot against the prediction
    width — the reference's sparse-label support in these costs
    (SumOfSquaresCostLayer / MultiBinaryLabelCrossEntropy accept a sparse
    id matrix, CostLayer.cpp)."""
    t = label.data
    if jnp.issubdtype(t.dtype, jnp.integer):
        from paddle_tpu.layers.base import is_sparse_ids

        if is_sparse_ids(label, width):
            # padded multi-id rows (the feeder's big-vocab sparse_ids form,
            # [.., nnz] with sentinel == width): multi-hot by summing the
            # one-hots — sentinels one-hot to all-zero rows, duplicates
            # clamp to 1 (NO_VALUE sparse labels are binary).  Dispatch is
            # on the EXACT sparse_ids flag (base.is_sparse_ids contract) —
            # a plain [B, T] id-sequence label must keep per-frame one-hots
            return jnp.minimum(
                jnp.sum(
                    jax.nn.one_hot(t, width, dtype=jnp.float32), axis=-2
                ),
                1.0,
            )
        return jax.nn.one_hot(_label_ids(label), width, dtype=jnp.float32)
    return t


@register_layer("square_error", auto_activation=False, full_precision=True)
def square_error_apply(conf, params, inputs, ctx):
    """0.5 * sum((x - y)^2) per sample (SumOfSquaresCostLayer; an integer
    label acts as the one-hot row, the reference's sparse-label form)."""
    x, y = inputs[0], inputs[1]
    d = x.data - _label_as_dense(y, x.data.shape[-1])
    cost = 0.5 * jnp.sum(jnp.square(d), axis=-1)
    return _per_sample(cost, x)


@register_layer("smooth_l1", auto_activation=False, full_precision=True)
def smooth_l1_apply(conf, params, inputs, ctx):
    """SmoothL1Cost: 0.5 d^2 if |d|<1 else |d|-0.5, summed per sample."""
    x, y = inputs[0], inputs[1]
    d = x.data - y.data
    a = jnp.abs(d)
    cost = jnp.sum(jnp.where(a < 1.0, 0.5 * d * d, a - 0.5), axis=-1)
    return _per_sample(cost, x)


@register_layer("huber_regression", auto_activation=False, full_precision=True)
def huber_regression_apply(conf, params, inputs, ctx):
    delta = conf.attr("delta", 1.0)
    x, y = inputs[0], inputs[1]
    a = jnp.abs(x.data - y.data)
    cost = jnp.sum(
        jnp.where(a <= delta, 0.5 * a * a, delta * (a - 0.5 * delta)), axis=-1
    )
    return _per_sample(cost, x)


@register_layer("huber_classification", auto_activation=False, full_precision=True)
def huber_classification_apply(conf, params, inputs, ctx):
    """HuberTwoClassification: labels {0,1} -> y in {-1,+1},
    cost = 0 if y*f>1, (1-y*f)^2 if -1<=y*f<=1, -4*y*f if y*f<-1."""
    x, label = inputs[0], inputs[1]
    f = x.data[..., 0] if x.data.ndim >= 2 else x.data
    y = 2.0 * _label_ids(label).astype(f.dtype) - 1.0
    z = y * f
    cost = jnp.where(z > 1.0, 0.0, jnp.where(z < -1.0, -4.0 * z, jnp.square(1.0 - z)))
    return _per_sample(cost, x)


@register_layer("rank_cost", auto_activation=False, full_precision=True)
def rank_cost_apply(conf, params, inputs, ctx):
    """RankingCost: pairwise logistic loss on score difference
    (CostLayer.cpp RankingCost::forwardImp)."""
    left, right, label = inputs[0], inputs[1], inputs[2]
    o = left.data[..., 0] - right.data[..., 0]
    t = label.data
    t = t[..., 0] if t.ndim >= 2 else t
    t = t.astype(o.dtype)
    cost = jax.nn.softplus(o) - t * o
    return _per_sample(cost, left)


@register_layer("sum_cost", auto_activation=False, full_precision=True)
def sum_cost_apply(conf, params, inputs, ctx):
    """SumCostLayer: cost = sum of input row."""
    x = inputs[0]
    cost = jnp.sum(x.data, axis=-1)
    if x.is_seq:
        cost = jnp.sum(cost * x.mask(cost.dtype), axis=-1) if cost.ndim == 2 else cost
    return _per_sample(cost, x)


@register_layer("cross_entropy_with_selfnorm", auto_activation=False, full_precision=True)
def ce_selfnorm_apply(conf, params, inputs, ctx):
    """MultiClassCrossEntropyWithSelfNorm: CE + alpha * log(Z)^2 where Z is
    the row sum of the (softmax) output."""
    prob, label = inputs[0], inputs[1]
    alpha = conf.attr("softmax_selfnorm_alpha", 0.1)
    ids = _label_ids(label)
    z = jnp.sum(prob.data, axis=-1)
    p = jnp.take_along_axis(prob.data, ids[..., None], axis=-1)[..., 0] / jnp.maximum(
        z, _EPS
    )
    cost = -jnp.log(jnp.maximum(p, _EPS)) + alpha * jnp.square(jnp.log(jnp.maximum(z, _EPS)))
    return _per_sample(cost, prob)


@register_layer("multi_nn_cost", auto_activation=False, full_precision=True)
def multi_nn_cost_apply(conf, params, inputs, ctx):
    """Joint training objective of a model_type('multi_nn') ensemble: the
    sum of every sub-network's mean cost — the reference trainer sums all
    output Arguments of MultiNetwork::forward (Argument::sum over outArgs,
    TrainerInternal.cpp), which concatenates the sub-networks' outputs
    (MultiNetwork.cpp:67-95).  Gradients flow into every sub-network from
    this single scalar."""
    total = 0.0
    for t in inputs:
        total = total + jnp.mean(t.data)
    return SeqTensor(jnp.broadcast_to(total, (1,)))
