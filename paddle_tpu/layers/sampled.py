"""Large-vocabulary output layers: NCE, hierarchical sigmoid, selective_fc,
and the LambdaRank cost.

Reference: paddle/gserver/layers/{NCELayer,HierarchicalSigmoidLayer,
SelectiveFullyConnectedLayer,LambdaCost}.cpp.

TPU-native design notes:
  * NCE noise sampling happens inside the jitted step from the layer RNG
    (jax.random.categorical over a static noise distribution) — a fixed
    [B, K] sample buffer instead of the reference's per-row CPU sampler,
    so shapes stay static.
  * hsigmoid walks the same implicit complete binary tree as the reference
    (SimpleCode: node ids from the bits of ``label + num_classes``) but
    evaluates the whole padded path vector at once: gather path-node rows,
    one batched matvec, mask, sum.
  * selective_fc computes the full [B, C] matmul and masks — on the MXU a
    dense matmul beats per-row gathered GEMVs for the widths this layer is
    used at, and XLA fuses the mask for free.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as init
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.layers.base import register_layer


# ---------------------------------------------------------------------------
# nce
# ---------------------------------------------------------------------------


def nce_init(conf, in_confs, rng):
    c = conf.attrs["num_classes"]
    d = sum(ic.size for ic in in_confs[: conf.attrs["num_feat_inputs"]])
    p = {"w": init.normal(rng, (c, d), init.default_std(d))}
    if conf.bias:
        p["b"] = init.zeros((c,))
    return p


@register_layer("nce", init=nce_init, auto_activation=False, full_precision=True)
def nce_apply(conf, params, inputs, ctx):
    """Noise-contrastive estimation cost → [B, 1].

    inputs: feature layer(s), then the label id slot.  Noise ids are drawn
    uniformly (or from attrs["noise_dist"]) per step from the layer RNG.
    """
    nfeat = conf.attrs["num_feat_inputs"]
    k = conf.attrs["num_neg_samples"]
    c = conf.attrs["num_classes"]

    # sequence inputs run FRAME-WISE (each timestep one NCE sample) — the
    # reference NCELayer checks label rows == input frame rows, so a seq
    # feature pairs with a seq label position by position
    seq_in = inputs[0].is_seq and inputs[0].data.ndim == 3
    if seq_in:
        x = jnp.concatenate(
            [t.data.reshape(-1, t.data.shape[-1]) for t in inputs[:nfeat]],
            axis=-1,
        )  # [B*T, D]
    else:
        x = jnp.concatenate(
            [t.data.reshape(t.data.shape[0], -1) for t in inputs[:nfeat]],
            axis=-1,
        )
    label = inputs[nfeat].data.astype(jnp.int32).reshape(-1)  # [B] / [B*T]
    b_ = x.shape[0]

    dist = conf.attrs.get("noise_dist")
    if dist is None:
        logq = jnp.full((c,), -math.log(c))
    else:
        logq = jnp.log(jnp.asarray(dist) + 1e-12)

    rng = ctx.layer_rng(conf.name)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    noise = jax.random.categorical(rng, logq[None, :], shape=(b_, k))  # [B,K]

    ids = jnp.concatenate([label[:, None], noise], axis=1)  # [B, 1+K]
    w = params["w"][ids]  # [B, 1+K, D]
    logits = jnp.einsum("bd,bkd->bk", x, w)
    if "b" in params:
        logits = logits + params["b"][ids]
    # subtract log(k * q(class)) — the NCE correction
    logits = logits - (math.log(k) + logq[ids])
    labels01 = jnp.concatenate(
        [jnp.ones((b_, 1)), jnp.zeros((b_, k))], axis=1
    )
    # binary logistic loss per candidate, summed
    loss = jnp.sum(
        jnp.maximum(logits, 0) - logits * labels01
        + jnp.log1p(jnp.exp(-jnp.abs(logits))),
        axis=1,
    )
    if seq_in:
        t0 = inputs[0]
        frames = loss.reshape(t0.data.shape[0], t0.data.shape[1])  # [B, T]
        m = t0.mask(frames.dtype)
        lab_t = inputs[nfeat]
        if lab_t.is_seq:
            # the reference CHECKs label rows == feature rows; lengths are
            # traced here, so the defensible equivalent is counting only
            # frames BOTH sides declare valid (a frame past the label's
            # end must not train against padding ids)
            m = m * lab_t.mask(frames.dtype)
        return SeqTensor(jnp.sum(frames * m, axis=1)[:, None])
    return SeqTensor(loss[:, None])


# ---------------------------------------------------------------------------
# hsigmoid
# ---------------------------------------------------------------------------


def hsigmoid_init(conf, in_confs, rng):
    c = conf.attrs["num_classes"]
    d = sum(ic.size for ic in in_confs[:-1])
    p = {"w": init.normal(rng, (c - 1, d), init.default_std(d))}
    if conf.bias:
        p["b"] = init.zeros((c - 1,))
    return p


@register_layer("hsigmoid", init=hsigmoid_init, auto_activation=False, full_precision=True)
def hsigmoid_apply(conf, params, inputs, ctx):
    """Hierarchical sigmoid cost → [B, 1] over an implicit complete binary
    tree (reference SimpleCode in paddle/math/MathFunctions-era code paths:
    node j of class c comes from the bits of c + num_classes)."""
    c = conf.attrs["num_classes"]
    maxlen = max(int(math.ceil(math.log2(c))), 1)

    x = jnp.concatenate(
        [t.data.reshape(t.data.shape[0], -1) for t in inputs[:-1]], axis=-1
    )
    label = inputs[-1].data.astype(jnp.int32).reshape(-1)  # [B]

    code = label + c  # [B]; binary rep: 1 b_1 b_2 ... b_L
    # number of significant bits minus 1 = path length
    nbits = jnp.floor(jnp.log2(code.astype(jnp.float32) + 0.5)).astype(jnp.int32) + 1
    plen = nbits - 1  # [B]

    j = jnp.arange(maxlen)[None, :]  # [1, L]
    shift_idx = plen[:, None] - j  # bits from MSB side
    node = (code[:, None] >> shift_idx) - 1  # internal node id at step j
    bit = (code[:, None] >> (shift_idx - 1)) & 1  # branch taken at step j
    valid = j < plen[:, None]
    node = jnp.clip(node, 0, c - 2)

    w = params["w"][node]  # [B, L, D]
    score = jnp.einsum("bd,bld->bl", x, w)
    if "b" in params:
        score = score + params["b"][node]
    # P(branch) = sigmoid(score) if bit==0 else sigmoid(-score)  (reference
    # convention: sumByBitCode uses (1 - code_bit) sign)
    z = jnp.where(bit == 0, score, -score)
    nll_terms = jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0)
    loss = jnp.sum(jnp.where(valid, nll_terms, 0.0), axis=1)
    return SeqTensor(loss[:, None])


# ---------------------------------------------------------------------------
# selective_fc
# ---------------------------------------------------------------------------


def selective_fc_init(conf, in_confs, rng):
    d = sum(ic.size for ic in in_confs[:-1])
    p = {"w": init.normal(rng, (d, conf.size), init.default_std(d))}
    if conf.bias:
        p["b"] = init.zeros((conf.size,))
    return p


@register_layer("selective_fc", init=selective_fc_init)
def selective_fc_apply(conf, params, inputs, ctx):
    """fc whose output is masked to the selected columns (last input is the
    [B, C] 0/1 selection; without it behaves as plain fc — reference
    SelectiveFullyConnectedLayer.cpp full_mode)."""
    has_sel = conf.attrs.get("has_selection", True)
    feats = inputs[:-1] if has_sel else inputs
    x = jnp.concatenate(
        [t.data.reshape(t.data.shape[0], -1) for t in feats], axis=-1
    )
    out = jnp.matmul(x, params["w"])
    if "b" in params:
        out = out + params["b"]
    if has_sel:
        sel = inputs[-1].data.reshape(out.shape[0], -1)
        out = out * (sel > 0).astype(out.dtype)
    return SeqTensor(out, feats[0].lengths)


# ---------------------------------------------------------------------------
# lambda_cost — LambdaRank (LambdaCost.cpp)
# ---------------------------------------------------------------------------


@register_layer("lambda_cost", auto_activation=False, full_precision=True)
def lambda_cost_apply(conf, params, inputs, ctx):
    """Listwise LambdaRank cost per query sequence → [B, 1].

    inputs[0]: relevance scores from the model, sequence [B, T, 1];
    inputs[1]: gold relevance labels, sequence [B, T, 1].
    cost = sum over doc pairs (i better than j) of
           |ΔNDCG(i,j)| * log(1 + exp(-(s_i - s_j))), NDCG truncated at
           attrs["ndcg_num"].
    """
    score_t, label_t = inputs
    assert score_t.is_seq
    s = score_t.data[..., 0] if score_t.data.ndim == 3 else score_t.data
    y = label_t.data[..., 0] if label_t.data.ndim == 3 else label_t.data
    lengths = score_t.lengths
    b_, t_ = s.shape
    ndcg_num = conf.attrs.get("ndcg_num", 5)

    pos = jnp.arange(t_)
    valid = pos[None, :] < lengths[:, None]  # [B, T]

    # ideal DCG: labels sorted descending, gains 2^y - 1, discount 1/log2(r+2)
    y_masked = jnp.where(valid, y, -jnp.inf)
    y_sorted = -jnp.sort(-y_masked, axis=1)
    gains_sorted = jnp.where(
        jnp.isfinite(y_sorted), jnp.power(2.0, y_sorted) - 1.0, 0.0
    )
    disc = 1.0 / jnp.log2(pos.astype(jnp.float32) + 2.0)
    trunc = pos < ndcg_num
    idcg = jnp.sum(gains_sorted * disc * trunc, axis=1)  # [B]
    idcg = jnp.where(idcg > 0, idcg, 1.0)

    # current ranking of each doc by score (dense rank via pairwise count)
    gt = (s[:, None, :] > s[:, :, None]) & valid[:, None, :]
    rank = jnp.sum(gt, axis=2)  # [B, T] 0-based rank of each doc
    doc_disc = jnp.where(rank < ndcg_num,
                         1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0), 0.0)
    gain = jnp.power(2.0, jnp.where(valid, y, 0.0)) - 1.0

    # |ΔNDCG| for swapping i and j
    dg = (gain[:, :, None] - gain[:, None, :]) * (
        doc_disc[:, :, None] - doc_disc[:, None, :]
    )
    delta = jnp.abs(dg) / idcg[:, None, None]  # [B, T, T]

    sdiff = s[:, :, None] - s[:, None, :]
    pair_loss = jnp.log1p(jnp.exp(-jnp.abs(sdiff))) + jnp.maximum(-sdiff, 0)
    better = (y[:, :, None] > y[:, None, :]) & valid[:, :, None] & valid[:, None, :]
    cost = jnp.sum(jnp.where(better, delta * pair_loss, 0.0), axis=(1, 2))
    return SeqTensor(cost[:, None])
